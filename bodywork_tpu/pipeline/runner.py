"""Local in-process pipeline runner (reference C1/C10 behavior).

Executes a :class:`PipelineSpec`'s DAG for one simulated day per run — the
in-process equivalent of Bodywork materialising the DAG as k8s Jobs and
Deployments. Orchestrator guarantees preserved from the reference:

- batch stages get ``retries`` attempts (``bodywork.yaml:21``) and a
  completion deadline (``max_completion_time_seconds`` — ``bodywork.yaml:20``);
- service stages get a startup deadline and a health check before the DAG
  proceeds (``bodywork.yaml:39`` + k8s probes);
- a failed stage (exit-code contract, ``stage_1:170-178``) aborts the day
  with a :class:`StageFailure` naming the stage.

``run_simulation`` loops the daily DAG over N simulated days — the
reference's "re-run the deployment every day" (README.md:5) without needing
a day to take a day.
"""
from __future__ import annotations

import dataclasses
import importlib
import threading
import time
from datetime import date, timedelta

from bodywork_tpu.obs.spans import Span, SpanRecorder
from bodywork_tpu.pipeline.spec import PipelineSpec, StageSpec
from bodywork_tpu.pipeline.stages import StageContext
from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import DATASETS_PREFIX
from bodywork_tpu.utils.errors import StageError
from bodywork_tpu.utils.logging import configure_logger, get_logger

log = get_logger("pipeline.runner")


class StageFailure(StageError):
    """A stage exhausted its retries."""


def _hit_kill_point(kind: str) -> None:
    """Chaos process-kill hook (``chaos.kill``), resolved through
    ``sys.modules`` so the runner never widens any stage's import
    closure: the module is only ever present when something (the crash
    harness, a test) armed a kill switch."""
    import sys

    mod = sys.modules.get("bodywork_tpu.chaos.kill")
    if mod is not None:
        mod.hit_kill_point(kind)


def _is_simulated_crash(exc: BaseException) -> bool:
    """True for ``chaos.kill.SimulatedCrash`` — the in-process stand-in
    for process death, which must propagate RAW: no stage retry, no
    StageFailure wrapping, no journal completion."""
    import sys

    mod = sys.modules.get("bodywork_tpu.chaos.kill")
    return mod is not None and isinstance(exc, mod.SimulatedCrash)


def _device_ctx(device):
    """jax.default_device(device), or a no-op when device is None."""
    if device is None:
        import contextlib

        return contextlib.nullcontext()
    import jax

    return jax.default_device(device)


@dataclasses.dataclass
class DayResult:
    day: date
    wall_clock_s: float
    stage_seconds: dict[str, float]
    stage_results: dict[str, object]
    #: spans recorded during this day's run_day window (stage spans plus
    #: any overlap/prefetch work that completed inside it) — the input to
    #: obs.spans.day_report / chrome_trace
    spans: list[Span] = dataclasses.field(default_factory=list)
    #: stages skipped because the run journal recorded them complete and
    #: every recorded artefact digest verified against the store
    skipped_stages: tuple[str, ...] = ()
    #: True when the journal already marked the WHOLE day complete and
    #: verification confirmed it — nothing executed, no service started
    #: (``cli run-day`` maps this to its resumed-noop exit code)
    noop: bool = False


def resolve_executable(path: str):
    """``"pkg.mod:fn"`` -> the callable."""
    module_name, _, fn_name = path.partition(":")
    if not fn_name:
        raise ValueError(f"executable must be 'module:function', got {path!r}")
    module = importlib.import_module(module_name)
    return getattr(module, fn_name)


class LocalRunner:
    def __init__(self, spec: PipelineSpec, store: ArtefactStore,
                 drift: "DriftConfig | None" = None, device=None):  # noqa: F821
        self.spec = spec
        self.store = store
        if drift is None:
            from bodywork_tpu.data.drift_config import DriftConfig

            drift = DriftConfig()
        self.drift = drift
        #: pin ALL this runner's computations — including its own worker
        #: threads — to one jax device (device isolation for concurrent
        #: pipelines sharing a pool; jax.default_device alone is
        #: thread-local and would miss the spawned threads)
        self.device = device
        #: (date, box) handoff from a lookahead train to the next run_day
        self._pending_train: tuple | None = None
        #: background history-snapshot compactor (data.snapshot): at most
        #: one refresh in flight; day N+1's cold readers get day N's
        #: consolidation without the day loop ever paying the write
        self._compact_thread: threading.Thread | None = None
        self._compact_lock = threading.Lock()
        #: dataset prefetch state: date -> {"ready": Event, "X", "y"},
        #: filled by a single background worker (see _enqueue_generate)
        self._dataset_boxes: dict[date, dict] = {}
        self._gen_queue: list[tuple[date, dict]] = []
        self._gen_worker: threading.Thread | None = None
        self._gen_lock = threading.Lock()
        #: one span timeline for this runner's lifetime: stages AND the
        #: background overlaps (prefetch, lookahead train, prewarm) land
        #: on it, so a trace shows the overlap actually overlapping
        self.recorder = SpanRecorder(label=spec.name)
        configure_logger(spec.log_level)

    # -- single stages -----------------------------------------------------
    def _run_batch_stage(self, stage: StageSpec, ctx: StageContext):
        import dataclasses as _dc

        from bodywork_tpu.store.epoch import EpochGuardedStore

        from bodywork_tpu.utils.retry import classify_error

        fn = resolve_executable(stage.executable)
        last_exc: BaseException | None = None
        last_kind = "unknown"
        for attempt in range(1 + stage.retries):
            if attempt:
                log.warning(
                    f"retrying {stage.name} (attempt {attempt + 1}; "
                    f"last failure classified {last_kind})"
                )
            # A daemon thread (not an executor) so a stage hung past its
            # deadline is truly abandoned — like a k8s Job past
            # activeDeadlineSeconds — and cannot block interpreter exit via
            # concurrent.futures' atexit join.
            box: dict[str, object] = {}
            # each ATTEMPT writes through its own store epoch: when the
            # runner abandons a timed-out worker below, revoking the
            # epoch guarantees the zombie thread's late writes never land
            # in the shared store (k8s kills the pod; in-process this is
            # the equivalent fence)
            epoch = EpochGuardedStore(ctx.store, label=stage.name)
            attempt_ctx = _dc.replace(ctx, store=epoch)

            def _target(attempt_ctx=attempt_ctx):
                try:
                    with _device_ctx(self.device):
                        box["result"] = fn(attempt_ctx, **stage.args)
                except BaseException as exc:  # noqa: BLE001 — reported below
                    box["exc"] = exc

            worker = threading.Thread(
                target=_target, name=f"stage-{stage.name}", daemon=True
            )
            worker.start()
            worker.join(timeout=stage.max_completion_time_s)
            if worker.is_alive():
                # A timed-out worker cannot be killed and may still be
                # writing to the shared store; retrying alongside it would
                # run two attempts concurrently. Revoke its write epoch
                # and fail the stage immediately (the k8s materialisation
                # kills the whole pod instead).
                epoch.revoke()
                last_exc = TimeoutError(
                    f"exceeded max_completion_time_seconds="
                    f"{stage.max_completion_time_s}"
                )
                log.error(f"{stage.name}: {last_exc}")
                break
            if "exc" in box:
                last_exc = box["exc"]  # type: ignore[assignment]
                if _is_simulated_crash(last_exc):
                    # in-process process-death stand-in: propagate raw —
                    # retrying it would absorb the very failure mode the
                    # crash-resume harness exists to prove survivable
                    raise last_exc
                # fail fast on permanent errors (utils.retry taxonomy):
                # a ValueError/TypeError/KeyError — or a StageError not
                # caused by anything transient — can never succeed on
                # retry, so burning the remaining attempts against the
                # completion deadline only delays the day's failure
                last_kind = classify_error(last_exc)
                log.error(
                    f"{stage.name} failed ({last_kind}): {last_exc!r}"
                )
                if last_kind == "permanent":
                    log.error(
                        f"{stage.name}: permanent error — aborting "
                        f"without the remaining "
                        f"{stage.retries - attempt} retr"
                        f"{'y' if stage.retries - attempt == 1 else 'ies'}"
                    )
                    break
            else:
                return box.get("result")
        raise StageFailure(stage.name, repr(last_exc))

    def _run_service_stage(self, stage: StageSpec, ctx: StageContext):
        """Start + health-gate a service stage, honouring ``retries`` and the
        stage-failure contract (every failure surfaces as StageFailure)."""
        last_exc: Exception | None = None
        for attempt in range(1 + stage.retries):
            if attempt:
                log.warning(f"retrying {stage.name} (attempt {attempt + 1})")
            try:
                return self._start_and_health_gate(stage, ctx)
            except Exception as exc:
                last_exc = exc
                log.error(f"{stage.name} failed to start: {exc!r}")
        if isinstance(last_exc, StageFailure):
            raise last_exc
        raise StageFailure(stage.name, repr(last_exc))

    def _start_and_health_gate(self, stage: StageSpec, ctx: StageContext):
        fn = resolve_executable(stage.executable)
        deadline = time.monotonic() + stage.max_startup_time_s
        args = dict(stage.args)
        if stage.replicas > 1:
            # honour the spec's replica count locally (reference
            # bodywork.yaml:40), not just in emitted Deployment YAML —
            # but only for executables that can take it (a custom service
            # callable without the parameter must keep working)
            import inspect

            params = inspect.signature(fn).parameters
            if "replicas" in params or any(
                p.kind is inspect.Parameter.VAR_KEYWORD
                for p in params.values()
            ):
                args.setdefault("replicas", stage.replicas)
        with _device_ctx(self.device):
            handle = fn(ctx, **args)
        # health-check before the DAG proceeds (k8s readiness probe analogue)
        import requests

        health_url = handle.url.replace("/score/v1", "/healthz")
        poll_s = 0.002  # werkzeug's thread is typically up in <10 ms
        try:
            while True:
                try:
                    if requests.get(health_url, timeout=2).ok:
                        break
                except requests.RequestException:
                    # not just ConnectionError: a slow-to-wake server can
                    # also ReadTimeout; both mean "poll again"
                    pass
                if time.monotonic() > deadline:
                    raise StageFailure(
                        stage.name,
                        f"not healthy within max_startup_time_seconds="
                        f"{stage.max_startup_time_s}",
                    )
                time.sleep(poll_s)
                poll_s = min(poll_s * 2, 0.05)
        except BaseException:
            # never leak a started-but-not-registered server (a leaked
            # thread+socket per retry otherwise)
            handle.stop()
            raise
        ctx.services[stage.name] = handle
        return handle

    def _run_stage_timed(self, stage_name: str, ctx: StageContext,
                         stage_seconds: dict, stage_results: dict,
                         today: date, concurrent: bool = False) -> None:
        """Run one stage, recording wall-clock and result. With
        ``concurrent=True`` (stage is on a step thread) ANY failure is
        parked in ``ctx.failures`` for the step barrier to re-raise — so
        sibling stages finish cleanly, as independent k8s pods would —
        instead of dying silently in the thread's excepthook."""
        from bodywork_tpu.utils.profiling import annotate

        stage = self.spec.stages[stage_name]
        start_rel = self.recorder.now()
        t0 = time.perf_counter()
        try:
            with annotate(stage_name):  # named span in an active trace
                if stage.kind == "service":
                    result = self._run_service_stage(stage, ctx)
                else:
                    result = self._run_batch_stage(stage, ctx)
        except BaseException as exc:
            stage_seconds[stage_name] = time.perf_counter() - t0
            # the span duration IS stage_seconds (one measurement, two
            # views), so trace durations sum-check against DayResult
            self.recorder.add(stage_name, "stage", start_rel,
                              stage_seconds[stage_name], day=str(today),
                              failed=True)
            if not concurrent:
                raise
            if not isinstance(exc, StageFailure) and not _is_simulated_crash(exc):
                exc = StageFailure(stage.name, repr(exc))
            ctx.failures[stage_name] = exc
            return
        stage_seconds[stage_name] = time.perf_counter() - t0
        extra = {}
        if stage.kind == "batch" and getattr(result, "mode", None) is not None \
                and getattr(result, "rows_touched", None) is not None:
            # a TrainResult: the span records HOW the model was produced
            # (full vs incremental), the data footprint that cost, and
            # any degradation — the trace answers "why was this day
            # O(history)" without correlating against logs
            extra["train_mode"] = result.mode
            extra["rows_touched"] = result.rows_touched
            if getattr(result, "fallback_reason", None):
                extra["fallback_reason"] = result.fallback_reason
        if stage.kind == "service":
            # the serve span records WHAT went live and under whose
            # authority (registry production vs latest-checkpoint
            # fallback) — the trace answers "which model served this
            # day" without correlating against the store
            apps = getattr(result, "replica_apps", None)
            app = apps[0] if apps else getattr(result, "app", None)
            served_key = getattr(app, "model_key", None)
            if served_key is not None:
                extra["served_key"] = served_key
                extra["model_source"] = getattr(app, "model_source", None)
        self.recorder.add(stage_name, "stage", start_rel,
                          stage_seconds[stage_name], day=str(today), **extra)
        stage_results[stage_name] = result
        log.info(
            f"[{today}] {stage_name} done in {stage_seconds[stage_name]:.3f}s"
        )

    def _full_refit_fallback(self, today: date, ctx, journal,
                             stage_names: list[str]) -> None:
        """The registry gate REJECTED this day's incremental candidate:
        re-run the train stage(s) as a FULL refit immediately — the day
        must still end with a gateable, trustworthy candidate, not with
        yesterday's model and a rejected fine-tune. The retrain
        re-registers the same date-keyed checkpoint with new bytes
        (records.register_candidate flips the rejected record back to
        candidate on a digest change), and the caller re-gates it under
        the standard policy. The journal's train-stage artefact digests
        are re-recorded so a crash-resume verifies the FULL refit's
        bytes, not the rejected incremental's."""
        import dataclasses as _dc

        from bodywork_tpu.train.incremental import count_fallback

        # the lookahead handoff (if any) was already consumed by the
        # original train run — and it computed the INCREMENTAL result;
        # the fallback must genuinely retrain
        ctx.prefetched_train = None
        for name in stage_names:
            count_fallback("gate_rejected")
            log.warning(
                f"[{today}] incremental candidate rejected by the gate; "
                f"re-running {name} as a full refit"
            )
            stage = self.spec.stages[name]
            fn = resolve_executable(stage.executable)
            with self.recorder.span(f"full-refit-fallback-{name}", "gate",
                                    day=str(today)):
                with _device_ctx(self.device):
                    result = fn(ctx, **{**stage.args, "mode": "full"})
            result = _dc.replace(result, fallback_reason="gate_rejected")
            ctx.stage_results[name] = result
            if journal is not None:
                completes = self._journal_artefacts([name], ctx)
                if completes:
                    journal.record_completes(completes)

    def _run_registry_gate(self, today: date, ctx, journal=None,
                           train_stages: set | None = None) -> None:
        """The promotion-gate step between train and serve
        (``bodywork_tpu.registry``): adjudicate the candidate the train
        step just registered — promote it to the ``production`` alias or
        reject it — BEFORE the serve step resolves what to load, so a
        bad retrain never takes traffic. Runner-internal, so it rides
        the day report as its own ``gate``-category span (plus the
        decision in ``stage_results``) rather than an entry in
        ``stage_seconds``, which stays exactly the user's DECLARED DAG.
        No retries; a gate FAILURE (as opposed to a rejection) only
        logs — serving then keeps the current production (or the
        latest-checkpoint fallback on a store that has never promoted).

        INCREMENTAL candidates (``train/incremental.py``) get two extra
        behaviours: the gate policy arms shadow evaluation
        (``INCREMENTAL_SHADOW_DAYS`` — the approximate MLP path is only
        safe because a degraded fine-tune is caught here), and a
        rejection triggers the same-day full-refit fallback
        (:meth:`_full_refit_fallback`) followed by a re-gate under the
        standard policy."""
        stage_results = ctx.stage_results
        start_rel = self.recorder.now()
        t0 = time.perf_counter()
        failed = False
        fallback = False
        verdict = None
        try:
            from bodywork_tpu.registry import ModelRegistry

            def _result_mode(name):
                result = stage_results.get(name)
                mode = getattr(result, "mode", None)
                if mode is not None:
                    return mode
                # a journal-SKIPPED train stage leaves its journal entry
                # dict (not a TrainResult) in stage_results: resolve the
                # mode the stage ran with (spec arg, else the pod env
                # knob) — a crash resumed between train-complete and the
                # gate must not silently adjudicate an incremental
                # candidate under the default policy, dropping the
                # shadow check and the full-refit fallback
                from bodywork_tpu.pipeline.stages import _train_env_mode

                return (
                    self.spec.stages[name].args.get("mode")
                    or _train_env_mode()
                )

            incremental_stages = [
                n for n in (train_stages or ())
                if _result_mode(n) == "incremental"
            ]
            if incremental_stages:
                from bodywork_tpu.registry.gates import GatePolicy
                from bodywork_tpu.train.incremental import (
                    INCREMENTAL_SHADOW_DAYS,
                )

                registry = ModelRegistry(
                    self.store,
                    policy=GatePolicy(shadow_days=INCREMENTAL_SHADOW_DAYS),
                )
            else:
                registry = ModelRegistry(self.store)
            decision = registry.gate(day=today)
            if decision is not None and not decision.promote:

                def _produced(name, model_key):
                    result = stage_results.get(name)
                    if getattr(result, "model_artefact_key", None) == model_key:
                        return True
                    # journal-skipped stage: the entry's artefact digest
                    # map names what the stage produced
                    return isinstance(result, dict) and model_key in (
                        result.get("artefacts") or {}
                    )

                rejected = [
                    n for n in incremental_stages
                    if _produced(n, decision.model_key)
                ]
                if rejected:
                    fallback = True
                    self._full_refit_fallback(today, ctx, journal, rejected)
                    decision = ModelRegistry(self.store).gate(day=today)
            stage_results["registry-gate"] = decision
            if decision is not None:
                verdict = "promoted" if decision.promote else "rejected"
                log.info(
                    f"[{today}] registry gate: {verdict.upper()} "
                    f"{decision.model_key}"
                )
        except Exception as exc:
            failed = True
            log.error(f"registry gate failed (non-fatal): {exc!r}")
        extra = {"verdict": verdict} if verdict else {}
        if fallback:
            extra["full_refit_fallback"] = True
        if failed:
            extra["failed"] = True
        self.recorder.add("registry-gate", "gate", start_rel,
                          time.perf_counter() - t0, day=str(today), **extra)

    def _generate_offsets(self) -> list[int]:
        return [
            s.args.get("offset_days", 1)
            for s in self.spec.stages.values()
            if s.executable.endswith(":generate_stage")
        ]

    def _enqueue_generate(self, targets: list[date]) -> None:
        """Queue the generator's device sampling for the given dates on the
        single background prefetch worker. The generator is a pure function
        of (date, drift), so its device round-trips can run any time before
        each date's generate stage; that stage waits on the box's ``ready``
        event and only persists (at its proper DAG position, so stage-1
        never sees tomorrow's file early). A multi-day simulation enqueues
        its WHOLE horizon at day 0, keeping every sampling round-trip off
        the critical path (a day is now shorter than one round-trip)."""
        with self._gen_lock:
            fresh = [t for t in targets if t not in self._dataset_boxes]
            for t in fresh:
                box = {"ready": threading.Event()}
                self._dataset_boxes[t] = box
                # queue carries the box itself: a stage popping its entry
                # from _dataset_boxes must not break the worker
                self._gen_queue.append((t, box))
            if fresh and self._gen_worker is None:
                self._gen_worker = threading.Thread(
                    target=self._generate_worker,
                    name="dataset-prefetch",
                    daemon=True,
                )
                self._gen_worker.start()

    def _generate_worker(self) -> None:
        while True:
            with self._gen_lock:
                if not self._gen_queue:
                    self._gen_worker = None
                    return
                target, box = self._gen_queue.pop(0)
            try:
                from bodywork_tpu.data.generator import generate_day

                with self.recorder.span(
                    f"prefetch-dataset-{target}", "prefetch"
                ):
                    with _device_ctx(self.device):
                        X, y = generate_day(target, self.drift)
                box["X"], box["y"] = X, y
            except Exception as exc:  # stage falls back to inline
                log.warning(f"dataset prefetch failed (non-fatal): {exc!r}")
            finally:
                box["ready"].set()

    def _refresh_snapshot_async(self) -> None:
        """Refresh the consolidated-history snapshot on a background
        thread when the day that just ran made it stale. Off the
        critical path by construction: the day's wall-clock is already
        measured, and at most one refresh is in flight (a long write
        simply skips a beat — the next day triggers again)."""
        with self._compact_lock:
            if self._compact_thread is not None and self._compact_thread.is_alive():
                return

            def _work():
                try:
                    from bodywork_tpu.data.snapshot import (
                        refresh_due,
                        write_snapshot,
                    )

                    if refresh_due(self.store):
                        with self.recorder.span("snapshot-refresh", "compact"):
                            write_snapshot(self.store)
                except Exception as exc:  # cold readers keep the old snapshot
                    log.warning(f"snapshot refresh failed (non-fatal): {exc!r}")

            self._compact_thread = threading.Thread(
                target=_work, name="snapshot-compactor", daemon=True
            )
            self._compact_thread.start()

    def _start_lookahead_train(self, tomorrow: date) -> None:
        """Train tomorrow's model NOW, on a background thread — tomorrow's
        training set is complete the moment today's generate stage persists
        its dataset, so the train overlaps today's test stage. Tomorrow's
        ``train_stage`` collects the result (``ctx.prefetched_train``)."""
        train_spec = next(
            (
                s
                for s in self.spec.stages.values()
                if s.executable.endswith(":train_stage")
            ),
            None,
        )
        if train_spec is None:
            return
        ctx_next = StageContext(
            store=self.store,
            today=tomorrow,
            drift=self.drift,
            persistent_process=True,
            # compute only: artefacts are written when tomorrow's train
            # stage collects the result, so an aborted day never leaves a
            # future-dated model in the store
            defer_artefacts=True,
        )
        fn = resolve_executable(train_spec.executable)
        box: dict = {}

        def _work():
            try:
                with self.recorder.span(
                    f"lookahead-train-{tomorrow}", "overlap"
                ):
                    with _device_ctx(self.device):
                        box["result"] = fn(ctx_next, **train_spec.args)
            except BaseException as exc:  # tomorrow's stage retrains inline
                box["exc"] = exc

        t = threading.Thread(
            target=_work, name=f"lookahead-train-{tomorrow}", daemon=True
        )
        box["thread"] = t
        t.start()
        self._pending_train = (tomorrow, box)

    # -- crash resume ------------------------------------------------------
    def _resume_state(self, journal) -> tuple[dict[str, dict], str]:
        """Verify the journal's completed stages against the store and
        classify how this run starts. Returns ``(skip set, outcome)``
        where outcome is a ``bodywork_tpu_runner_resumes_total`` label.
        Only BATCH stages are ever skippable — a service died with the
        process and must restart regardless of what the journal says."""
        skip, mismatch = journal.verify_completed()
        skip = {
            name: entry
            for name, entry in skip.items()
            if name in self.spec.stages
            and self.spec.stages[name].kind == "batch"
        }
        if journal.was_corrupt:
            outcome = "rerun_corrupt"
        elif mismatch:
            outcome = "rerun_mismatch"
        elif journal.prior_status is None:
            outcome = "fresh"
        else:
            outcome = "resumed"
        return skip, outcome

    def _noop_day_result(self, today: date, skip: dict) -> DayResult:
        """The whole day was already journalled complete and every
        artefact verified: report it without executing anything (no
        stage, no service, no gate)."""
        span_mark = self.recorder.mark()
        start_rel = self.recorder.now()
        for name in self.spec.stages:
            self.recorder.add(name, "stage", start_rel, 0.0,
                              day=str(today), skipped=True)
        self.recorder.add(f"run-day-{today}", "day", start_rel, 0.0,
                          resumed_noop=True)
        log.info(
            f"[{today}] run journal marks the day complete and every "
            "artefact verified; resumed as a no-op"
        )
        return DayResult(
            day=today,
            wall_clock_s=0.0,
            stage_seconds={name: 0.0 for name in self.spec.stages},
            stage_results={
                name: skip.get(name, {"state": "complete"})
                for name in self.spec.stages
            },
            spans=self.recorder.since(span_mark),
            skipped_stages=tuple(self.spec.stages),
            noop=True,
        )

    def _journal_artefacts(self, names: list[str], ctx) -> dict[str, dict]:
        """``{stage: {artefact key: content digest}}`` for the batch
        stages that just completed — what ``record_completes`` persists.
        Digests hash the bytes actually in the store (the source of
        truth a resume will verify against), never in-memory copies."""
        from bodywork_tpu.pipeline.journal import artefact_digest
        from bodywork_tpu.pipeline.stages import stage_artefact_keys

        out: dict[str, dict] = {}
        for name in names:
            stage = self.spec.stages[name]
            if stage.kind == "service" or name not in ctx.stage_results:
                continue
            artefacts: dict[str, str] = {}
            for key in stage_artefact_keys(
                stage, ctx.stage_results.get(name), ctx
            ):
                try:
                    artefacts[key] = artefact_digest(self.store.get_bytes(key))
                except Exception as exc:  # journal stays honest: no digest,
                    # no skip — the stage just re-runs on resume
                    log.warning(
                        f"could not digest {key!r} for the journal: {exc!r}"
                    )
            out[name] = artefacts
        return out

    # -- DAG execution -----------------------------------------------------
    def run_day(
        self,
        today: date,
        scoring_url: str | None = None,
        lookahead_train: bool = False,
        resume: bool = True,
    ) -> DayResult:
        journal = None
        skip: dict[str, dict] = {}
        if resume:
            from bodywork_tpu.pipeline.journal import RunJournal, count_resume

            journal = RunJournal(self.store, today)
            journal.acquire()  # LeaseLost propagates: the caller exits
            skip, outcome = self._resume_state(journal)
            batch_stages = [
                n for n, s in self.spec.stages.items() if s.kind == "batch"
            ]
            if journal.prior_status == "complete" and all(
                n in skip for n in batch_stages
            ):
                count_resume("noop")
                journal.release()  # nothing to do: don't sit on the TTL
                return self._noop_day_result(today, skip)
            count_resume(outcome)
        ctx = StageContext(
            store=self.store,
            today=today,
            drift=self.drift,
            scoring_url=scoring_url,
            persistent_process=True,
        )
        pending = getattr(self, "_pending_train", None)
        if pending is not None and pending[0] == today:
            ctx.prefetched_train = pending[1]
        self._pending_train = None
        self._enqueue_generate(
            [today + timedelta(days=o) for o in self._generate_offsets()]
        )
        ctx.prefetched_datasets = self._dataset_boxes
        gen_stages = {
            name
            for name, s in self.spec.stages.items()
            if s.executable.endswith(":generate_stage")
        }
        train_stages = {
            name
            for name, s in self.spec.stages.items()
            if s.executable.endswith(":train_stage")
        }
        gate_pending = bool(train_stages)
        if gate_pending and any(
            set(step) & train_stages
            and any(self.spec.stages[n].kind == "service" for n in step)
            for step in self.spec.dag
        ):
            # the gate fires at the step BARRIER after train completes;
            # a spec co-locating train and a service stage in one step
            # makes the service resolve its model before this day's
            # candidate is adjudicated — say so rather than silently
            # weakening the "a bad retrain never takes traffic" contract
            log.warning(
                "pipeline spec places a train stage and a service stage "
                "in the same DAG step: the registry gate runs at the "
                "step boundary, so the service resolves its model "
                "BEFORE today's candidate is gated (it serves the "
                "previous production / latest until the next reload poll)"
            )
        stage_seconds: dict[str, float] = {}
        stage_results = ctx.stage_results
        span_mark = self.recorder.mark()
        day_start_rel = self.recorder.now()
        day_start = time.perf_counter()
        try:
            for step in self.spec.dag:
                # seeded process-kill point: one per step barrier (plus
                # one after the last step) — the crash soak's
                # stage-boundary sweep anchors here
                _hit_kill_point("stage_boundary")
                to_run = [n for n in step if n not in skip]
                for name in step:
                    if name in skip:
                        # journal-verified complete: report the skip in
                        # the same shapes a run records (span + seconds
                        # + a stage_results entry) so day reports stay
                        # structurally identical
                        stage_seconds[name] = 0.0
                        stage_results[name] = skip[name]
                        self.recorder.add(name, "stage", self.recorder.now(),
                                          0.0, day=str(today), skipped=True)
                        log.info(
                            f"[{today}] {name} skipped "
                            "(journal-verified complete)"
                        )
                if journal is not None and to_run:
                    # write-ahead: a crash from here on finds these
                    # stages at 'intent' and re-executes them
                    journal.record_intents(to_run)
                # stages within a step are independent and run CONCURRENTLY
                # (concurrent pods in the k8s materialisation); steps are
                # barriers
                if len(to_run) == 1:
                    self._run_stage_timed(to_run[0], ctx, stage_seconds,
                                          stage_results, today)
                elif to_run:
                    threads = [
                        threading.Thread(
                            target=self._run_stage_timed,
                            args=(name, ctx, stage_seconds, stage_results,
                                  today, True),
                            name=f"step-{name}",
                        )
                        for name in to_run
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    failed = [n for n in to_run if n in ctx.failures]
                    if failed:
                        raise ctx.failures[failed[0]]
                if journal is not None and to_run:
                    completes = self._journal_artefacts(to_run, ctx)
                    if completes:
                        journal.record_completes(completes)
                # the registry gate sits BETWEEN train and serve: as soon
                # as every train stage has registered its candidate (and
                # before any later step resolves what to serve), the gate
                # promotes or rejects it
                if gate_pending and train_stages <= set(stage_results):
                    self._run_registry_gate(
                        today, ctx, journal, train_stages=train_stages
                    )
                    gate_pending = False
                # tomorrow's training set is complete once every generate
                # stage has persisted: overlap tomorrow's train with the
                # rest of today (typically the test stage)
                if (
                    lookahead_train
                    and gen_stages
                    and gen_stages <= set(stage_results)
                ):
                    self._start_lookahead_train(today + timedelta(days=1))
                    lookahead_train = False
            _hit_kill_point("stage_boundary")
            if journal is not None:
                journal.record_day_complete()
        except BaseException as exc:
            from bodywork_tpu.utils.shutdown import ShutdownRequested

            if journal is not None:
                if isinstance(exc, ShutdownRequested):
                    # graceful SIGTERM: a clean 'interrupted' mark so the
                    # next run resumes (in-flight stages stay at intent)
                    journal.record_interrupted()
                elif not _is_simulated_crash(exc):
                    # stage failure etc. unwinding normally: release the
                    # lease so the CronJob's backoff retry starts
                    # immediately instead of waiting out the TTL. A
                    # simulated crash gets NO cleanup — it stands in for
                    # process death, where none runs.
                    journal.release()
            raise
        finally:
            for name, handle in ctx.services.items():
                handle.stop()
        wall_clock_s = time.perf_counter() - day_start
        # the day envelope, then the window slice: stage spans plus any
        # overlap/prefetch spans that completed inside this day
        self.recorder.add(f"run-day-{today}", "day", day_start_rel,
                          wall_clock_s)
        # consolidate history AFTER the clock stops: tomorrow's cold
        # readers (and this process's own next train, via the caches) see
        # today's days in one artefact without today paying the write
        self._refresh_snapshot_async()
        return DayResult(
            day=today,
            wall_clock_s=wall_clock_s,
            stage_seconds=stage_seconds,
            stage_results=stage_results,
            spans=self.recorder.since(span_mark),
            skipped_stages=tuple(n for n in self.spec.stages if n in skip),
        )

    # -- multi-day simulation ----------------------------------------------
    def bootstrap(self, start: date) -> None:
        """Seed day-0 data if the store has none (the reference bootstraps by
        hand-running the stage-3 notebook before the first deployment)."""
        if not self.store.history(DATASETS_PREFIX):
            from bodywork_tpu.data.generator import generate_day
            from bodywork_tpu.data.io import Dataset, persist_dataset

            with self.recorder.span(f"bootstrap-{start}", "setup"):
                with _device_ctx(self.device):
                    X, y = generate_day(start, self.drift)
                persist_dataset(self.store, Dataset(X, y, start))
            log.info(f"bootstrapped day-0 dataset for {start}")

    def _prewarm_horizon(self, days: int) -> None:
        """Start background compiles of every train/eval row bucket the
        whole simulation horizon will need. Day lengths shrank below XLA
        compile time, so warming only 1-2 days ahead (the trainer's own
        lookahead) can lose the race on bucket-crossing days; the runner
        knows the full horizon up front and warms it all at day 0."""
        stage = next(
            (
                s
                for s in self.spec.stages.values()
                if s.executable.endswith(":train_stage")
            ),
            None,
        )
        if stage is None:
            return
        from bodywork_tpu.train.prewarm import prewarm_async

        model_type = stage.args.get("model_type", "linear")
        if stage.args.get("mesh_data") or stage.args.get("mesh_model", 1) > 1:
            # sharded training dispatches mesh programs the single-device
            # prewarm cannot represent (and mesh_* are not model kwargs)
            return
        from bodywork_tpu.pipeline.stages import _train_env_mode

        if (stage.args.get("mode") or _train_env_mode()) == "incremental":
            # the incremental path never dispatches the fused full-fit
            # programs this warms (its eval buckets are tail-sized and
            # constant); the rare full-refit fallback pays its own
            # compile instead of every sim bootstrap paying all of them
            return
        model_kwargs = {
            k: v for k, v in stage.args.items()
            if k not in ("model_type", "mode", "mesh_data", "mesh_model")
        } or None
        # Base the estimate on the ACTUAL persisted history size (the y>=0
        # filter drops a sigma-dependent fraction of n_samples, so counting
        # days * n_samples would overshoot and can warm the wrong bucket on
        # a crossing day). load_all_datasets is cached, so this prepays
        # stage-1's parse rather than adding work. Future days still need an
        # estimate; warm both ends of the plausible filter-drop range so the
        # bucket actually crossed is covered either way.
        from bodywork_tpu.data.io import load_all_datasets

        n_now = len(load_all_datasets(self.store))
        per_day = self.drift.n_samples
        with self.recorder.span("prewarm-enqueue", "prewarm", days=days):
            for i in range(days):
                prewarm_async(model_type, model_kwargs, n_now + i * per_day)
                prewarm_async(
                    model_type, model_kwargs, n_now + int(i * per_day * 0.85)
                )

    def _drain_compactor(self, timeout_s: float = 60.0) -> bool:
        """Join the background snapshot compactor (True when none is
        left running). Called on BOTH exits of ``run_simulation`` — a
        crash path that leaves the daemon thread mid-refresh would let a
        half-written snapshot race whatever inspects the store next (the
        crash soak's byte-identity check, a resuming runner)."""
        thread = self._compact_thread
        if thread is None:
            return True
        thread.join(timeout=timeout_s)
        if thread.is_alive():
            log.warning(
                f"background snapshot refresh still running after "
                f"{timeout_s:.0f}s; abandoning it"
            )
            return False
        return True

    def run_simulation(
        self, start: date, days: int, profile_dir: str | None = None,
        resume: bool = True,
    ) -> list[DayResult]:
        """The daily MLOps loop over N simulated days: each day trains on
        history to date, deploys, generates the next (drifted) day, and
        tests the live service against it.

        ``profile_dir`` wraps the whole loop in a ``jax.profiler`` trace
        (the TPU-native analogue of the reference's full-sample-rate Sentry
        tracing — SURVEY.md §5); view with TensorBoard or Perfetto."""
        from bodywork_tpu.utils.profiling import maybe_trace

        self.bootstrap(start)
        self._prewarm_horizon(days)
        # queue every sampling round-trip of the horizon off-path now
        self._enqueue_generate(
            [
                start + timedelta(days=i + o)
                for i in range(days)
                for o in self._generate_offsets()
            ]
        )
        # Pay ALL the horizon's bucket compiles during bootstrap (dataset
        # prefetch above overlaps the wait). A compile (~0.3 s linear,
        # seconds for the MLP) dwarfs a steady-state day, so letting the
        # serialized prewarm worker race the loop puts bucket-crossing
        # compiles back on the critical path it exists to clear.
        if days > 1:
            from bodywork_tpu.train.prewarm import wait_idle

            t0 = time.perf_counter()
            with self.recorder.span("prewarm-drain", "prewarm"):
                wait_idle()
            log.info(
                f"horizon bucket compiles drained in "
                f"{time.perf_counter() - t0:.2f}s (bootstrap cost)"
            )
        results = []
        try:
            with maybe_trace(profile_dir, label=f"{days}-day simulation"):
                for i in range(days):
                    today = start + timedelta(days=i)
                    result = self.run_day(
                        today, lookahead_train=(i < days - 1), resume=resume
                    )
                    results.append(result)
                    log.info(
                        f"simulated day {today}: "
                        f"{result.wall_clock_s:.2f}s wall-clock"
                    )
        except BaseException:
            # an exception escaping run_day (stage failure, SIGTERM
            # unwind, simulated crash) must still drain — or at least
            # deterministically abandon — the background compactor:
            # returning with the daemon thread mid-write would let a
            # half-written snapshot race the soak's byte-identity check
            # or the resuming runner's first reads
            self._drain_compactor()
            raise
        # Drain the background compactor and top up the final day's
        # consolidation before returning (untimed — the day loop's clock
        # already stopped): a process exiting right after run_simulation
        # would otherwise kill the daemon thread mid-refresh, and a
        # 1-day run would never produce a snapshot at all.
        if not self._drain_compactor():
            # an unusually slow write is still in flight: starting a
            # second full consolidation here would duplicate the
            # whole O(history) write and race it on the same keys
            return results
        try:
            from bodywork_tpu.data.snapshot import refresh_due, write_snapshot

            if refresh_due(self.store):
                with self.recorder.span("snapshot-refresh", "compact"):
                    write_snapshot(self.store)
        except Exception as exc:  # cold readers keep the old snapshot
            log.warning(f"final snapshot refresh failed (non-fatal): {exc!r}")
        return results
