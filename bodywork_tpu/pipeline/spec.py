"""Declarative pipeline specification (reference C1, ``bodywork.yaml``).

The reference declares its whole orchestration layer in one YAML file: a
project name, a DAG string (``stage-1 >> stage-2 >> stage-3 >> stage-4`` —
``bodywork.yaml:5``), and per-stage blocks with executable path, pip
requirements, cpu/memory requests, batch-vs-service type, retries, timeouts,
replicas, port, ingress, and secret env injection (``bodywork.yaml:8-82``).

This module keeps that declarative model — same stage taxonomy
(``batch`` run-to-completion vs ``service`` long-running), same
retry/timeout/replica knobs, same ``a >> b,c >> d`` DAG grammar — but adds
the TPU scheduling dimension: each stage can request a GKE TPU node-pool
accelerator/topology, and executables are framework entrypoints rather than
ad-hoc scripts.
"""
from __future__ import annotations

import dataclasses
import io
from typing import Any

import yaml


def parse_dag(dag: str) -> list[list[str]]:
    """``"a >> b,c >> d"`` -> ``[["a"], ["b", "c"], ["d"]]``.

    Same grammar as Bodywork DAG strings (``bodywork.yaml:5``); stages within
    a step may run concurrently, steps run in order.
    """
    steps = []
    for step in dag.split(">>"):
        names = [s.strip() for s in step.split(",") if s.strip()]
        if names:
            steps.append(names)
    return steps


@dataclasses.dataclass
class ResourceSpec:
    """Per-stage resource requests (reference ``bodywork.yaml:17-18,36-37``)
    plus the TPU node-pool dimension."""

    cpu_request: float = 0.5
    memory_mb: int = 256
    #: GKE TPU accelerator type for nodeSelector, e.g. "tpu-v5-lite-podslice"
    tpu_accelerator: str | None = None
    #: GKE TPU topology for nodeSelector, e.g. "1x1" (v5e-1) or "2x4" (v5e-8)
    tpu_topology: str | None = None
    #: chips requested as the ``google.com/tpu`` resource (PER HOST)
    tpu_chips: int = 0
    #: worker hosts in the TPU slice. >1 turns a batch stage's Job into an
    #: Indexed multi-host Job (one pod per host) with a headless Service
    #: and JAX coordinator wiring, so ``parallel.multihost_init`` joins the
    #: pods into one jax.distributed cluster (mesh over ICI+DCN)
    tpu_hosts: int = 1


@dataclasses.dataclass
class StageSpec:
    """One pipeline stage (reference per-stage blocks, ``bodywork.yaml:8-82``)."""

    name: str
    kind: str  # "batch" (Job) | "service" (Deployment)
    #: dotted path to the stage callable, e.g.
    #: "bodywork_tpu.pipeline.stages:train_stage"
    executable: str
    args: dict[str, Any] = dataclasses.field(default_factory=dict)
    retries: int = 2                      # bodywork.yaml:21
    max_completion_time_s: float = 30.0   # bodywork.yaml:20 (batch)
    max_startup_time_s: float = 30.0      # bodywork.yaml:39 (service)
    replicas: int = 1                     # bodywork.yaml:40
    port: int | None = None               # bodywork.yaml:41
    ingress: bool = False                 # bodywork.yaml:42
    env: dict[str, str] = dataclasses.field(default_factory=dict)
    #: names of k8s secrets to inject as env vars (bodywork.yaml:22-26);
    #: these are REQUIRED — a missing secret fails the pod at admission
    #: (CreateContainerConfigError), not obscurely at runtime
    secrets: list[str] = dataclasses.field(default_factory=list)
    #: secrets injected with ``optional: true`` — for features that are
    #: no-ops when unconfigured (e.g. the sentry-integration DSN)
    optional_secrets: list[str] = dataclasses.field(default_factory=list)
    #: container image override for THIS stage's pods (reference parity:
    #: per-stage dependency isolation, bodywork.yaml:10-16 pins each
    #: stage's own requirements); None = the pipeline-wide image
    image: str | None = None
    #: THIS stage's pinned pip requirements (reference
    #: bodywork.yaml:10-16,29-35,50-54,67-72: each stage installs its own
    #: pin set so stages deploy and upgrade independently). When set and
    #: ``image`` is not, the manifest generator derives a per-stage image
    #: tag from these pins, and ``pipeline.images`` emits the build
    #: context (Dockerfile + requirements.txt) that produces it.
    requirements: list[str] = dataclasses.field(default_factory=list)
    resources: ResourceSpec = dataclasses.field(default_factory=ResourceSpec)

    def __post_init__(self):
        if self.kind not in ("batch", "service"):
            raise ValueError(f"stage {self.name!r}: kind must be batch|service")


@dataclasses.dataclass
class PipelineSpec:
    name: str
    dag: list[list[str]]
    stages: dict[str, StageSpec]
    log_level: str = "INFO"               # bodywork.yaml:83-84
    version: str = "0.1"

    def __post_init__(self):
        declared = set(self.stages)
        in_dag = {s for step in self.dag for s in step}
        missing = in_dag - declared
        if missing:
            raise ValueError(f"DAG references undeclared stages: {sorted(missing)}")

    def service_dns(self, stage_name: str) -> str:
        """Cluster-internal service name, same convention as Bodywork's
        ``<project>--<stage>`` (``stage_4:28``)."""
        return f"{self.name}--{stage_name}"

    # -- YAML round-trip ---------------------------------------------------
    def to_yaml(self) -> str:
        doc = {
            "project": {
                "name": self.name,
                "version": self.version,
                "DAG": " >> ".join(",".join(step) for step in self.dag),
            },
            "stages": {
                name: _stage_to_doc(stage) for name, stage in self.stages.items()
            },
            "logging": {"log_level": self.log_level},
        }
        buf = io.StringIO()
        yaml.safe_dump(doc, buf, sort_keys=False)
        return buf.getvalue()

    @classmethod
    def from_yaml(cls, text: str) -> "PipelineSpec":
        doc = yaml.safe_load(text)
        stages = {
            name: _stage_from_doc(name, block)
            for name, block in doc.get("stages", {}).items()
        }
        return cls(
            name=doc["project"]["name"],
            dag=parse_dag(doc["project"]["DAG"]),
            stages=stages,
            log_level=doc.get("logging", {}).get("log_level", "INFO"),
            version=str(doc["project"].get("version", "0.1")),
        )


def _stage_to_doc(stage: StageSpec) -> dict:
    doc: dict[str, Any] = {
        "kind": stage.kind,
        "executable": stage.executable,
        "args": dict(stage.args),
        "retries": stage.retries,
        "resources": dataclasses.asdict(stage.resources),
    }
    if stage.kind == "batch":
        doc["max_completion_time_seconds"] = stage.max_completion_time_s
    else:
        doc["max_startup_time_seconds"] = stage.max_startup_time_s
        doc["replicas"] = stage.replicas
        doc["port"] = stage.port
        doc["ingress"] = stage.ingress
    if stage.env:
        doc["env"] = dict(stage.env)
    if stage.secrets:
        doc["secrets"] = list(stage.secrets)
    if stage.optional_secrets:
        doc["optional_secrets"] = list(stage.optional_secrets)
    if stage.image:
        doc["image"] = stage.image
    if stage.requirements:
        doc["requirements"] = list(stage.requirements)
    return doc


#: secrets the framework itself declares optional-by-design; YAML written
#: before the required/optional split listed them under plain ``secrets``,
#: and materialising those as required refs would CreateContainerConfigError
#: every pod on clusters that never created them
_KNOWN_OPTIONAL_SECRETS = ("sentry-integration",)


def _stage_from_doc(name: str, doc: dict) -> StageSpec:
    resources = ResourceSpec(**doc.get("resources", {}))
    secrets = list(doc.get("secrets", []))
    optional_secrets = list(doc.get("optional_secrets", []))
    for known in _KNOWN_OPTIONAL_SECRETS:
        if known in secrets:  # legacy-doc migration
            secrets.remove(known)
            if known not in optional_secrets:
                optional_secrets.append(known)
    return StageSpec(
        name=name,
        kind=doc["kind"],
        executable=doc["executable"],
        args=doc.get("args", {}),
        retries=doc.get("retries", 2),
        max_completion_time_s=doc.get("max_completion_time_seconds", 30.0),
        max_startup_time_s=doc.get("max_startup_time_seconds", 30.0),
        replicas=doc.get("replicas", 1),
        port=doc.get("port"),
        ingress=doc.get("ingress", False),
        env=doc.get("env", {}),
        secrets=secrets,
        optional_secrets=optional_secrets,
        image=doc.get("image"),
        requirements=list(doc.get("requirements", [])),
        resources=resources,
    )


#: Per-stage pinned requirement sets (reference parity:
#: ``bodywork.yaml:10-16,29-35,50-54,67-72`` gives each stage its own pip
#: pins so stages deploy and upgrade independently — and drift apart only
#: deliberately, unlike the reference's accidental numpy 1.19.5-vs-1.19.4
#: skew, SURVEY.md §2 known-bugs). One shared pin table + per-stage
#: SELECTIONS keeps versions consistent where stages overlap.
_PINS = {
    "jax": "jax[tpu]==0.9.0",
    "numpy": "numpy==2.0.2",
    "pandas": "pandas==3.0.3",
    "werkzeug": "werkzeug==3.1.5",
    "requests": "requests==2.32.5",
    "optax": "optax==0.2.6",
    "pyyaml": "pyyaml==6.0.3",
}

#: Every stage pod runs ``python -m bodywork_tpu.cli run-stage``. The
#: cli -> runner -> stages baseline imports only pyyaml; each stage BODY
#: lazily imports its own closure, so the pin sets genuinely differ —
#: notably the test stage runs with no accelerator runtime at all
#: (reference parity: bodywork.yaml:67-72's stage 4 installs no sklearn
#: either). tests/test_pipeline.py measures each stage's actual
#: execution closure in a clean interpreter and asserts these sets
#: cover it.
STAGE_REQUIREMENTS = {
    # train: device compute + optimizer + history IO
    "stage-1-train-model": ["jax", "optax", "numpy", "pandas", "pyyaml"],
    # serve: device compute + the WSGI service (no pandas on the hot path)
    "stage-2-serve-model": ["jax", "optax", "numpy", "werkzeug", "pyyaml"],
    # generate: the fused jax sampler + CSV persistence
    "stage-3-generate-next-dataset": ["jax", "numpy", "pandas", "pyyaml"],
    # test: HTTP client + metric frames — deliberately jax-free
    "stage-4-test-model-scoring-service": [
        "numpy", "pandas", "requests", "pyyaml",
    ],
}


def stage_requirements(stage_name: str) -> list[str]:
    """The pinned requirement lines for one canonical stage."""
    return [_PINS[p] for p in STAGE_REQUIREMENTS[stage_name]]


def default_pipeline(
    model_type: str = "linear",
    scoring_mode: str = "batch",
    port: int = 5000,
    overlap_generate: bool = False,
) -> PipelineSpec:
    """The canonical daily train->serve->generate->test pipeline, mirroring
    the reference's four stages (``bodywork.yaml``) scheduled onto a v5e
    node pool.

    ``overlap_generate`` moves stage-3 into stage-2's DAG step
    (``s1 >> s2,s3 >> s4``): generation depends only on the simulated date,
    not on the freshly trained model, so running it concurrently with
    service startup preserves every data dependency (stage-4 still runs
    after both) while hiding one device round-trip per day. The reference's
    strictly serial DAG (``bodywork.yaml:5``) remains the default.
    """
    v5e = ResourceSpec(
        cpu_request=0.5,
        memory_mb=512,
        tpu_accelerator="tpu-v5-lite-podslice",
        tpu_topology="1x1",
        tpu_chips=1,
    )
    # the reference injects its secrets into EVERY stage (bodywork.yaml:22-26
    # mounts aws-credentials + sentry-integration); the store needs no
    # credential secret here (PVC/GCS workload identity), so the per-stage
    # list is the error-monitoring secret carrying SENTRY_DSN — OPTIONAL,
    # because error monitoring is a no-op when unconfigured (utils/errors.py)
    # and a required ref would fail every pod on clusters without it
    secrets = ["sentry-integration"]
    stages = {
        "stage-1-train-model": StageSpec(
            name="stage-1-train-model",
            requirements=stage_requirements("stage-1-train-model"),
            kind="batch",
            executable="bodywork_tpu.pipeline.stages:train_stage",
            args={"model_type": model_type},
            optional_secrets=list(secrets),
            resources=v5e,
        ),
        "stage-2-serve-model": StageSpec(
            name="stage-2-serve-model",
            requirements=stage_requirements("stage-2-serve-model"),
            kind="service",
            executable="bodywork_tpu.pipeline.stages:serve_stage",
            # compile only the buckets the tester's request sizes need
            # (each warmed bucket is one device dispatch at startup)
            args={"buckets": [2048] if scoring_mode == "batch" else [1]},
            replicas=2,
            port=port,
            ingress=False,
            optional_secrets=list(secrets),
            resources=v5e,
        ),
        "stage-3-generate-next-dataset": StageSpec(
            name="stage-3-generate-next-dataset",
            requirements=stage_requirements("stage-3-generate-next-dataset"),
            kind="batch",
            executable="bodywork_tpu.pipeline.stages:generate_stage",
            optional_secrets=list(secrets),
            resources=dataclasses.replace(v5e, tpu_chips=1),
        ),
        "stage-4-test-model-scoring-service": StageSpec(
            name="stage-4-test-model-scoring-service",
            requirements=stage_requirements("stage-4-test-model-scoring-service"),
            kind="batch",
            executable="bodywork_tpu.pipeline.stages:test_stage",
            # one full simulated day (<=1440 rows) scores in a single padded
            # device call in batch mode
            args=(
                {"mode": scoring_mode, "batch_size": 2048}
                if scoring_mode == "batch"
                else {"mode": scoring_mode}
            ),
            optional_secrets=list(secrets),
            resources=ResourceSpec(cpu_request=0.5, memory_mb=256),
        ),
    }
    if overlap_generate:
        dag = [
            ["stage-1-train-model"],
            ["stage-2-serve-model", "stage-3-generate-next-dataset"],
            ["stage-4-test-model-scoring-service"],
        ]
    else:
        dag = [
            ["stage-1-train-model"],
            ["stage-2-serve-model"],
            ["stage-3-generate-next-dataset"],
            ["stage-4-test-model-scoring-service"],
        ]
    return PipelineSpec(name="bodywork-tpu-pipeline", dag=dag, stages=stages)
