"""The four canonical stage callables (reference C2-C5 entrypoints).

Each stage is a function ``stage(ctx, **args)`` over a shared
:class:`StageContext` — the framework's replacement for the reference's
convention that a stage is "a python script with a ``main()``"
(``bodywork.yaml:9,28,49,66``). Batch stages return when done; service
stages return a handle the runner owns for the rest of the day.

Stage semantics (and their reference call stacks, SURVEY.md §3):

- ``train_stage``    <- ``stage_1_train_model.main`` (§3.1)
- ``serve_stage``    <- ``stage_2_serve_model`` ``__main__`` (§3.2)
- ``generate_stage`` <- ``stage_3_synthetic_data_generation.main`` (§3.3)
- ``test_stage``     <- ``stage_4_test_model_scoring_service.main`` (§3.4)
"""
from __future__ import annotations

import dataclasses
from datetime import date, timedelta

from bodywork_tpu.data import Dataset, generate_day, persist_dataset
from bodywork_tpu.data.generator import DriftConfig
from bodywork_tpu.monitor import (
    HttpScoringClient,
    InProcessScoringClient,
    run_service_test,
    scoring_endpoint,
)
from bodywork_tpu.serve import ServiceHandle, create_app
from bodywork_tpu.models.checkpoint import load_model
from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.utils.logging import get_logger

log = get_logger("pipeline.stages")


@dataclasses.dataclass
class StageContext:
    """Everything a stage needs from the orchestrator."""

    store: ArtefactStore
    #: the simulated "today" (the reference uses wall-clock ``date.today()``;
    #: parameterising it lets simulations run faster than real time)
    today: date
    drift: DriftConfig = dataclasses.field(default_factory=DriftConfig)
    #: service handles started earlier in the DAG, keyed by stage name
    services: dict = dataclasses.field(default_factory=dict)
    #: URL of the scoring service for cross-process testing (cluster DNS in
    #: k8s — ``stage_4:28``); None means test in-process via the app object
    scoring_url: str | None = None
    #: True when the orchestrator runs many days in one process (the local
    #: day-loop runner): enables cross-day warm-ahead optimisations that
    #: would be dead weight in a one-shot per-day pod
    persistent_process: bool = False
    #: failures from stages run on concurrent-step threads, keyed by stage
    #: name (the step barrier re-raises the first one)
    failures: dict = dataclasses.field(default_factory=dict)


def generate_stage(ctx: StageContext, offset_days: int = 1) -> str:
    """Generate the *next* simulated day's drifting data
    (reference stage 3: tomorrow's dataset appears today)."""
    target = ctx.today + timedelta(days=offset_days)
    X, y = generate_day(target, ctx.drift)
    key = persist_dataset(ctx.store, Dataset(X, y, target))
    return key


def train_stage(ctx: StageContext, model_type: str = "linear", **model_kwargs):
    """Train on all data to date, persist model + metrics (reference stage 1)."""
    from bodywork_tpu.train import train_on_history

    return train_on_history(
        ctx.store,
        model_type,
        model_kwargs=model_kwargs or None,
        prewarm_next=ctx.persistent_process,
        rows_per_day=ctx.drift.n_samples,
    )


def serve_stage(
    ctx: StageContext,
    host: str = "127.0.0.1",
    port: int = 0,
    buckets: tuple[int, ...] | None = None,
) -> ServiceHandle:
    """Load the latest model into device HBM and start the scoring service
    on a background thread (reference stage 2). Returns the handle; the
    runner keeps it alive for the rest of the day and tears it down at
    day end (the k8s deployment path instead keeps it up until re-deploy).

    ``buckets`` narrows the predictor's compiled shape set (each warmed
    bucket costs one device dispatch at startup) — the pipeline spec sets it
    to match the tester's request sizes."""
    model, model_date = load_model(ctx.store)
    # in the persistent day-loop these exact bucket shapes executed on
    # previous days, so skip warmup's error-surfacing device sync; a
    # one-shot pod keeps it (device faults fail startup, not requests)
    app = create_app(
        model,
        model_date,
        buckets=tuple(buckets) if buckets else None,
        warmup_sync=not ctx.persistent_process,
    )
    handle = ServiceHandle(app, host=host, port=port).start()
    handle.app = app
    return handle


def test_stage(
    ctx: StageContext,
    mode: str = "batch",
    service_stage: str = "stage-2-serve-model",
    max_rows: int | None = None,
    batch_size: int = 512,
):
    """Score the latest dataset through the live service and persist drift
    metrics (reference stage 4)."""
    if ctx.scoring_url is not None:
        client = HttpScoringClient(scoring_endpoint(ctx.scoring_url, mode))
    elif service_stage in ctx.services:
        client = InProcessScoringClient(ctx.services[service_stage].app)
    else:
        raise RuntimeError(
            f"test_stage needs a scoring_url or a running service "
            f"{service_stage!r} in the context"
        )
    return run_service_test(
        ctx.store, client, mode=mode, max_rows=max_rows, batch_size=batch_size
    )
