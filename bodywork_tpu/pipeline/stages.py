"""The four canonical stage callables (reference C2-C5 entrypoints).

Each stage is a function ``stage(ctx, **args)`` over a shared
:class:`StageContext` — the framework's replacement for the reference's
convention that a stage is "a python script with a ``main()``"
(``bodywork.yaml:9,28,49,66``). Batch stages return when done; service
stages return a handle the runner owns for the rest of the day.

Stage semantics (and their reference call stacks, SURVEY.md §3):

- ``train_stage``    <- ``stage_1_train_model.main`` (§3.1)
- ``serve_stage``    <- ``stage_2_serve_model`` ``__main__`` (§3.2)
- ``generate_stage`` <- ``stage_3_synthetic_data_generation.main`` (§3.3)
- ``test_stage``     <- ``stage_4_test_model_scoring_service.main`` (§3.4)
"""
from __future__ import annotations

import dataclasses
from datetime import date, timedelta

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.utils.logging import get_logger

# Stage-body dependencies (data/serve/monitor/models) import LAZILY
# inside each stage function: every stage pod runs this module, but each
# stage should pull only its own dependency closure — that is what lets
# the per-stage pin sets (``spec.STAGE_REQUIREMENTS``) genuinely differ,
# e.g. the test stage running without the accelerator runtime at all
# (reference parity: bodywork.yaml:67-72's stage 4 installs no sklearn).
# tests/test_pipeline.py pins each stage's measured import closure.

log = get_logger("pipeline.stages")


def _default_drift():
    from bodywork_tpu.data.drift_config import DriftConfig

    return DriftConfig()


def _params_equal(a, b) -> bool:
    """Exact (bitwise) equality of two HOST param pytrees."""
    import jax
    import numpy as np

    leaves_a, tree_a = jax.tree_util.tree_flatten(a)
    leaves_b, tree_b = jax.tree_util.tree_flatten(b)
    return (
        tree_a == tree_b
        and len(leaves_a) == len(leaves_b)
        and all(np.array_equal(x, y) for x, y in zip(leaves_a, leaves_b))
    )


@dataclasses.dataclass
class StageContext:
    """Everything a stage needs from the orchestrator."""

    store: ArtefactStore
    #: the simulated "today" (the reference uses wall-clock ``date.today()``;
    #: parameterising it lets simulations run faster than real time)
    today: date
    drift: "DriftConfig" = dataclasses.field(default_factory=_default_drift)  # noqa: F821
    #: service handles started earlier in the DAG, keyed by stage name
    services: dict = dataclasses.field(default_factory=dict)
    #: URL of the scoring service for cross-process testing (cluster DNS in
    #: k8s — ``stage_4:28``); None means test in-process via the app object
    scoring_url: str | None = None
    #: True when the orchestrator runs many days in one process (the local
    #: day-loop runner): enables cross-day warm-ahead optimisations that
    #: would be dead weight in a one-shot per-day pod
    persistent_process: bool = False
    #: failures from stages run on concurrent-step threads, keyed by stage
    #: name (the step barrier re-raises the first one)
    failures: dict = dataclasses.field(default_factory=dict)
    #: dataset prefetch boxes (persistent-process runner only): maps a
    #: target date -> {"ready": Event, "X": ..., "y": ...}. The generator is
    #: a pure function of (date, drift config), so its device sampling runs
    #: on a background worker ahead of time; stage-3 waits on ``ready`` and
    #: only writes the CSV.
    prefetched_datasets: dict = dataclasses.field(default_factory=dict)
    #: completed stages' return values this day, keyed by stage name (lets
    #: later stages reuse in-memory state the artefact store round-trip
    #: would otherwise re-create — e.g. serve reusing HBM-resident params)
    stage_results: dict = dataclasses.field(default_factory=dict)
    #: lookahead-train handoff from the previous simulated day (the runner
    #: starts tomorrow's train as soon as today's generate stage persists
    #: tomorrow's dataset): {"thread": Thread, "result": TrainResult}
    prefetched_train: dict | None = None
    #: True for a lookahead context: compute but do NOT write artefacts (the
    #: collecting day's stage persists them at its proper DAG position)
    defer_artefacts: bool = False


def stage_artefact_keys(stage_spec, result, ctx: StageContext) -> list[str]:
    """The durable artefact keys a just-completed stage produced — what
    the run journal (``pipeline/journal.py``) records (with content
    digests) so a resumed run can verify-and-skip the stage. Keyed off
    the executable the same way the runner's overlap machinery is;
    unknown stages return ``[]``, which the journal records as
    "complete but nothing verifiable" — a resuming run re-executes them
    rather than trusting blindly."""
    executable = stage_spec.executable
    if executable.endswith(":generate_stage"):
        return [result] if isinstance(result, str) else []
    if executable.endswith(":train_stage"):
        keys = [
            getattr(result, "model_artefact_key", None),
            getattr(result, "metrics_artefact_key", None),
            # an incremental train's sufficient-statistics document
            # (train/incremental.py) is journalled too: a resumed run
            # re-verifies its digest, and a mismatch re-runs the stage,
            # which rebuilds or re-folds it — never trusts it blindly
            getattr(result, "trainstate_artefact_key", None),
        ]
        return [k for k in keys if k]
    if executable.endswith(":test_stage"):
        # the test stage persists metrics keyed by the LATEST dataset
        # day (the one generate just wrote) — recompute the same key
        from bodywork_tpu.store.base import ArtefactNotFound
        from bodywork_tpu.store.schema import DATASETS_PREFIX, test_metrics_key

        try:
            _key, d = ctx.store.latest(DATASETS_PREFIX)
        except ArtefactNotFound:
            return []
        return [test_metrics_key(d)]
    return []


def generate_stage(ctx: StageContext, offset_days: int = 1) -> str:
    """Generate the *next* simulated day's drifting data
    (reference stage 3: tomorrow's dataset appears today).

    If the runner prefetched this date's samples at day start (the
    generator depends only on the date, not on any earlier stage's output),
    the device work is already done and only the persist remains. The
    dataset is NOT persisted before this stage's DAG position either way —
    stage-1's "all data to date" must never see tomorrow's file early."""
    from bodywork_tpu.data.io import Dataset, persist_dataset

    target = ctx.today + timedelta(days=offset_days)
    box = ctx.prefetched_datasets.pop(target, None)
    if box is not None:
        box["ready"].wait()
        if "X" in box:
            X, y = box["X"], box["y"]
        else:  # prefetch failed; fall back to computing inline
            from bodywork_tpu.data.generator import generate_day

            X, y = generate_day(target, ctx.drift)
    else:
        from bodywork_tpu.data.generator import generate_day

        X, y = generate_day(target, ctx.drift)
    key = persist_dataset(ctx.store, Dataset(X, y, target))
    return key


def _train_env_mode() -> str:
    """The deployed train mode from the pod environment
    (``BODYWORK_TPU_TRAIN_MODE``): an operator flips the daily retrain
    between the full refit and the O(1)-per-day incremental path
    (``train/incremental.py``) without a spec change. Malformed values
    degrade to ``full`` with a warning (the same contract as
    :func:`_serve_env_knobs` — a typo must never crash the pod); pinned
    against the ``cli train --mode`` choices by tests/test_incremental.py."""
    import os

    from bodywork_tpu.train.trainer import TRAIN_MODES

    raw = os.environ.get("BODYWORK_TPU_TRAIN_MODE", "").strip()
    if raw and raw not in TRAIN_MODES:
        log.warning(
            f"ignoring BODYWORK_TPU_TRAIN_MODE={raw!r} "
            f"(expected one of {TRAIN_MODES})"
        )
        raw = ""
    return raw or "full"


def train_stage(
    ctx: StageContext,
    model_type: str = "linear",
    mode: str | None = None,
    mesh_data: int | None = None,
    mesh_model: int = 1,
    **model_kwargs,
):
    """Train on all data to date, persist model + metrics (reference stage 1).

    ``mode`` picks the full refit vs the incremental O(1)-per-day path
    (spec args or ``cli train --mode``; None defaults from the pod
    environment via :func:`_train_env_mode`).

    ``mesh_data``/``mesh_model`` > 1 (spec args or ``train --mesh-data``)
    run the fit as the dp x tp sharded training step over a device mesh —
    see :func:`bodywork_tpu.train.train_on_history`.

    If the runner already ran this day's train as a lookahead (overlapped
    with the previous day's test stage — the training set for day d is
    complete the moment day d-1's generate stage persists), just collect
    that result; a failed lookahead falls back to training inline."""
    box = ctx.prefetched_train
    if box is not None:
        box["thread"].join()
        if "result" in box:
            result = box["result"]
            if result.model_artefact_key is None:
                # the lookahead deferred its writes; persist here, at this
                # stage's DAG position
                from bodywork_tpu.train import persist_train_result

                result = persist_train_result(ctx.store, result)
            return result
        log.warning(
            f"lookahead train failed ({box.get('exc')!r}); retraining inline"
        )
    from bodywork_tpu.train import train_on_history

    return train_on_history(
        ctx.store,
        model_type,
        model_kwargs=model_kwargs or None,
        prewarm_next=ctx.persistent_process,
        rows_per_day=ctx.drift.n_samples,
        persist=not ctx.defer_artefacts,
        mesh_data=mesh_data,
        mesh_model=mesh_model,
        mode=mode if mode is not None else _train_env_mode(),
    )


def _serve_env_knobs() -> tuple[
    str, int | None, float | None, str, int | None, int
]:
    """The deployed serving knobs (``(server_engine, max_pending,
    retry_after_max_s, dtype, mesh_data, mesh_model)``) from the pod
    environment — the k8s serve Deployment materialises them as env vars
    (``pipeline/k8s.py``) so an operator flips the HTTP front-end, the
    admission budget, the serving precision, or the device mesh with a
    ``kubectl set env``, no image rebuild. Malformed values are ignored
    with a warning (same contract as ``cli serve``'s env defaults): a
    typo must degrade to the default, never crash the serving pod."""
    import os

    from bodywork_tpu.serve.predictor import SERVE_DTYPES
    from bodywork_tpu.serve.server import SERVER_ENGINES

    engine = os.environ.get("BODYWORK_TPU_SERVER_ENGINE", "").strip()
    if engine and engine not in SERVER_ENGINES:
        log.warning(
            f"ignoring BODYWORK_TPU_SERVER_ENGINE={engine!r} "
            f"(expected one of {SERVER_ENGINES})"
        )
        engine = ""
    dtype = os.environ.get("BODYWORK_TPU_SERVE_DTYPE", "").strip()
    if dtype and dtype not in SERVE_DTYPES:
        log.warning(
            f"ignoring BODYWORK_TPU_SERVE_DTYPE={dtype!r} "
            f"(expected one of {SERVE_DTYPES})"
        )
        dtype = ""
    max_pending: int | None = None
    raw = os.environ.get("BODYWORK_TPU_MAX_PENDING", "").strip()
    if raw:
        try:
            max_pending = int(raw)
            if max_pending < 1:
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_MAX_PENDING={raw!r} "
                "(need an int >= 1)"
            )
            max_pending = None
    retry_after_max_s: float | None = None
    raw = os.environ.get("BODYWORK_TPU_RETRY_AFTER_MAX_S", "").strip()
    if raw:
        try:
            retry_after_max_s = float(raw)
            if retry_after_max_s < 1.0:
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_RETRY_AFTER_MAX_S={raw!r} "
                "(need a number >= 1)"
            )
            retry_after_max_s = None
    # the serving mesh (serve.server.build_predictor): data-parallel row
    # sharding x Megatron tensor parallelism. None/1 = single-device,
    # byte-identical to the pre-mesh behaviour
    mesh_data: int | None = None
    raw = os.environ.get("BODYWORK_TPU_MESH_DATA", "").strip()
    if raw:
        try:
            mesh_data = int(raw)
            if mesh_data < 1:
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_MESH_DATA={raw!r} (need an int >= 1)"
            )
            mesh_data = None
    mesh_model = 1
    raw = os.environ.get("BODYWORK_TPU_MESH_MODEL", "").strip()
    if raw:
        try:
            mesh_model = int(raw)
            if mesh_model < 1:
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_MESH_MODEL={raw!r} (need an int >= 1)"
            )
            mesh_model = 1
    return engine or "thread", max_pending, retry_after_max_s, \
        dtype or "float32", mesh_data, mesh_model


def _serve_tuned_env_knobs() -> tuple[
    float | None, int | None, tuple[int, ...] | None, str | None
]:
    """The deployed coalescer/bucket/tuned-config knobs
    (``(batch_window_ms, batch_max_rows, buckets, tuned_config_ref)``)
    from the pod environment — the second half of the serve Deployment's
    env materialisation (``pipeline/k8s.py``), split from
    :func:`_serve_env_knobs` only to keep that function's pinned tuple
    shape stable. Same malformed-degrades contract: a typo'd value is
    ignored with a warning, never a crash-looping pod. The knob names
    are pinned three ways against ``tune.config.TUNED_KNOB_ENV`` and
    the k8s env list by tests/test_tune.py."""
    import os

    window_ms: float | None = None
    raw = os.environ.get("BODYWORK_TPU_BATCH_WINDOW_MS", "").strip()
    if raw:
        try:
            window_ms = float(raw)
            # 0 is a legitimate EXPLICIT value: coalescing off, beating
            # a tuned document's window (the tuner itself fits 0.0 at
            # sparse arrival rates)
            if window_ms < 0:
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_BATCH_WINDOW_MS={raw!r} "
                "(need a number >= 0)"
            )
            window_ms = None
    max_rows: int | None = None
    raw = os.environ.get("BODYWORK_TPU_BATCH_MAX_ROWS", "").strip()
    if raw:
        try:
            max_rows = int(raw)
            if max_rows < 1:
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_BATCH_MAX_ROWS={raw!r} "
                "(need an int >= 1)"
            )
            max_rows = None
    buckets: tuple[int, ...] | None = None
    raw = os.environ.get("BODYWORK_TPU_BUCKETS", "").strip()
    if raw:
        try:
            buckets = tuple(int(b) for b in raw.split(",") if b.strip())
            if not buckets or any(b <= 0 for b in buckets):
                raise ValueError(raw)
        except ValueError:
            log.warning(
                f"ignoring BODYWORK_TPU_BUCKETS={raw!r} "
                "(need comma-separated positive ints)"
            )
            buckets = None
    from bodywork_tpu.tune.config import TUNED_CONFIG_ENV

    tuned = os.environ.get(TUNED_CONFIG_ENV, "").strip() or None
    return window_ms, max_rows, buckets, tuned


def _serve_fleet_env_knobs() -> int | None:
    """The deployed process-fleet topology knob
    (``BODYWORK_TPU_FRONTENDS`` — disaggregated serving: N
    parse/admission front-ends feeding one device-owning dispatcher)
    from the pod environment. Split from :func:`_serve_env_knobs` only
    to keep that function's pinned tuple shape stable, exactly as
    :func:`_serve_tuned_env_knobs` is. ``cli serve`` consumes the knob
    to build the process fleet; the IN-PROCESS serve stage cannot (one
    process by construction), so it surfaces and warns instead of
    silently swallowing a deployed topology choice. Name pinned
    three ways against the ``cli serve --frontends`` default and the
    k8s serve Deployment env list by tests. Same malformed-degrades
    contract: a typo is a warning, never a crash-looping pod."""
    import os

    raw = os.environ.get("BODYWORK_TPU_FRONTENDS", "").strip()
    if not raw:
        return None
    try:
        frontends = int(raw)
        if frontends < 1:
            raise ValueError(raw)
    except ValueError:
        log.warning(
            f"ignoring BODYWORK_TPU_FRONTENDS={raw!r} (need an int >= 1)"
        )
        return None
    return frontends


def _serve_transport_env_knobs() -> tuple[str, str | None, str, bool]:
    """The deployed cross-host-split knobs (``(transport,
    dispatcher_addr, role, standby)`` — ``serve.netqueue`` /
    ``serve.leadership``: which row-queue transport the front-end ->
    dispatcher handoff rides, where the dispatcher's listener lives,
    which half of the split this pod runs, and whether the dispatcher
    runs with a warm standby) from the pod environment. Split out like
    :func:`_serve_fleet_env_knobs`, and consumed the same way:
    ``cli serve`` builds the topology from them; the IN-PROCESS serve
    stage cannot (one process, no row-queue), so it surfaces and warns.
    The transport/role choice sets (and the standby boolean parse) are
    pinned == ``serve.netqueue.SERVE_TRANSPORTS`` / ``SERVE_ROLES`` ==
    the ``cli serve`` parser choices by tests/test_netqueue.py. Same
    malformed-degrades contract: a typo'd value is a warning and the
    default, never a crash-looping pod."""
    import os

    # choice sets hardcoded to keep this import-light (the same reason
    # the cli parser hardcodes them); the guard test pins all three
    transports = ("shm", "tcp", "unix")
    roles = ("auto", "frontend", "dispatcher")
    transport = os.environ.get("BODYWORK_TPU_SERVE_TRANSPORT", "").strip()
    if transport and transport not in transports:
        log.warning(
            f"ignoring BODYWORK_TPU_SERVE_TRANSPORT={transport!r} "
            f"(expected one of {transports})"
        )
        transport = ""
    role = os.environ.get("BODYWORK_TPU_SERVE_ROLE", "").strip()
    if role and role not in roles:
        log.warning(
            f"ignoring BODYWORK_TPU_SERVE_ROLE={role!r} "
            f"(expected one of {roles})"
        )
        role = ""
    addr = os.environ.get("BODYWORK_TPU_DISPATCHER_ADDR", "").strip() or None
    raw_standby = os.environ.get(
        "BODYWORK_TPU_SERVE_STANDBY", ""
    ).strip().lower()
    standby = raw_standby in ("1", "true", "yes", "on")
    if raw_standby and not standby and raw_standby not in (
        "0", "false", "no", "off"
    ):
        log.warning(
            f"ignoring BODYWORK_TPU_SERVE_STANDBY={raw_standby!r} "
            "(expected a boolean like 1/0/true/false)"
        )
    return transport or "shm", addr, role or "auto", standby


def serve_stage(
    ctx: StageContext,
    host: str = "127.0.0.1",
    port: int = 0,
    buckets: tuple[int, ...] | None = None,
    replicas: int = 1,
    watch_interval_s: float | None = None,
    engine: str = "auto",
    server_engine: str | None = None,
    max_pending: int | None = None,
    retry_after_max_s: float | None = None,
    mesh_data: int | None = None,
    mesh_model: int | None = None,
    batch_window_ms: float | None = None,
    batch_max_rows: int | None = None,
    tuned_config: str | None = None,
) -> "ServiceHandle":  # noqa: F821
    """Load the latest model into device HBM and start the scoring service
    on a background thread (reference stage 2). Returns the handle; the
    runner keeps it alive for the rest of the day and tears it down at
    day end (the k8s deployment path instead keeps it up until re-deploy).

    ``buckets`` narrows the predictor's compiled shape set (each warmed
    bucket costs one device dispatch at startup) — the pipeline spec sets it
    to match the tester's request sizes.

    ``replicas > 1`` (the runner passes the spec's count — reference
    ``bodywork.yaml:40``) serves through N independent app instances behind
    a round-robin front, so multi-replica semantics are exercised locally,
    not just in emitted Deployment YAML. Replicas share the HBM-resident
    params (read-only), like the reference's replicas share the S3
    artefact.

    ``engine`` selects the prediction engine exactly as ``cli serve
    --engine`` does ("auto" picks the Pallas kernel only in its winning
    regime and resolves to the plain XLA apply everywhere else, so the
    parity workloads are unchanged); a non-default predictor instance is
    shared read-only across the replicas, the same sharing the hot-reload
    watcher applies on swap.

    ``server_engine``/``max_pending``/``retry_after_max_s`` pick the
    HTTP front-end and admission budget (``serve.server.SERVER_ENGINES``
    / ``serve.admission``), defaulting from the pod environment
    (:func:`_serve_env_knobs` — the knobs the k8s serve Deployment
    materialises) so a deployed service switches engines without a
    spec change. One admission controller is shared across the replica
    apps: they share the listen port, so they share the backpressure
    boundary.

    ``mesh_data``/``mesh_model`` shard the serving forward pass over a
    ``data x model`` device mesh (``serve.server.build_predictor`` —
    MLP weights Megatron-split, request rows data-split, programs
    AOT-cached per mesh), again defaulting from the pod environment so
    a deployed service scales onto more chips with one
    ``kubectl set env``.

    ``batch_window_ms``/``batch_max_rows`` opt the stage's replica apps
    into request coalescing, and ``tuned_config`` names a tuned
    serving-config document (``cli tune``'s output; ``"latest"`` or a
    ``tuning/`` key) whose fitted values fill every knob left unset —
    all three default from the pod environment
    (:func:`_serve_tuned_env_knobs`); explicit spec args win, then the
    per-knob env vars, then the tuned document, then the built-in
    defaults, and a malformed document degrades to defaults instead of
    crash-looping the pod (``tune/config.py``)."""
    from bodywork_tpu.models.checkpoint import load_model
    from bodywork_tpu.serve import ServiceHandle, create_app

    # Resolve WHAT to serve through the registry when one exists (the
    # production alias — only gate-promoted checkpoints take traffic;
    # bodywork_tpu.registry), falling back to the newest date-keyed
    # checkpoint on a registry-less store (original behavior,
    # byte-identical). Load the artefact WITHOUT the host->device
    # transfer first: if the in-process train stage produced this exact
    # checkpoint this day, its params are already resident in HBM —
    # verify the artefact bytes match the in-memory copy and reuse it,
    # saving the re-upload round-trip. (The artefact is still read and
    # remains the source of truth: any mismatch falls back to serving
    # exactly what the store holds.)
    from bodywork_tpu.models.checkpoint import resolve_serving_key

    served_key, served_source = resolve_serving_key(ctx.store)
    model, model_date = load_model(ctx.store, served_key, device=False)
    reused = False
    # snapshot: concurrent step siblings may insert results mid-iteration
    for result in list(ctx.stage_results.values()):
        candidate = getattr(result, "model", None)
        if (
            candidate is not None
            and getattr(candidate, "params", None) is not None
            and type(candidate) is type(model)
            and _params_equal(candidate.host_params(), model._host_params)
        ):
            model = candidate
            reused = True
            break
    if not reused:
        import jax

        model.params = jax.device_put(model.params)
    from bodywork_tpu.serve.server import (
        SERVER_ENGINES,
        build_admission,
        build_serving_predictor,
    )

    (env_engine, env_max_pending, env_retry_max, env_dtype,
     env_mesh_data, env_mesh_model) = _serve_env_knobs()
    if server_engine is None:
        server_engine = env_engine
    if server_engine not in SERVER_ENGINES:
        raise ValueError(
            f"unknown server engine {server_engine!r}; "
            f"expected one of {SERVER_ENGINES}"
        )
    if max_pending is None:
        max_pending = env_max_pending
    if retry_after_max_s is None:
        retry_after_max_s = env_retry_max
    if mesh_data is None:
        mesh_data = env_mesh_data
    if mesh_model is None:
        mesh_model = env_mesh_model
    env_frontends = _serve_fleet_env_knobs()
    if env_frontends:
        log.warning(
            f"BODYWORK_TPU_FRONTENDS={env_frontends} selects the "
            "disaggregated process fleet (`cli serve --frontends`); "
            "the in-process serve stage runs one process and ignores it"
        )
    env_transport, _env_addr, env_role, _env_standby = (
        _serve_transport_env_knobs()
    )
    if env_transport != "shm" or env_role != "auto":
        log.warning(
            f"BODYWORK_TPU_SERVE_TRANSPORT={env_transport!r} / "
            f"BODYWORK_TPU_SERVE_ROLE={env_role!r} select the cross-host "
            "disaggregated split (`cli serve --transport/--role`); the "
            "in-process serve stage runs one process and ignores them"
        )
    # coalescer/bucket/tuned-config knobs: spec args > per-knob env >
    # tuned document > built-in defaults (tune/config.py)
    env_window, env_max_rows, env_buckets, env_tuned = \
        _serve_tuned_env_knobs()
    if batch_window_ms is None:
        batch_window_ms = env_window
    if batch_max_rows is None:
        batch_max_rows = env_max_rows
    if buckets is None and env_buckets:
        buckets = env_buckets
    if tuned_config is None:
        tuned_config = env_tuned
    tuned_digest = None
    if tuned_config:
        from bodywork_tpu.tune.config import resolve_serving_knobs

        resolved = resolve_serving_knobs(
            ctx.store, tuned_config,
            batch_window_ms=batch_window_ms,
            batch_max_rows=batch_max_rows,
            buckets=tuple(buckets) if buckets else None,
            max_pending=max_pending,
        )
        batch_window_ms = resolved.batch_window_ms
        batch_max_rows = resolved.batch_max_rows
        buckets = resolved.buckets
        max_pending = resolved.max_pending
        tuned_digest = resolved.tuned_digest
    admission = build_admission(server_engine, max_pending, retry_after_max_s)
    # dtype + mesh from the pod env (BODYWORK_TPU_SERVE_DTYPE /
    # BODYWORK_TPU_MESH_DATA / BODYWORK_TPU_MESH_MODEL): a quantized
    # choice runs the shadow quality gate before it may serve, a mesh
    # choice shards the forward pass, exactly as `cli serve` does — the
    # defaults are byte-identical to the pre-knob behaviour
    predictor, _served_dtype = build_serving_predictor(
        ctx.store, model, mesh_data, engine,
        buckets=tuple(buckets) if buckets else None,
        dtype=env_dtype,
        mesh_model=mesh_model or 1,
    )
    # warmup itself skips shapes already dispatched this process, and only
    # syncs when something new was dispatched — so the persistent day-loop
    # pays the error-surfacing sync exactly once (day 1), one-shot pods
    # always (device faults fail startup, not requests)
    from bodywork_tpu.serve.server import _registry_bounds

    model_bounds = _registry_bounds(ctx.store, served_key)
    apps = [
        create_app(
            model,
            model_date,
            buckets=tuple(buckets) if buckets else None,
            predictor=predictor,
            model_key=served_key,
            model_source=served_source,
            # ONE controller shared across replica apps: they share the
            # listen port, so they share the backpressure boundary
            admission=admission,
            model_bounds=model_bounds,
            # each replica app owns its coalescer, exactly as each
            # multiproc worker does
            batch_window_ms=batch_window_ms,
            batch_max_rows=batch_max_rows,
        )
        for _ in range(max(replicas, 1))
    ]
    for app in apps:
        app.tuned_config_digest = tuned_digest
    if server_engine == "aio":
        # the asyncio front-end round-robins replica apps natively
        from bodywork_tpu.serve.aio import AioServiceHandle

        handle = AioServiceHandle(apps, host=host, port=port)
    else:
        from bodywork_tpu.serve.server import RoundRobinApp

        front = RoundRobinApp(apps) if len(apps) > 1 else apps[0]
        handle = ServiceHandle(front, host=host, port=port)
    if watch_interval_s:
        # hot reload (beyond-parity): the deployed service lives across
        # days, swapping in each retrain's checkpoint instead of being
        # re-rolled per day like the reference's stage 2. The SLO
        # watchdog rides the same loop, closing the canary release loop
        # (ops/slo.py; breach thresholds from the pod env knobs).
        from bodywork_tpu.ops.slo import SloWatchdog, policy_from_env
        from bodywork_tpu.serve.reload import CheckpointWatcher

        watchdog = SloWatchdog(ctx.store, apps, policy=policy_from_env())
        watcher = CheckpointWatcher(
            apps, ctx.store, poll_interval_s=watch_interval_s,
            served_key=served_key, engine=engine,
            mesh_data=mesh_data, mesh_model=mesh_model or 1,
            # the spec's explicit narrowing must survive engine-changing
            # swaps (the watcher only re-applies engine default buckets
            # when the caller never narrowed them)
            buckets=tuple(buckets) if buckets else None,
            slo_watchdog=watchdog,
        )
        watcher.start()
        handle.add_cleanup(watcher.stop)
    handle.start()
    handle.replica_apps = apps
    return handle


def test_stage(
    ctx: StageContext,
    mode: str = "batch",
    service_stage: str = "stage-2-serve-model",
    max_rows: int | None = None,
    batch_size: int = 512,
):
    """Score the latest dataset through the live service and persist drift
    metrics (reference stage 4)."""
    from bodywork_tpu.monitor import (
        HttpScoringClient,
        InProcessScoringClient,
        run_service_test,
        scoring_endpoint,
    )

    if ctx.scoring_url is not None:
        client = HttpScoringClient(scoring_endpoint(ctx.scoring_url, mode))
    elif service_stage in ctx.services:
        client = InProcessScoringClient(ctx.services[service_stage].app)
    else:
        raise RuntimeError(
            f"test_stage needs a scoring_url or a running service "
            f"{service_stage!r} in the context"
        )
    return run_service_test(
        ctx.store, client, mode=mode, max_rows=max_rows, batch_size=batch_size
    )
