"""Model registry: gated promotion, shadow evaluation, one-op rollback.

The release-management layer between training and serving (docs/REGISTRY.md):
training registers candidates, the gate engine promotes or rejects them,
serving resolves the ``production`` alias, and rollback is one
compare-and-swap flip back to ``previous``.
"""
from bodywork_tpu.registry.gates import GateDecision, GatePolicy, evaluate_candidate
from bodywork_tpu.registry.manager import (
    ModelRegistry,
    PromotionConflict,
    RegistryError,
)
from bodywork_tpu.registry.records import (
    RegistryCorrupt,
    read_aliases,
    register_candidate,
    registry_exists,
    resolve_alias,
)
from bodywork_tpu.registry.shadow import shadow_evaluate

__all__ = [
    "GateDecision",
    "GatePolicy",
    "ModelRegistry",
    "PromotionConflict",
    "RegistryCorrupt",
    "RegistryError",
    "evaluate_candidate",
    "read_aliases",
    "register_candidate",
    "registry_exists",
    "resolve_alias",
    "shadow_evaluate",
]
