"""Model registry: gated promotion, shadow evaluation, one-op rollback.

The release-management layer between training and serving (docs/REGISTRY.md):
training registers candidates, the gate engine promotes or rejects them,
serving resolves the ``production`` alias, and rollback is one
compare-and-swap flip back to ``previous``. The live half of the loop is
the CANARY slot on the same alias document: ``canary_start`` routes a
seeded fraction of real traffic to a candidate, the SLO watchdog
(``bodywork_tpu.ops.slo``) measures it against production, and
``canary_abort``/``canary_promote`` end the experiment in one CAS each.
"""
from bodywork_tpu.registry.gates import GateDecision, GatePolicy, evaluate_candidate
from bodywork_tpu.registry.manager import (
    CANARY_ACTION_METHODS,
    CANARY_ACTIONS,
    ModelRegistry,
    PromotionConflict,
    RegistryError,
    RollbackBlocked,
)
from bodywork_tpu.registry.records import (
    RegistryCorrupt,
    read_aliases,
    register_candidate,
    registry_exists,
    resolve_alias,
    resolve_canary,
)
from bodywork_tpu.registry.shadow import shadow_evaluate

__all__ = [
    "CANARY_ACTION_METHODS",
    "CANARY_ACTIONS",
    "GateDecision",
    "GatePolicy",
    "ModelRegistry",
    "PromotionConflict",
    "RegistryCorrupt",
    "RegistryError",
    "RollbackBlocked",
    "evaluate_candidate",
    "read_aliases",
    "register_candidate",
    "registry_exists",
    "resolve_alias",
    "resolve_canary",
    "shadow_evaluate",
]
