"""Config lifecycle events: the tuned-config release ledger.

The paper's lifecycle thesis — train, serve, drift, test, repeat —
applies to CONFIGS exactly as the registry already applies it to
models: a tuned config that goes live is a release, and a release needs
an authoritative "what is active, what preceded it, what happened"
document with the same write discipline as the model alias ledger
(``registry/records.py``). This module is that document for the online
tuning control plane (``tune/online.py``):

- **The config log** ``tuning/config-log.json`` — a live CAS-mutated
  pointer (no embedded date; invisible to ``history``/``latest`` like
  ``registry/aliases.json`` and the trainstate doc). It carries the
  ACTIVE tuned config (key + digest + the exact knobs applied + the
  pre-apply baseline window), the PREVIOUS one (the revert target), a
  monotonically increasing ``rev``, and a bounded applied/reverted
  event history ``cli tune status`` renders.
- **Write discipline**: mutated EXCLUSIVELY through
  ``put_bytes_if_match``. Each lifecycle transition (apply, revert) is
  EXACTLY ONE CAS — the same budget the model canary machinery pins
  for abort/promote — and a lost race raises
  :class:`ConfigLogConflict` instead of retrying: a concurrent
  controller already acted, and the loser's next poll re-reads truth.
- **Revert without re-reads**: entries embed the applied ``knobs``
  verbatim, so a revert re-applies the previous knob VALUES directly —
  it cannot be confused by the previous document having been
  overwritten (date-keyed tuned configs are re-fit in place on a
  same-day refit).

Corrupt-read handling mirrors the alias document's strict side: the
log names which knobs are live in the fleet, so a corrupt log raises
:class:`ConfigLogCorrupt` (``cli tune status`` exits 1 on it) rather
than silently reading as "nothing applied".
"""
from __future__ import annotations

import json

from bodywork_tpu.store.base import ArtefactStore, CasConflict
from bodywork_tpu.store.schema import CONFIG_LOG_KEY
from bodywork_tpu.utils.integrity import stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("registry.configlog")

CONFIG_LOG_SCHEMA = "bodywork_tpu.config_log/1"

#: bounded event history: the log is a live pointer, not an archive —
#: the flight recorder and the tuned documents themselves carry the
#: deep evidence
MAX_HISTORY = 50


class ConfigLogCorrupt(RuntimeError):
    """The config log exists but fails validation. Callers must NOT
    treat this as "nothing applied" — the knobs it named may be live in
    the fleet; surface the corruption instead (``cli tune status``
    exits 1)."""


class ConfigLogConflict(RuntimeError):
    """A concurrent controller won the CAS race for this lifecycle
    transition. Deliberately NOT retried inside this module: each
    transition's budget is exactly one CAS, and the loser's next poll
    re-reads the document another writer just made true."""


def _count_event(event: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_registry_config_events_total",
        "Tuned-config lifecycle transitions recorded in the config log",
    ).inc(event=event)


def _entry(key: str, digest: str, knobs: dict, baseline: dict | None) -> dict:
    return {
        "key": key,
        "digest": digest,
        "knobs": dict(knobs),
        "baseline": dict(baseline) if baseline else None,
    }


def read_config_log(store: ArtefactStore, with_token: bool = False):
    """The config log (validated), or None when absent. ``with_token``
    returns ``(doc, version_token)`` with the token read BEFORE the
    payload — the registry alias reader's CAS-safety ordering. Raises
    :class:`ConfigLogCorrupt` when the document exists but fails
    schema/digest validation."""
    token = store.version_token(CONFIG_LOG_KEY)
    if token is None and not store.exists(CONFIG_LOG_KEY):
        return (None, None) if with_token else None
    try:
        raw = store.get_bytes(CONFIG_LOG_KEY)
        doc = json.loads(raw.decode("utf-8"))
    except Exception as exc:
        raise ConfigLogCorrupt(
            f"config log {CONFIG_LOG_KEY!r} unreadable: {exc!r}"
        )
    if (
        not isinstance(doc, dict)
        or doc.get("schema") != CONFIG_LOG_SCHEMA
        or verify_doc(doc) is False
        or not isinstance(doc.get("history"), list)
    ):
        raise ConfigLogCorrupt(
            f"config log {CONFIG_LOG_KEY!r} fails schema/doc-digest "
            "validation"
        )
    return (doc, token) if with_token else doc


def _write(store: ArtefactStore, doc: dict, expected_token) -> None:
    """The ONE CAS write every lifecycle transition funnels through."""
    assert doc.get("schema") == CONFIG_LOG_SCHEMA, doc
    try:
        store.put_bytes_if_match(
            CONFIG_LOG_KEY,
            json.dumps(
                stamp_doc(doc), sort_keys=True, indent=1
            ).encode("utf-8"),
            expected_token,
        )
    except CasConflict as exc:
        raise ConfigLogConflict(
            f"config log CAS lost ({exc}); a concurrent controller "
            "acted — re-read on the next poll"
        ) from exc


def record_config_applied(
    store: ArtefactStore,
    key: str,
    digest: str,
    knobs: dict,
    baseline: dict | None = None,
    reason: str = "drift_refit",
) -> dict:
    """Record that a tuned config went LIVE: the current active entry
    (if any) becomes the revert target, ``key``/``digest``/``knobs``
    become active with their pre-apply ``baseline`` window attached
    (what the guard verdict compares the post-apply window against).
    Exactly one CAS; returns the written document."""
    doc, token = read_config_log(store, with_token=True)
    if doc is None:
        doc = {
            "schema": CONFIG_LOG_SCHEMA, "rev": 0,
            "active": None, "previous": None, "history": [],
        }
    rev = int(doc.get("rev", 0)) + 1
    new_doc = {
        "schema": CONFIG_LOG_SCHEMA,
        "rev": rev,
        "last_op": "applied",
        "active": _entry(key, digest, knobs, baseline),
        "previous": doc.get("active"),
        "history": (doc.get("history") or [])[-(MAX_HISTORY - 1):] + [{
            "event": "applied", "rev": rev, "key": key,
            "digest": digest, "reason": reason,
        }],
    }
    _write(store, new_doc, token)
    _count_event("applied")
    log.info(
        f"config log: applied {key} ({digest[:23]}…, rev {rev}, "
        f"{reason})"
    )
    return new_doc


def record_config_reverted(
    store: ArtefactStore,
    reason: str,
    flight_record: str | None = None,
) -> tuple[dict | None, dict]:
    """Record that the ACTIVE config was auto-reverted (the breach
    verdict's action): the previous entry becomes active again (None =
    back to built-in defaults / boot-time knobs), with the reverted
    config's key, digest, reason, and the flight-recorder dump key in
    the event. Exactly one CAS; returns ``(restored_entry_or_None,
    reverted_entry)``. Raises ``ValueError`` when nothing is active —
    a revert needs something to revert."""
    doc, token = read_config_log(store, with_token=True)
    if doc is None or not doc.get("active"):
        raise ValueError("config log has no active config to revert")
    reverted = doc["active"]
    restored = doc.get("previous")
    rev = int(doc.get("rev", 0)) + 1
    event = {
        "event": "reverted", "rev": rev, "key": reverted["key"],
        "digest": reverted["digest"], "reason": reason,
    }
    if flight_record:
        event["flight_record"] = flight_record
    new_doc = {
        "schema": CONFIG_LOG_SCHEMA,
        "rev": rev,
        "last_op": "reverted",
        "active": restored,
        # one level of undo, like the alias document's previous slot:
        # a revert consumes it (reverting back onto the config that
        # just breached would be a flap loop, not an undo)
        "previous": None,
        "history": (doc.get("history") or [])[-(MAX_HISTORY - 1):] + [event],
    }
    _write(store, new_doc, token)
    _count_event("reverted")
    log.warning(
        f"config log: REVERTED {reverted['key']} "
        f"({reverted['digest'][:23]}…, rev {rev}): {reason}"
    )
    return restored, reverted
