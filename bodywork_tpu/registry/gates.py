"""Promotion-gate engine: decides candidate-vs-production.

A freshly trained checkpoint is a *candidate* until this gate says
otherwise — the release-management step between ``train`` and ``serve``
that the reference pipeline (serve-whatever-is-newest) lacks. The gate
reads three signals, cheapest first:

1. **Candidate model-metrics** (the train stage's held-out MAPE /
   r_squared CSV): absolute sanity — metrics must exist, parse, and be
   finite; correlation over ``min_r2`` (and MAPE under ``max_mape``
   when that opt-in ceiling is set — measured healthy days reach
   MAPE≈52 when the drift sinusoid pushes labels through zero, so an
   absolute MAPE ceiling is OFF by default like every other MAPE rule
   in this codebase). A candidate with no readable quality signal
   NEVER promotes.
2. **Comparison against production** (the current production record's
   metrics). The DEFAULT relative check is the bounded correlation
   drop — candidate ``r_squared`` may not fall more than
   ``max_r2_drop_vs_production`` below production's — because the
   day-level MAPE ratio is tail-noise-dominated for label
   distributions touching zero (the same measured pathology that keeps
   ``report --mape-ratio`` opt-in: a flat-control day exceeded 5.8x its
   train MAPE with no drift at all — ``monitor/tester.py``). The MAPE
   ratio (``max_mape_vs_production`` x + ``mape_slack`` absolute) is
   therefore OPT-IN, for label distributions bounded away from zero. A
   degradation is overridden ONLY when the drift test-metrics say
   production itself has drifted (the live residual-bias rule from
   :func:`bodywork_tpu.monitor.detect_drift` over ``drift_window``
   days) — a stale production model must not be able to veto every
   fresh retrain forever.
3. **Optional shadow evaluation** (``shadow_days > 0``): score the
   candidate in-process over the last K days of data next to production
   (:mod:`bodywork_tpu.registry.shadow` — no live traffic touched) and
   block when the prediction deltas exceed
   ``shadow_max_mean_abs_delta``, or when the candidate's shadow-window
   MAPE degrades past the same ratio used in check 2.

Decisions are pure functions of artefact bytes (no wall clock, no
randomness), so the chaos harness's byte-identical guarantee holds over
the decision events the manager appends to registry records.
"""
from __future__ import annotations

import csv
import dataclasses
import io
import math
from datetime import date

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore
from bodywork_tpu.utils.logging import get_logger

log = get_logger("registry.gates")

DECISION_SCHEMA = "bodywork_tpu.registry_decision/1"


@dataclasses.dataclass
class GatePolicy:
    """Promotion-gate knobs (docs/REGISTRY.md §3). Defaults follow the
    codebase's calibration findings (``monitor/tester.py``,
    ``cli report --mape-ratio``): correlation-based checks are the
    bounded, calibrated signal; every MAPE-based rule is OPT-IN because
    day-level MAPE is unbounded tail noise when labels touch zero
    (healthy days measured at MAPE≈52 under the drift sinusoid)."""

    #: OPT-IN absolute ceiling on the candidate's held-out MAPE (None =
    #: off; only for label distributions bounded away from zero)
    max_mape: float | None = None
    #: absolute floor on the candidate's held-out score/label
    #: correlation — catches uncorrelated-garbage fits outright
    min_r2: float = 0.2
    #: DEFAULT relative check: candidate r_squared may drop at most this
    #: far below production's (bounded statistic, robust to the
    #: near-zero-label tails that make day-level MAPE ratios noise)
    max_r2_drop_vs_production: float = 0.2
    #: OPT-IN relative check (None = off, the default — see the module
    #: docstring's measured MAPE-ratio pathology): candidate MAPE may be
    #: at most this multiple of production's…
    max_mape_vs_production: float | None = None
    #: …plus this absolute slack (two tiny MAPEs must not trip the
    #: ratio); also the slack under the shadow-window MAPE ratio
    mape_slack: float = 0.05
    #: shadow-window MAPE ratio (shadow scores BOTH models on the SAME
    #: rows, so the ratio is a fair same-denominator comparison there)
    shadow_max_mape_ratio: float = 1.5
    #: trailing days of drift test-metrics consulted for the
    #: production-has-drifted override of the degradation check
    drift_window: int = 7
    #: shadow evaluation over the last K dataset days; 0 = off
    shadow_days: int = 0
    #: block when the candidate-vs-production mean |prediction delta|
    #: over the shadow window exceeds this (None = record, never block)
    shadow_max_mean_abs_delta: float | None = None
    #: shadow window (dataset days) for the QUANTIZED-serving quality
    #: gate (``serve --dtype {bfloat16,int8}``): the quantized predictor
    #: scores the last K days next to the f32 predictor of the SAME
    #: checkpoint, and may only take traffic when the delta passes the
    #: same ceilings the candidate shadow check uses
    #: (``shadow_max_mape_ratio`` + ``mape_slack``,
    #: ``shadow_max_mean_abs_delta``) — one quality-gate rulebook, one
    #: new knob (:func:`evaluate_quantization`)
    quantized_shadow_days: int = 3


@dataclasses.dataclass
class GateDecision:
    model_key: str
    promote: bool
    checks: list[dict]
    reasons: list[str]
    day: date | None = None
    shadow: dict | None = None

    def to_event(self) -> dict:
        """The decision as a record-history event (deterministic JSON)."""
        return {
            "event": "gate_decision",
            "schema": DECISION_SCHEMA,
            "day": str(self.day) if self.day else None,
            "promote": self.promote,
            "checks": self.checks,
            "reasons": self.reasons,
            **({"shadow": self.shadow} if self.shadow is not None else {}),
        }


def read_model_metrics(store: ArtefactStore, metrics_key: str | None) -> dict | None:
    """Parse the one-row train-metrics CSV (``date,MAPE,r_squared,
    max_residual``) with the stdlib csv module — the gate runs inside
    serving-adjacent processes and must not pull pandas into their
    closure. None when absent/unparseable."""
    if not metrics_key:
        return None
    try:
        text = store.get_bytes(metrics_key).decode("utf-8")
    except (ArtefactNotFound, UnicodeDecodeError):
        return None
    try:
        rows = list(csv.DictReader(io.StringIO(text)))
    except csv.Error:
        return None
    if not rows:
        return None
    row = rows[0]
    try:
        return {
            "MAPE": float(row["MAPE"]),
            "r_squared": float(row["r_squared"]),
        }
    except (KeyError, TypeError, ValueError):
        return None


def _production_drifted(store: ArtefactStore, window: int) -> bool:
    """The live drift verdict over the trailing window (the calibrated
    bias rule) — pandas imported lazily, only on the degradation-
    override path."""
    try:
        from bodywork_tpu.monitor import detect_drift, drift_report

        report = drift_report(store)
        if report.empty:
            return False
        return bool(detect_drift(report, window=window)["drifted"])
    except Exception as exc:  # a broken report must not wedge the gate
        log.warning(f"drift check failed (treating as not-drifted): {exc!r}")
        return False


def evaluate_quantization(
    report: dict, policy: GatePolicy | None = None
) -> tuple[bool, str]:
    """The quantized-serving quality verdict over a shadow-comparison
    report (``registry.shadow.shadow_compare``: quantized = candidate,
    f32 = production — the SAME checkpoint, two dtypes). Applies exactly
    the candidate shadow check's ceilings (``shadow_max_mape_ratio`` +
    ``mape_slack``, ``shadow_max_mean_abs_delta``): the question "may
    this lower-precision variant answer for that model" IS the shadow
    question, so it gets the shadow rulebook, not a new one. Returns
    ``(ok, detail)``; the serving boot path keeps f32 on a False."""
    policy = policy or GatePolicy()
    ok = True
    detail = (
        f"mean|Δ|={report['mean_abs_delta']:.6f} over "
        f"{report['days']} day(s)/{report['rows']} rows"
    )
    if (
        policy.shadow_max_mean_abs_delta is not None
        and report["mean_abs_delta"] > policy.shadow_max_mean_abs_delta
    ):
        ok = False
        detail += f" exceeds {policy.shadow_max_mean_abs_delta}"
    q_mape = report.get("candidate_mape")
    f32_mape = report.get("production_mape")
    if (
        q_mape is not None
        and f32_mape is not None
        and math.isfinite(q_mape)
        and math.isfinite(f32_mape)
    ):
        ceiling = f32_mape * policy.shadow_max_mape_ratio + policy.mape_slack
        if q_mape > ceiling:
            ok = False
            detail += (
                f"; quantized shadow MAPE {q_mape:.6f} exceeds ceiling "
                f"{ceiling:.6f} (f32 {f32_mape:.6f})"
            )
    else:
        # a non-finite quantized MAPE is a broken variant, full stop
        if q_mape is None or not math.isfinite(q_mape):
            ok = False
            detail += f"; quantized shadow MAPE unusable ({q_mape})"
    return ok, detail


def evaluate_candidate(
    store: ArtefactStore,
    candidate: dict,
    production: dict | None,
    policy: GatePolicy | None = None,
    day: date | None = None,
) -> GateDecision:
    """Run the gate checks for one candidate record against the current
    production record (None = bootstrap: no production yet, only the
    absolute checks apply). Returns the full decision — the manager
    applies it (promote / reject) and appends it to the record."""
    policy = policy or GatePolicy()
    checks: list[dict] = []
    reasons: list[str] = []
    promote = True
    shadow_report = None

    def check(name: str, ok: bool, detail: str) -> bool:
        checks.append({"name": name, "ok": bool(ok), "detail": detail})
        if not ok:
            reasons.append(f"{name}: {detail}")
        return ok

    cand_metrics = read_model_metrics(store, candidate.get("metrics_key"))
    if cand_metrics is None or not all(
        math.isfinite(v) for v in cand_metrics.values()
    ):
        check(
            "candidate-metrics", False,
            "no readable finite train metrics for the candidate",
        )
        return GateDecision(
            candidate["model_key"], False, checks, reasons, day=day
        )
    mape, r2 = cand_metrics["MAPE"], cand_metrics["r_squared"]
    absolute_ok = r2 >= policy.min_r2 and (
        policy.max_mape is None or mape <= policy.max_mape
    )
    promote &= check(
        "candidate-metrics",
        absolute_ok,
        f"r_squared={r2:.6f} (min {policy.min_r2}), MAPE={mape:.6f} "
        + (
            f"(max {policy.max_mape})"
            if policy.max_mape is not None
            else "(no ceiling: MAPE rules are opt-in)"
        ),
    )

    prod_metrics = (
        read_model_metrics(store, production.get("metrics_key"))
        if production is not None
        else None
    )
    if production is not None and prod_metrics is None:
        # not the candidate's fault, so it does not block promotion —
        # but the audit trail must show the comparison was SKIPPED, not
        # passed (an operator reading the decision event would otherwise
        # assume the relative check ran)
        check(
            "vs-production", True,
            "production train metrics unreadable; relative comparison "
            "SKIPPED (absolute checks only)",
        )
    if prod_metrics is not None:
        degraded: list[str] = []
        compared = False  # did ANY relative comparison actually run?
        prod_r2 = prod_metrics["r_squared"]
        if math.isfinite(prod_r2):
            compared = True
            r2_floor = prod_r2 - policy.max_r2_drop_vs_production
            if r2 < r2_floor:
                degraded.append(
                    f"r_squared={r2:.6f} below floor {r2_floor:.6f} "
                    f"(production {prod_r2:.6f})"
                )
        if (
            policy.max_mape_vs_production is not None
            and math.isfinite(prod_metrics["MAPE"])
        ):
            compared = True
            ceiling = (
                prod_metrics["MAPE"] * policy.max_mape_vs_production
                + policy.mape_slack
            )
            if mape > ceiling:
                degraded.append(
                    f"MAPE={mape:.6f} exceeds ceiling {ceiling:.6f} "
                    f"(production {prod_metrics['MAPE']:.6f})"
                )
        if not compared:
            # production's metrics read but every compared figure is
            # non-finite (e.g. a hand-promoted model with r_squared=nan):
            # same audit contract as the unreadable case above — the
            # trail must say SKIPPED, not claim a comparison that never
            # ran passed
            check(
                "vs-production", True,
                f"production metrics non-finite (r_squared={prod_r2}); "
                "relative comparison SKIPPED (absolute checks only)",
            )
        elif not degraded:
            check(
                "vs-production", True,
                f"r_squared={r2:.6f} vs production {prod_r2:.6f} "
                f"(max drop {policy.max_r2_drop_vs_production})",
            )
        elif _production_drifted(store, policy.drift_window):
            # production is stale per the live drift signal: a fresh
            # candidate wins even though its held-out metrics look
            # worse — the held-out set itself has drifted under
            # production
            check(
                "vs-production", True,
                f"{'; '.join(degraded)} — but production drifted over "
                f"the last {policy.drift_window} day(s); promoting "
                "fresh candidate",
            )
        else:
            promote &= check(
                "vs-production", False,
                f"{'; '.join(degraded)} and production shows no live drift",
            )

    if policy.shadow_days > 0 and production is not None:
        from bodywork_tpu.registry.shadow import shadow_evaluate

        try:
            shadow_report = shadow_evaluate(
                store,
                candidate["model_key"],
                production["model_key"],
                days=policy.shadow_days,
            )
        except Exception as exc:
            promote &= check(
                "shadow", False, f"shadow evaluation failed: {exc!r}"
            )
        else:
            ok = True
            detail = (
                f"mean|Δ|={shadow_report['mean_abs_delta']:.6f} over "
                f"{shadow_report['days']} day(s)/{shadow_report['rows']} rows"
            )
            if (
                policy.shadow_max_mean_abs_delta is not None
                and shadow_report["mean_abs_delta"]
                > policy.shadow_max_mean_abs_delta
            ):
                ok = False
                detail += (
                    f" exceeds {policy.shadow_max_mean_abs_delta}"
                )
            cand_shadow = shadow_report.get("candidate_mape")
            prod_shadow = shadow_report.get("production_mape")
            if (
                cand_shadow is not None
                and prod_shadow is not None
                and math.isfinite(cand_shadow)
                and math.isfinite(prod_shadow)
            ):
                shadow_ceiling = (
                    prod_shadow * policy.shadow_max_mape_ratio
                    + policy.mape_slack
                )
                if cand_shadow > shadow_ceiling:
                    ok = False
                    detail += (
                        f"; shadow MAPE {cand_shadow:.6f} exceeds "
                        f"ceiling {shadow_ceiling:.6f} "
                        f"(production {prod_shadow:.6f})"
                    )
            promote &= check("shadow", ok, detail)

    return GateDecision(
        candidate["model_key"], bool(promote), checks, reasons,
        day=day, shadow=shadow_report,
    )
