"""Registry operations: register / gate / promote / rollback / demote.

:class:`ModelRegistry` is the one mutation surface over the record and
alias artefacts (:mod:`bodywork_tpu.registry.records`). Every alias
mutation is ONE compare-and-swap of the alias document against the
token it was read under — two concurrent promoters cannot interleave:
the loser's CAS fails with a clean :class:`PromotionConflict` and the
document never holds a half-updated state. Rollback is the same single
CAS flipping ``production`` <-> ``previous`` — one operation, no
artefact copying, no deletion.

Record updates (status moves, decision events) happen AFTER the alias
CAS lands: records are the audit trail, the alias is the truth, and a
crash between the two leaves serving correct with a repairable ledger —
never the reverse.

Operations emit metrics:
``bodywork_tpu_registry_promotions_total{outcome=promoted|rejected|conflict}``
and ``bodywork_tpu_registry_rollbacks_total``.
"""
from __future__ import annotations

from datetime import date

from bodywork_tpu.registry import records as rec
from bodywork_tpu.registry.gates import GateDecision, GatePolicy, evaluate_candidate
from bodywork_tpu.store.base import ArtefactStore, CasConflict
from bodywork_tpu.utils.logging import get_logger

log = get_logger("registry.manager")


class RegistryError(RuntimeError):
    """A registry operation could not be applied (unknown model, nothing
    to roll back to, …) — a clean operator-facing error, not a crash."""


class PromotionConflict(RegistryError):
    """Another promoter's alias write landed first. The alias is intact
    (the CAS lost cleanly); re-read and retry if still relevant."""


class RollbackBlocked(RegistryError):
    """The restore target failed pre-verification: the ``previous``
    checkpoint is missing from the store, or its bytes no longer match
    the record's lineage digest. The alias is untouched — flipping it
    would point serving at garbage exactly when an operator is trying
    to recover, so the refusal is loud (its own `cli registry rollback`
    exit code) and leaves a ``rollback_refused`` lineage event."""


def _count_promotion(outcome: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_registry_promotions_total",
        "Registry promotion gate outcomes",
    ).inc(outcome=outcome)


def _count_rollback() -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_registry_rollbacks_total",
        "Registry rollbacks (production alias flipped back to previous)",
    ).inc()


def _count_rollback_refused(reason: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_registry_rollback_refusals_total",
        "Rollbacks refused because the restore target failed "
        "pre-verification, by reason",
    ).inc(reason=reason)


def _count_canary_event(event: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_registry_canary_events_total",
        "Canary lifecycle transitions (start/abort/promote/repair)",
    ).inc(event=event)


#: the canary lifecycle verbs — ONE list pinned three ways by a guard
#: test (tests/test_canary.py): ``cli registry canary <action>`` choices,
#: the :class:`ModelRegistry` methods in :data:`CANARY_ACTION_METHODS`,
#: and the states documented in docs/REGISTRY.md, so the CLI, the
#: manager API, and the docs cannot drift apart.
CANARY_ACTIONS = ("start", "stop", "promote", "status")
CANARY_ACTION_METHODS = {
    "start": "canary_start",
    "stop": "canary_abort",
    "promote": "canary_promote",
    "status": "canary_status",
}


class ModelRegistry:
    def __init__(self, store: ArtefactStore, policy: GatePolicy | None = None):
        self.store = store
        self.policy = policy or GatePolicy()

    # -- reads -------------------------------------------------------------

    def resolve(self, alias: str = "production") -> str | None:
        return rec.resolve_alias(self.store, alias)

    def records(self) -> list[dict]:
        return rec.list_records(self.store)

    def newest_candidate(self) -> dict | None:
        """The most recent record still in ``candidate`` status (date-key
        order — the thing the daily gate step adjudicates). Walks
        records NEWEST-first, loading lazily, and stops at the first
        ``production``/``archived`` record: candidates predating the
        current production are stale history the gate would never pick,
        so the daily gate reads O(1-2) records, not O(models-ever-
        trained) — that scan would grow by one store GET per day,
        forever."""
        from bodywork_tpu.store.schema import REGISTRY_RECORDS_PREFIX

        for key, _d in reversed(self.store.history(REGISTRY_RECORDS_PREFIX)):
            record = rec._validated_read(
                self.store, key, rec.RECORD_SCHEMA, "record"
            )
            if record is None:
                continue  # corrupt past budget: counted + flagged
            status = record.get("status")
            if status == "candidate":
                return record
            if status in ("production", "archived"):
                return None
        return None

    def production_record(self) -> dict | None:
        key = self.resolve("production")
        return rec.load_record(self.store, key) if key else None

    # -- mutations ---------------------------------------------------------

    def register(
        self,
        model_key: str,
        metrics_key: str | None = None,
        day: date | None = None,
    ) -> dict:
        return rec.register_candidate(
            self.store, model_key, metrics_key=metrics_key, day=day
        )

    def promote(
        self,
        model_key: str,
        day: date | None = None,
        reason: str = "promoted",
    ) -> dict:
        """Point ``production`` at ``model_key`` (one alias CAS; the old
        production becomes ``previous``). The model must be registered —
        promotion of an unknown checkpoint is refused, that is the whole
        point of the registry. Returns the new alias document."""
        record = rec.load_record(self.store, model_key)
        if record is None:
            raise RegistryError(
                f"cannot promote unregistered model {model_key!r}; "
                "register it first"
            )
        doc, token = rec.read_aliases(self.store, with_token=True)
        old_production = doc.get("production") if doc else None
        if old_production == model_key:
            # alias already points here — but REPAIR a ledger that
            # disagrees (e.g. a crash between a past alias CAS and its
            # record update, or a same-key re-register): the aliased
            # model's record must read "production"
            if record.get("status") != "production":
                rec.append_event(
                    self.store, model_key,
                    {"event": "promoted", "day": str(day) if day else None,
                     "reason": "repair: alias already points here"},
                    status="production",
                )
            log.info(f"{model_key} is already production; no-op")
            return doc
        new_doc = {
            "schema": rec.ALIAS_SCHEMA,
            "production": model_key,
            "previous": old_production,
            "rev": (doc.get("rev", 0) + 1) if doc else 1,
            "updated_day": str(day) if day else None,
            "last_op": "promote",
            # a live canary SURVIVES an ordinary promotion (its baseline
            # just changed; the watchdog keeps measuring) — unless the
            # promoted key IS the canary, which graduates the slot
            **{
                k: doc[k]
                for k in (rec.CANARY_DOC_KEYS if doc else ())
                if k in doc and doc.get("canary") != model_key
            },
        }
        try:
            rec.write_aliases(self.store, new_doc, token)
        except CasConflict as exc:
            _count_promotion("conflict")
            raise PromotionConflict(
                f"promotion of {model_key!r} lost the alias race: {exc}"
            ) from exc
        event_day = str(day) if day else None
        rec.append_event(
            self.store, model_key,
            {"event": "promoted", "day": event_day, "reason": reason,
             "replaced": old_production},
            status="production",
        )
        if old_production and old_production != model_key:
            rec.append_event(
                self.store, old_production,
                {"event": "superseded", "day": event_day,
                 "by": model_key},
                status="archived",
            )
        _count_promotion("promoted")
        log.info(
            f"promoted {model_key} to production "
            f"(previous: {old_production or 'none'})"
        )
        return new_doc

    def _verify_restorable(self, model_key: str, day: date | None) -> None:
        """Pre-verify a rollback's restore target BEFORE the alias CAS:
        the checkpoint must exist and its bytes must still match the
        record's lineage digest. A dangling or bit-rotted ``previous``
        rolled back blind puts a degraded (or unloadable) model live at
        the exact moment resilience machinery is being exercised — the
        refusal raises :class:`RollbackBlocked`, counts the reason, and
        leaves a ``rollback_refused`` event on the target's record so
        the ledger explains why production did not move."""
        reason = None
        if not self.store.exists(model_key):
            reason = "checkpoint_missing"
            detail = f"previous checkpoint {model_key!r} is missing"
        else:
            record = rec.load_record(self.store, model_key)
            expected = record.get("model_digest") if record else None
            if record is None:
                reason = "record_unreadable"
                detail = (
                    f"record for {model_key!r} is absent or corrupt; "
                    "cannot verify the checkpoint's lineage digest"
                )
            elif expected and rec.model_digest(
                self.store.get_bytes(model_key)
            ) != expected:
                reason = "digest_mismatch"
                detail = (
                    f"checkpoint {model_key!r} no longer matches its "
                    f"record digest {expected[:15]}… (at-rest corruption?)"
                )
        if reason is None:
            return
        _count_rollback_refused(reason)
        # best-effort lineage event: with the record itself unreadable
        # there is nowhere durable to write the refusal
        rec.append_event(
            self.store, model_key,
            {"event": "rollback_refused", "day": str(day) if day else None,
             "reason": reason},
        )
        log.error(f"rollback REFUSED ({reason}): {detail}")
        raise RollbackBlocked(detail)

    def rollback(self, day: date | None = None, reason: str = "rollback") -> dict:
        """ONE operation back to the previous production: a single CAS
        flipping the alias document's ``production`` <-> ``previous``.
        No artefacts move; the checkpoint watcher's next poll swaps the
        restored model back in. The restore target is pre-verified
        (exists + record digest matches) before the CAS —
        :meth:`_verify_restorable` — so a rollback can never land on a
        dangling or corrupt ``previous``."""
        doc, token = rec.read_aliases(self.store, with_token=True)
        if doc is None:
            raise RegistryError("no registry alias document; nothing to roll back")
        current, previous = doc.get("production"), doc.get("previous")
        if not previous:
            raise RegistryError(
                "no previous production recorded; nothing to roll back to"
            )
        self._verify_restorable(previous, day)
        new_doc = {
            "schema": rec.ALIAS_SCHEMA,
            "production": previous,
            "previous": current,
            "rev": doc.get("rev", 0) + 1,
            "updated_day": str(day) if day else None,
            "last_op": "rollback",
            # a live canary survives the flip unless the restored
            # production IS the canary key (the slot would point at the
            # model now serving 100% anyway)
            **{
                k: doc[k]
                for k in rec.CANARY_DOC_KEYS
                if k in doc and doc.get("canary") != previous
            },
        }
        try:
            rec.write_aliases(self.store, new_doc, token)
        except CasConflict as exc:
            raise PromotionConflict(
                f"rollback lost the alias race: {exc}"
            ) from exc
        event_day = str(day) if day else None
        rec.append_event(
            self.store, previous,
            {"event": "restored", "day": event_day, "reason": reason},
            status="production",
        )
        if current:
            rec.append_event(
                self.store, current,
                {"event": "rolled_back", "day": event_day, "reason": reason},
                status="rejected",
            )
        _count_rollback()
        log.info(f"rolled back production {current} -> {previous}")
        return new_doc

    def demote(
        self,
        model_key: str,
        day: date | None = None,
        reason: str = "demoted",
    ) -> dict:
        """Mark a non-production record ``rejected`` (a bad candidate an
        operator retires by hand). Demoting PRODUCTION is refused —
        that is what :meth:`rollback` is for (it also decides what
        serves next; demote must not leave the alias dangling)."""
        if self.resolve("production") == model_key:
            raise RegistryError(
                f"{model_key!r} is production; use rollback instead of demote"
            )
        record = rec.append_event(
            self.store, model_key,
            {"event": "demoted", "day": str(day) if day else None,
             "reason": reason},
            status="rejected",
        )
        if record is None:
            raise RegistryError(f"no registry record for {model_key!r}")
        return record

    # -- the canary lifecycle ----------------------------------------------
    #
    # A canary is a CAS-mutated slot on the SAME alias document that
    # already carries production/previous: the serving path routes a
    # seeded hash-of-request fraction of live traffic to it while the
    # SLO watchdog (ops/slo.py) measures both streams. Every lifecycle
    # transition is ONE compare-and-swap of the alias document — a
    # breaching canary is gone after exactly one CAS, and two concurrent
    # watchdogs (multi-worker serving) cannot double-apply an abort: the
    # loser gets a clean PromotionConflict and finds the slot already
    # cleared on re-read.

    def canary_state(self, doc: dict | None = None) -> dict | None:
        """The live canary's alias-side state, or None. Unlike
        :func:`~bodywork_tpu.registry.records.resolve_canary` this does
        NOT validate serveability — it reports what the slot says.
        ``doc`` lets a caller that already read the alias document skip
        a second (possibly torn-across-a-CAS) read."""
        if doc is None:
            doc = rec.read_aliases(self.store)
        if not doc or not doc.get("canary"):
            return None
        return {
            "key": doc.get("canary"),
            "fraction": doc.get("canary_fraction"),
            "seed": doc.get("canary_seed"),
            "day": doc.get("canary_day"),
        }

    def canary_start(
        self,
        model_key: str,
        fraction: float = 0.1,
        seed: int = 0,
        day: date | None = None,
    ) -> dict:
        """Open the live release loop: point the ``canary`` slot at a
        registered candidate so serving routes ``fraction`` of /score
        traffic to it (deterministically, by seeded request hash —
        ``serve.app.routes_to_canary``). Refused without a production
        baseline (nothing to fall back to or compare against), for the
        production key itself, for a gate-rejected record, and while
        another canary is live. One alias CAS."""
        if not 0.0 < fraction <= 1.0:
            raise RegistryError(
                f"canary fraction must be in (0, 1], got {fraction!r}"
            )
        record = rec.load_record(self.store, model_key)
        if record is None:
            raise RegistryError(
                f"cannot canary unregistered model {model_key!r}; "
                "register it first"
            )
        if record.get("status") == "rejected":
            raise RegistryError(
                f"{model_key!r} is gate-rejected; a rejected checkpoint "
                "must not take live traffic"
            )
        doc, token = rec.read_aliases(self.store, with_token=True)
        if doc is None or not doc.get("production"):
            raise RegistryError(
                "no production model; a canary needs a baseline to "
                "fall back to — promote one first"
            )
        if doc.get("production") == model_key:
            raise RegistryError(f"{model_key!r} already is production")
        if doc.get("canary"):
            raise RegistryError(
                f"a canary is already live ({doc['canary']!r}); stop it "
                "before starting another"
            )
        new_doc = {
            **{k: v for k, v in doc.items() if k not in rec.CANARY_DOC_KEYS},
            "rev": doc.get("rev", 0) + 1,
            "updated_day": str(day) if day else None,
            "last_op": "canary_start",
            "canary": model_key,
            "canary_fraction": float(fraction),
            "canary_seed": int(seed),
            "canary_day": str(day) if day else None,
        }
        try:
            rec.write_aliases(self.store, new_doc, token)
        except CasConflict as exc:
            raise PromotionConflict(
                f"canary start of {model_key!r} lost the alias race: {exc}"
            ) from exc
        rec.append_event(
            self.store, model_key,
            {"event": "canary_started", "day": str(day) if day else None,
             "fraction": float(fraction), "seed": int(seed)},
        )
        _count_canary_event("start")
        log.info(
            f"canary started: {model_key} at fraction {fraction} "
            f"(seed {seed})"
        )
        return new_doc

    def _canary_clear(
        self,
        last_op: str,
        event: str,
        day: date | None,
        reason: str,
        record_status: str | None,
        count_as: str,
    ) -> dict | None:
        """The shared canary-ending CAS: clear the slot in ONE alias
        write, then record the lineage event. Returns the new alias
        document, or None when no canary was live (idempotent — a
        concurrent watchdog may have cleared it first)."""
        doc, token = rec.read_aliases(self.store, with_token=True)
        if doc is None or not doc.get("canary"):
            return None
        canary_key = doc["canary"]
        new_doc = {
            **{k: v for k, v in doc.items() if k not in rec.CANARY_DOC_KEYS},
            "rev": doc.get("rev", 0) + 1,
            "updated_day": str(day) if day else None,
            "last_op": last_op,
        }
        try:
            rec.write_aliases(self.store, new_doc, token)
        except CasConflict as exc:
            raise PromotionConflict(
                f"{last_op} of {canary_key!r} lost the alias race: {exc}"
            ) from exc
        rec.append_event(
            self.store, canary_key,
            {"event": event, "day": str(day) if day else None,
             "reason": reason},
            status=record_status,
        )
        _count_canary_event(count_as)
        return new_doc

    def canary_abort(
        self,
        day: date | None = None,
        reason: str = "canary aborted",
    ) -> dict | None:
        """Retire the live canary in ONE CAS — the rollback primitive of
        the live release loop (the SLO watchdog's breach action, also
        ``cli registry canary stop``). Production never moved, so
        nothing is restored: the slot clears, 100% of traffic is back
        on production at the serving layer's next poll (the watchdog
        clears the in-process routing immediately), and the canary's
        record moves to ``rejected`` with the abort reason. Returns the
        new alias document, or None when no canary was live."""
        doc = self._canary_clear(
            "canary_abort", "canary_aborted", day, reason,
            record_status="rejected", count_as="abort",
        )
        if doc is not None:
            log.warning(f"canary ABORTED: {reason}")
        return doc

    def canary_repair(
        self,
        day: date | None = None,
        reason: str = "dangling canary slot",
    ) -> dict | None:
        """Clear a DANGLING canary slot (checkpoint deleted, record
        rejected — debris a crashed watchdog left). Same single-CAS
        shape as :meth:`canary_abort`, but the record keeps its status:
        the repair fixes the alias, it does not adjudicate the model."""
        doc = self._canary_clear(
            "canary_repair", "canary_repaired", day, reason,
            record_status=None, count_as="repair",
        )
        if doc is not None:
            log.warning(f"dangling canary slot repaired: {reason}")
        return doc

    def canary_promote(
        self,
        day: date | None = None,
        reason: str = "canary: survived SLO window healthy",
    ) -> dict:
        """Graduate the live canary to production in ONE CAS: the alias
        document simultaneously gains ``production = canary key``,
        demotes the old production to ``previous``, and clears the
        canary slot — there is no intermediate state where the canary is
        both slots or neither."""
        doc, token = rec.read_aliases(self.store, with_token=True)
        if doc is None or not doc.get("canary"):
            raise RegistryError("no live canary to promote")
        canary_key = doc["canary"]
        old_production = doc.get("production")
        new_doc = {
            **{k: v for k, v in doc.items() if k not in rec.CANARY_DOC_KEYS},
            "production": canary_key,
            "previous": old_production,
            "rev": doc.get("rev", 0) + 1,
            "updated_day": str(day) if day else None,
            "last_op": "canary_promote",
        }
        try:
            rec.write_aliases(self.store, new_doc, token)
        except CasConflict as exc:
            _count_promotion("conflict")
            raise PromotionConflict(
                f"canary promotion of {canary_key!r} lost the alias race: "
                f"{exc}"
            ) from exc
        event_day = str(day) if day else None
        rec.append_event(
            self.store, canary_key,
            {"event": "promoted", "day": event_day, "reason": reason,
             "replaced": old_production},
            status="production",
        )
        if old_production and old_production != canary_key:
            rec.append_event(
                self.store, old_production,
                {"event": "superseded", "day": event_day, "by": canary_key},
                status="archived",
            )
        _count_promotion("promoted")
        _count_canary_event("promote")
        log.info(
            f"canary promoted to production: {canary_key} "
            f"(previous: {old_production or 'none'})"
        )
        return new_doc

    def canary_status(self) -> dict:
        """The operator-facing canary snapshot (``cli registry canary
        status``): the alias slot, serveability (dangling or live), and
        the record's current status."""
        doc = rec.read_aliases(self.store)  # ONE read feeds every view
        state, dangling = rec.resolve_canary(self.store, doc)
        slot = self.canary_state(doc)
        record = (
            rec.load_record(self.store, slot["key"]) if slot else None
        )
        return {
            "canary": slot,
            "live": state is not None,
            "dangling_reason": dangling,
            "record_status": record.get("status") if record else None,
            "production": (doc or {}).get("production"),
        }

    # -- the gate ----------------------------------------------------------

    def gate(
        self,
        day: date | None = None,
        model_key: str | None = None,
        policy: GatePolicy | None = None,
        dry_run: bool = False,
    ) -> GateDecision | None:
        """Adjudicate one candidate (named, or the newest in
        ``candidate`` status): evaluate the policy, then promote or
        reject. Returns the decision, or None when there is nothing to
        gate. ``dry_run`` evaluates and returns WITHOUT writing
        anything — no decision event, no status move, no alias CAS.

        Bootstrap: with no production yet, a candidate passing the
        absolute checks is promoted directly — the gate cannot compare
        against a production that does not exist, and a registry with
        an empty alias gates nothing."""
        policy = policy or self.policy
        if model_key is not None:
            if self.resolve("production") == model_key:
                # rejecting here would flip the SERVING model's record to
                # ``rejected`` while the alias keeps serving it — the
                # ledger disowning production. Retiring production is what
                # rollback is for (it also decides what serves next).
                raise RegistryError(
                    f"{model_key!r} is production; the gate adjudicates "
                    "candidates — use rollback to retire production"
                )
            candidate = rec.load_record(self.store, model_key)
            if candidate is None:
                raise RegistryError(f"no registry record for {model_key!r}")
        else:
            candidate = self.newest_candidate()
            if candidate is None:
                return None
        production = self.production_record()
        decision = evaluate_candidate(
            self.store, candidate, production, policy=policy, day=day
        )
        if dry_run:
            return decision
        if decision.promote:
            rec.append_event(
                self.store, candidate["model_key"], decision.to_event()
            )
            self.promote(
                candidate["model_key"], day=day, reason="gate: passed"
            )
        else:
            # one CAS read-modify-write carries both the decision event
            # (promote=false + reasons) and the status move
            written = rec.append_event(
                self.store, candidate["model_key"], decision.to_event(),
                status="rejected",
            )
            if written is None:
                # the record vanished or reads corrupt past the repair
                # budget since we loaded it: the rejection did NOT stick,
                # and without status='rejected' the latest-checkpoint
                # fallback still treats this checkpoint as serveable
                log.error(
                    f"gate rejection of {candidate['model_key']} could not "
                    "be recorded (record unreadable); the checkpoint stays "
                    "a fallback candidate until its record is repaired"
                )
            _count_promotion("rejected")
            log.warning(
                f"gate REJECTED {candidate['model_key']}: "
                f"{'; '.join(decision.reasons) or 'policy'}"
            )
        return decision
