"""Registry records and the alias document (the release ledger).

The registry is a release-management layer BETWEEN training and serving:
training registers each checkpoint as a *candidate* record; the gate
engine (:mod:`bodywork_tpu.registry.gates`) decides promotion; serving
(:func:`bodywork_tpu.models.checkpoint.load_model`,
:class:`bodywork_tpu.serve.reload.CheckpointWatcher`) resolves the
``production`` alias instead of blindly following the newest key under
``models/``. Two artefact shapes, both plain JSON on the artefact store:

- **Per-model records** under ``registry/records/`` — one date-keyed
  document per checkpoint carrying lineage (model key, content digest,
  dataset-day coverage, metrics key), a status
  (``candidate``/``production``/``rejected``/``archived``) and an
  append-only ``history`` of events (register, gate decisions,
  promote/rollback/demote). Records are the audit trail; the serving
  path never requires them.
- **The alias document** ``registry/aliases.json`` — the single
  authoritative mapping of ``production``/``previous`` to model keys.
  It is mutated EXCLUSIVELY through the store's compare-and-swap
  primitive (``ArtefactStore.put_bytes_if_match``), so two concurrent
  promoters cannot clobber each other: exactly one wins, the loser gets
  a clean :class:`~bodywork_tpu.store.base.CasConflict`, and the
  document never tears. A guard test pins that no code path issues a
  raw ``put_bytes`` against the alias key.

Determinism: records carry NO wall-clock timestamps — events are
stamped with the *simulated* day and the lineage token is a content
digest (sha256 of the checkpoint bytes), not a backend version token —
so the chaos harness's byte-identical final-artefact guarantee
(docs/RESILIENCE.md) extends over ``registry/``.

Corrupt-read handling: every read validates the JSON schema. A corrupt
payload is retried a bounded number of times (under the chaos plan's
``max_consecutive`` cap a retried read is guaranteed clean, which keeps
chaos runs deterministic); a record still unreadable after the budget is
treated as ABSENT, counted on
``bodywork_tpu_registry_corrupt_records_total`` and flagged
``repair_needed`` on the store's registry state cache — the same
recover-and-flag shape the snapshot loader uses. The ALIAS document is
stricter: treating a corrupt alias as absent could silently revert
serving to the ungated latest-checkpoint fallback, so alias readers
raise :class:`RegistryCorrupt` instead and callers keep their current
state.

Stdlib-only on purpose: the serving hot path (checkpoint watcher) and
every stage pod resolve through this module, so it must not widen any
stage's pinned dependency closure.
"""
from __future__ import annotations

import json
from datetime import date

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import (
    DATASETS_PREFIX,
    REGISTRY_ALIAS_KEY,
    REGISTRY_RECORDS_PREFIX,
    model_metrics_key,
    registry_record_key,
)
from bodywork_tpu.utils.integrity import stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("registry.records")

RECORD_SCHEMA = "bodywork_tpu.registry_record/1"
ALIAS_SCHEMA = "bodywork_tpu.registry_aliases/1"

#: the status state machine a record moves through
STATUSES = ("candidate", "production", "rejected", "archived")

#: validation-read retry budget: 1 + CORRUPT_READ_RETRIES attempts.
#: Chosen to exceed the chaos plan's default ``max_consecutive`` cap of
#: 2, so a seeded soak's corrupt reads NEVER escalate to record-absent
#: (which would make gate decisions diverge from the fault-free twin).
CORRUPT_READ_RETRIES = 2


class RegistryCorrupt(RuntimeError):
    """The alias document failed validation on every read attempt.
    Callers must keep their current state (a watcher keeps serving what
    it serves) — falling back to latest-checkpoint here would put an
    ungated model live."""


def _count_corrupt(kind: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_registry_corrupt_records_total",
        "Registry reads that failed JSON/schema validation, by kind",
    ).inc(kind=kind)


def _flag_repair(store: ArtefactStore) -> None:
    # same shape as the snapshot loader's repair flag: a maintenance
    # pass (or the next register/promote rewrite) can act on it
    store.mutable_cache("_registry_state")["repair_needed"] = True


def _validated_read(
    store: ArtefactStore, key: str, schema: str, kind: str
) -> dict | None:
    """Read + validate a registry JSON document. Returns None when the
    key is absent, or when it stays corrupt past the retry budget (the
    caller decides whether absent-on-corrupt is safe — the alias reader
    does NOT accept it). Every corrupt attempt is counted."""
    from bodywork_tpu.store.base import ArtefactNotFound

    corrupt = False
    for _attempt in range(1 + CORRUPT_READ_RETRIES):
        try:
            raw = store.get_bytes(key)
        except ArtefactNotFound:
            return None
        try:
            doc = json.loads(raw.decode("utf-8"))
            if (
                isinstance(doc, dict)
                and doc.get("schema") == schema
                # embedded content digest (utils.integrity): a bit flip
                # that keeps the JSON parseable — one digit of a model
                # digest, a flipped status letter inside a quoted string
                # — must still read as corrupt; legacy digest-less
                # documents (None) stay acceptable
                and verify_doc(doc) is not False
            ):
                return doc
        except (UnicodeDecodeError, ValueError):
            pass
        corrupt = True
        _count_corrupt(kind)
        log.warning(f"corrupt registry document at {key!r}; re-reading")
    if corrupt:
        _flag_repair(store)
    return None


# -- per-model records -----------------------------------------------------


def load_record(
    store: ArtefactStore, model_key: str, with_token: bool = False
):
    """The registry record for ``model_key``, or None (absent, or corrupt
    past the retry budget — treated as absent, counted, flagged).
    ``with_token=True`` returns ``(record_or_None, version_token)`` with
    the token read BEFORE the payload, so a CAS against it can only win
    if nothing changed since; a ``(None, token)`` pair means the key
    EXISTS but is corrupt — the CAS repair-overwrite case."""
    key = registry_record_key(model_key)
    token = store.version_token(key) if with_token else None
    doc = _validated_read(store, key, RECORD_SCHEMA, "record")
    return (doc, token) if with_token else doc


def put_record(store: ArtefactStore, record: dict, expected_token) -> str:
    """Write one record through the SAME CAS primitive as the alias doc
    (``expected_token``: the token its read was taken under, None for
    create-only) — record mutations are read-modify-writes, and a
    concurrent gate and operator CLI appending to one record must not
    silently drop each other's events. :func:`update_record` is the
    retrying caller."""
    key = registry_record_key(record["model_key"])
    data = json.dumps(
        stamp_doc(record), sort_keys=True, indent=1
    ).encode("utf-8")
    store.put_bytes_if_match(key, data, expected_token)
    return key


def update_record(store: ArtefactStore, model_key: str, mutate, attempts: int = 4):
    """CAS read-modify-write loop for one record: load (token first),
    apply ``mutate(record_or_None) -> record_or_None``, conditional
    write; a lost race re-reads and re-applies. Returns the written
    record, or None when ``mutate`` returned None (nothing to do).
    ``mutate`` sees None for an absent record and may create one; a
    corrupt-past-budget record also reads as None but keeps its token,
    so the conditional write REPAIRS it in place."""
    from bodywork_tpu.store.base import CasConflict

    last: CasConflict | None = None
    for _attempt in range(attempts):
        record, token = load_record(store, model_key, with_token=True)
        updated = mutate(record)
        if updated is None:
            return None
        try:
            put_record(store, updated, expected_token=token)
            return updated
        except CasConflict as exc:
            last = exc  # concurrent writer: re-read, re-apply
    raise last


def list_records(store: ArtefactStore) -> list[dict]:
    """All readable records, oldest first (date-key order). Corrupt or
    unparseable records are skipped (counted by ``load_record``)."""
    out = []
    for key, _d in store.history(REGISTRY_RECORDS_PREFIX):
        doc = _validated_read(store, key, RECORD_SCHEMA, "record")
        if doc is not None:
            out.append(doc)
    return out


def model_digest(data: bytes) -> str:
    """Content digest used as the record's lineage version token —
    backend-independent (a filesystem inode token or GCS generation
    would tie the record's bytes to one backend instance and break the
    chaos twin comparison) and tamper-evident. Delegates to the shared
    format (``utils.integrity.sha256_digest``) so the integrity scrub
    can cross-check it against journal and sidecar evidence."""
    from bodywork_tpu.utils.integrity import sha256_digest

    return sha256_digest(data)


def register_candidate(
    store: ArtefactStore,
    model_key: str,
    metrics_key: str | None = None,
    day: date | None = None,
    model_bytes: bytes | None = None,
    prediction_bounds: dict | None = None,
) -> dict:
    """Create (or refresh) the candidate record for a persisted
    checkpoint: lineage (content digest, dataset-day coverage, metrics
    key) + a ``registered`` event. Training calls this instead of
    implicitly publishing — the checkpoint takes traffic only after a
    promotion flips the alias. Idempotent per (model_key, content): a
    re-register of identical bytes leaves the record byte-stable.
    ``model_bytes`` lets a caller that just wrote the checkpoint skip
    the full-artefact re-download the digest would otherwise cost (one
    GET per training day on a remote store).

    ``prediction_bounds`` (``{"lo": float, "hi": float}``, derived from
    training-label statistics — ``train.trainer._prediction_bounds``)
    is the serving-side sanity band: the prediction-sanity firewall
    (``serve.app``) treats outputs outside it as canary violations.
    Deterministic from the dataset bytes, so the chaos twins' records
    stay byte-identical."""
    from bodywork_tpu.utils.dates import date_from_key

    model_date = date_from_key(model_key)
    day = day or model_date
    if metrics_key is None and model_date is not None:
        metrics_key = model_metrics_key(model_date)
        if not store.exists(metrics_key):
            metrics_key = None
    if model_bytes is None:
        model_bytes = store.get_bytes(model_key)
    digest = model_digest(model_bytes)
    days = [str(d) for _k, d in store.history(DATASETS_PREFIX)]

    def _mutate(existing: dict | None) -> dict | None:
        if existing is not None:
            if existing.get("model_digest") == digest:
                return None  # byte-stable: same checkpoint, same record
            record = existing  # re-trained same key: refresh lineage
            record["model_digest"] = digest
            record["metrics_key"] = metrics_key
            # the retrain saw TODAY's dataset coverage — keeping the
            # original registration's span would make `registry show`
            # misstate the training data behind the bytes now recorded
            record["dataset_days"] = {
                "first": days[0] if days else None,
                "last": days[-1] if days else None,
                "count": len(days),
            }
            if prediction_bounds is not None:
                record["prediction_bounds"] = prediction_bounds
            if record.get("status") != "production":
                # a retrained rejected/archived key becomes a candidate
                # again; PRODUCTION keeps its status — silently flipping
                # the currently-aliased record to candidate would make
                # the ledger disown the model that is actually serving
                # (the digest-change event below records the drift)
                record["status"] = "candidate"
        else:
            record = {
                "schema": RECORD_SCHEMA,
                "model_key": model_key,
                "model_digest": digest,
                "data_date": str(model_date) if model_date else None,
                "dataset_days": {
                    "first": days[0] if days else None,
                    "last": days[-1] if days else None,
                    "count": len(days),
                },
                "metrics_key": metrics_key,
                "status": "candidate",
                "history": [],
            }
            if prediction_bounds is not None:
                record["prediction_bounds"] = prediction_bounds
        record["history"].append(
            {"event": "registered", "day": str(day) if day else None,
             **({"digest_changed": True} if existing is not None else {})}
        )
        return record

    record = update_record(store, model_key, _mutate)
    if record is None:
        return load_record(store, model_key)  # byte-stable no-op
    log.info(f"registered candidate {model_key} ({digest[:15]}…)")
    return record


def append_event(
    store: ArtefactStore,
    model_key: str,
    event: dict,
    status: str | None = None,
) -> dict | None:
    """Append one event to a record's history (and optionally move its
    status) — a CAS read-modify-write, so a concurrent gate and operator
    CLI appending to the same record lose nothing. Records are
    append-only: history never shrinks."""
    if status is not None:
        assert status in STATUSES, status

    def _mutate(record: dict | None) -> dict | None:
        if record is None:
            return None
        record["history"].append(event)
        if status is not None:
            record["status"] = status
        return record

    return update_record(store, model_key, _mutate)


# -- the alias document ----------------------------------------------------


def read_aliases(store: ArtefactStore, with_token: bool = False):
    """The alias document (validated), or None when it does not exist.
    ``with_token=True`` returns ``(doc, version_token)`` with the token
    read BEFORE the payload — so a CAS against that token can only
    succeed if nothing changed since (a write landing between the two
    reads makes the token stale and the CAS fail cleanly). Raises
    :class:`RegistryCorrupt` when the document exists but stays invalid
    past the retry budget."""
    token = store.version_token(REGISTRY_ALIAS_KEY)
    if token is None and not store.exists(REGISTRY_ALIAS_KEY):
        # absent — two metadata probes, no payload read: a reload
        # watcher polls this on every cycle, and a registry-less store
        # must not pay a failing GET (plus its corrupt-read retries)
        # per poll forever. Token-less backends fall through on the
        # exists() check, so absence is never inferred from a None
        # token alone.
        return (None, None) if with_token else None
    doc = _validated_read(store, REGISTRY_ALIAS_KEY, ALIAS_SCHEMA, "alias")
    if doc is None:
        if store.exists(REGISTRY_ALIAS_KEY):
            raise RegistryCorrupt(
                f"alias document {REGISTRY_ALIAS_KEY!r} failed validation "
                f"on every read attempt"
            )
        return (None, None) if with_token else None
    return (doc, token) if with_token else doc


def write_aliases(store: ArtefactStore, doc: dict, expected_token):
    """One CAS write of the alias document. Raises
    :class:`~bodywork_tpu.store.base.CasConflict` when someone else won
    the race — the ONLY way this document is ever written."""
    assert doc.get("schema") == ALIAS_SCHEMA, doc
    return store.put_bytes_if_match(
        REGISTRY_ALIAS_KEY,
        json.dumps(
            stamp_doc(doc), sort_keys=True, indent=1
        ).encode("utf-8"),
        expected_token,
    )


def registry_exists(store: ArtefactStore) -> bool:
    """True when the store has an ACTIVE registry — i.e. an alias
    document. Records alone do not count: before the first promotion
    there is nothing gated to serve, so serving keeps the
    latest-checkpoint behavior byte-identically."""
    return store.exists(REGISTRY_ALIAS_KEY)


def resolve_alias(store: ArtefactStore, alias: str = "production") -> str | None:
    """The model key the alias currently maps to, or None (no registry,
    or alias unset). Raises :class:`RegistryCorrupt` for an unreadable
    alias document — see the module docstring for why that must not
    silently become the latest-checkpoint fallback."""
    doc = read_aliases(store)
    if doc is None:
        return None
    return doc.get(alias)


# -- the canary slot -------------------------------------------------------

#: alias-document keys that together describe a live canary; cleared as a
#: unit by every canary-ending CAS (abort / promote / repair)
CANARY_DOC_KEYS = ("canary", "canary_fraction", "canary_seed", "canary_day")


def resolve_canary(store: ArtefactStore, doc: dict | None = None):
    """The live canary's serving state from the alias document:
    ``(state, dangling_reason)``.

    ``state`` is ``{"key", "fraction", "seed", "day", "bounds"}`` when a
    serveable canary is configured, else None. ``dangling_reason`` is a
    human-readable reason when the slot IS set but must be ignored — a
    canary pointing at a deleted checkpoint or a gate-rejected record
    (the stale slot a crashed watchdog leaves behind). Callers fall
    back to production-only serving on a dangling slot; the reload
    watcher additionally repairs it (one CAS + a repair event) so boot
    is never wedged by release-loop debris. ``doc`` lets a caller that
    already read the alias document skip the second read."""
    if doc is None:
        doc = read_aliases(store)
    if not doc:
        return None, None
    key = doc.get("canary")
    if not key:
        return None, None
    if key == doc.get("production"):
        return None, f"canary {key!r} already IS production"
    if not store.exists(key):
        return None, f"canary checkpoint {key!r} missing from the store"
    record = load_record(store, key)
    if record is not None and record.get("status") == "rejected":
        return None, f"canary {key!r} record is rejected"
    bounds = (record or {}).get("prediction_bounds")
    try:
        fraction = float(doc.get("canary_fraction", 0.1))
        seed = int(doc.get("canary_seed", 0))
    except (TypeError, ValueError):
        return None, f"canary {key!r} has malformed fraction/seed"
    if not 0.0 < fraction <= 1.0:
        return None, f"canary {key!r} fraction {fraction!r} outside (0, 1]"
    state = {
        "key": key,
        "fraction": fraction,
        "seed": seed,
        "day": doc.get("canary_day"),
        "bounds": bounds,
    }
    return state, None
