"""Shadow evaluation: score a candidate next to production, offline.

The canary step of the promotion gate WITHOUT touching live traffic:
both checkpoints are loaded in-process, the last K days of persisted
datasets are scored through each, and the report compares their
prediction deltas and per-model quality against the same labels — the
"validate a checkpoint before it takes traffic" practice of large-model
TPU serving (PAPERS.md: Gemma-on-TPU serving, pjit-era checkpoint
validation), shrunk to this pipeline's scale.

Deliberately in-process and read-only: no requests are mirrored, no
service is started, nothing is written. The report is a plain dict the
gate embeds in its decision event, so the audit trail shows WHY a
candidate was admitted or blocked.
"""
from __future__ import annotations

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import DATASETS_PREFIX
from bodywork_tpu.utils.logging import get_logger

log = get_logger("registry.shadow")

_APE_EPS = 2.220446049250313e-16


def _window_mape(preds, labels) -> float:
    import numpy as np

    denom = np.maximum(np.abs(labels), _APE_EPS)
    return float(np.mean(np.abs(preds - labels) / denom))


def shadow_compare(
    store: ArtefactStore,
    predict_candidate,
    predict_production,
    days: int = 7,
    max_rows_per_day: int | None = None,
) -> dict:
    """Score two ``predict(X) -> y`` callables over the last ``days``
    persisted dataset days and compare — the engine behind
    :func:`shadow_evaluate` (two checkpoints) and the quantized-serving
    quality gate (one checkpoint, two dtypes — ``serve.server``).
    Report shape as documented on :func:`shadow_evaluate`."""
    import numpy as np

    from bodywork_tpu.data.io import load_dataset

    hist = store.history(DATASETS_PREFIX)
    if not hist:
        raise ValueError("no dataset history to shadow-evaluate over")
    window = hist[-days:]
    deltas, cand_all, prod_all, labels_all = [], [], [], []
    for key, _d in window:
        ds = load_dataset(store, key)
        X, y = ds.X, ds.y
        if max_rows_per_day is not None:
            X, y = X[:max_rows_per_day], y[:max_rows_per_day]
        cand_pred = np.asarray(predict_candidate(X), dtype=np.float64)
        prod_pred = np.asarray(predict_production(X), dtype=np.float64)
        deltas.append(cand_pred - prod_pred)
        cand_all.append(cand_pred)
        prod_all.append(prod_pred)
        labels_all.append(np.asarray(y, dtype=np.float64))
    delta = np.concatenate(deltas)
    cand_pred = np.concatenate(cand_all)
    prod_pred = np.concatenate(prod_all)
    labels = np.concatenate(labels_all)
    return {
        "days": len(window),
        "rows": int(delta.size),
        "mean_abs_delta": float(np.mean(np.abs(delta))),
        "max_abs_delta": float(np.max(np.abs(delta))),
        "candidate_mape": _window_mape(cand_pred, labels),
        "production_mape": _window_mape(prod_pred, labels),
    }


def shadow_evaluate(
    store: ArtefactStore,
    candidate_key: str,
    production_key: str,
    days: int = 7,
    max_rows_per_day: int | None = None,
) -> dict:
    """Score both checkpoints over the last ``days`` persisted dataset
    days and compare. Returns::

        {"days": n, "rows": n,
         "mean_abs_delta": …,  "max_abs_delta": …,   # candidate vs production
         "candidate_mape": …,  "production_mape": …} # each vs the labels

    ``max_rows_per_day`` caps per-day rows (head) for cheap gates.
    Raises when either checkpoint or the window cannot be loaded — the
    gate surfaces that as a failed check rather than guessing.
    """
    from bodywork_tpu.models.checkpoint import load_model_bytes

    candidate = load_model_bytes(store.get_bytes(candidate_key))
    production = load_model_bytes(store.get_bytes(production_key))
    report = shadow_compare(
        store, candidate.predict, production.predict,
        days=days, max_rows_per_day=max_rows_per_day,
    )
    log.info(
        f"shadow eval {candidate_key} vs {production_key}: "
        f"mean|Δ|={report['mean_abs_delta']:.4f} over "
        f"{report['days']} day(s), candidate MAPE "
        f"{report['candidate_mape']:.4f} vs production "
        f"{report['production_mape']:.4f}"
    )
    return report
