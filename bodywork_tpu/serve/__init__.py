from bodywork_tpu.serve.predictor import (
    EXECUTABLE_CACHE,
    SERVE_DTYPES,
    BF16MLPPredictor,
    Int8MLPPredictor,
    PaddedPredictor,
)
from bodywork_tpu.serve.admission import AdmissionController, SharedBudgetSlot
from bodywork_tpu.serve.aio import AioServiceHandle
from bodywork_tpu.serve.app import create_app
from bodywork_tpu.serve.batcher import CoalescerSaturated, RequestCoalescer
from bodywork_tpu.serve.multiproc import MultiProcessService
from bodywork_tpu.serve.reload import CheckpointWatcher
from bodywork_tpu.serve.server import (
    SERVER_ENGINES,
    RoundRobinApp,
    ServiceHandle,
    build_admission,
    build_predictor,
    build_serving_predictor,
    resolve_engine,
    serve_latest_model,
)

__all__ = [
    "AdmissionController",
    "AioServiceHandle",
    "BF16MLPPredictor",
    "CheckpointWatcher",
    "CoalescerSaturated",
    "EXECUTABLE_CACHE",
    "Int8MLPPredictor",
    "RequestCoalescer",
    "MultiProcessService",
    "PaddedPredictor",
    "RoundRobinApp",
    "SERVER_ENGINES",
    "SERVE_DTYPES",
    "SharedBudgetSlot",
    "build_admission",
    "build_predictor",
    "build_serving_predictor",
    "create_app",
    "resolve_engine",
    "ServiceHandle",
    "serve_latest_model",
]
