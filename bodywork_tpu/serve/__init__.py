from bodywork_tpu.serve.predictor import PaddedPredictor
from bodywork_tpu.serve.app import create_app
from bodywork_tpu.serve.server import ServiceHandle, serve_latest_model

__all__ = ["PaddedPredictor", "create_app", "ServiceHandle", "serve_latest_model"]
