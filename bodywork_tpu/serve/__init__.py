"""Serving package. Attribute access is lazy (PEP 562): the
disaggregated front-end processes (``serve.frontend`` / ``serve.wire`` /
``serve.rowqueue``) live under this package but must stay
accelerator-free, so importing ``bodywork_tpu.serve.<leaf>`` cannot be
allowed to drag ``predictor``/``app`` (and therefore JAX) in eagerly.
``from bodywork_tpu.serve import create_app`` still works — it just pays
the import at first access instead of at package import."""
from __future__ import annotations

import importlib

#: public name -> defining submodule; the package namespace resolves
#: these on first attribute access
_EXPORTS = {
    "EXECUTABLE_CACHE": "predictor",
    "SERVE_DTYPES": "predictor",
    "BF16MLPPredictor": "predictor",
    "Int8MLPPredictor": "predictor",
    "PaddedPredictor": "predictor",
    "AdmissionController": "admission",
    "SharedBudgetSlot": "admission",
    "AioServiceHandle": "aio",
    "create_app": "app",
    "CoalescerSaturated": "batcher",
    "RequestCoalescer": "batcher",
    "MultiProcessService": "multiproc",
    "CheckpointWatcher": "reload",
    "SERVER_ENGINES": "server",
    "RoundRobinApp": "server",
    "ServiceHandle": "server",
    "build_admission": "server",
    "build_predictor": "server",
    "build_serving_predictor": "server",
    "resolve_engine": "server",
    "serve_latest_model": "server",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    value = getattr(
        importlib.import_module(f"{__name__}.{submodule}"), name
    )
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
