from bodywork_tpu.serve.predictor import PaddedPredictor
from bodywork_tpu.serve.app import create_app
from bodywork_tpu.serve.server import (
    RoundRobinApp,
    ServiceHandle,
    serve_latest_model,
)

__all__ = [
    "PaddedPredictor",
    "RoundRobinApp",
    "create_app",
    "ServiceHandle",
    "serve_latest_model",
]
