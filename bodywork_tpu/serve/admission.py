"""Admission control for the scoring service (ROADMAP open item 2).

Closed-loop benches hide queueing collapse: a client that waits for each
response before sending the next can never overrun the server, so
"requests/s at N clients" says nothing about behaviour under *open-loop*
arrival-rate load, where work keeps arriving whether or not the server
is keeping up. Without admission control an overloaded server queues
without bound — every request eventually answers, seconds late, which is
indistinguishable from an outage for the client and poisons the queue
for everyone behind it. The standard answer (and this module) is to
bound the work the server will hold and **shed the rest at the front
door**: a 429 + ``Retry-After`` returned before any parsing, coalescer
enqueue, or device work happens costs microseconds and tells a
well-behaved client exactly when to come back.

:class:`AdmissionController` is the one admission point both serving
front-ends share (the threaded WSGI engine checks it at the top of
``ScoringApp.__call__``; the asyncio engine checks it on the event loop
before touching the coalescer):

- **Bounded pending budget** — at most ``max_pending`` scoring requests
  admitted-and-unfinished at once; the (N+1)th is shed. The budget is
  the local analogue of a k8s pod's memory/queue headroom: it is sized
  so that admitted work clears within an acceptable latency bound.
- **External depth probe** (:meth:`attach_depth_probe`) — the queue an
  overloaded server drowns in is not always the one admission watches.
  On the asyncio engine the *event loop itself* is a queue: when
  request handling saturates the loop, excess connections back up as
  pending tasks UPSTREAM of the admission check, the internal pending
  count stays low (work is drained as fast as it is admitted), and
  latency grows without a single shed. The probe folds that upstream
  backlog (busy-connection count, ``serve.aio``) into the same budget:
  requests are shed while the TOTAL work held — admitted or still in
  the loop's accept backlog — exceeds ``max_pending``. The threaded
  engine needs no probe: each request runs admission on its own thread
  immediately, so the internal count IS the queue.
- **EWMA queue-delay estimator** — every released request reports the
  delay it actually experienced (admission -> response ready); the
  controller keeps an exponentially-weighted moving average. That
  estimate is the ``Retry-After`` a shed (or model-less 503) response
  carries, clamped to ``[retry_after_min_s, retry_after_max_s]`` so a
  cold estimator or a latency spike can never tell clients "come back
  in an hour" (see :meth:`retry_after_s`).
- **Saturation signals for the outside world** — current depth rides
  the ``bodywork_tpu_serve_queue_depth`` gauge (aggregate ``sum``: the
  multi-worker ``/metrics`` merge adds replica depths into the
  service-wide queue) and every shed increments
  ``bodywork_tpu_serve_shed_total{reason="admission"}``. Chaos-injected
  503/429s count into the same counter under ``reason="chaos"``
  (:mod:`bodywork_tpu.chaos.http`), so a dashboard can always tell real
  backpressure from injected adversity. ``/healthz`` surfaces the same
  numbers per replica (:meth:`state`).

The controller is engine-agnostic and thread-safe: admission decisions
are one lock acquisition + a counter compare, cheap enough for the
event-loop hot path.
"""
from __future__ import annotations

import collections
import math
import threading

from bodywork_tpu.obs import get_registry
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.admission")

__all__ = [
    "DEFAULT_MAX_PENDING",
    "SHED_TOTAL_METRIC",
    "QUEUE_DEPTH_METRIC",
    "PENDING_COST_METRIC",
    "AdmissionController",
    "SharedBudgetSlot",
    "build_admission",
    "count_shed",
]

#: default pending-request budget when admission is enabled without an
#: explicit size (``cli serve --server-engine aio`` with no
#: ``--max-pending``). Sized for the coalescer regime: 512 queued
#: single-row requests drain in ~8 full 64-row flushes — well under a
#: second on every measured backend — so admitted work meets its latency
#: bound while bursts 2x capacity still mostly admit.
DEFAULT_MAX_PENDING = 512

#: sheds by reason: ``admission`` (budget exceeded) vs ``chaos``
#: (fault-injected 503/429) — distinguishable by construction
SHED_TOTAL_METRIC = "bodywork_tpu_serve_shed_total"
#: admitted-and-unfinished scoring requests; gauge aggregate ``sum`` so
#: the multiproc merge reports the service-wide queue
QUEUE_DEPTH_METRIC = "bodywork_tpu_serve_queue_depth"
#: estimated dispatch-seconds of admitted-and-unfinished work when the
#: cost-priced shed is armed (:meth:`AdmissionController.configure_cost_shed`)
PENDING_COST_METRIC = "bodywork_tpu_serve_pending_cost_seconds"


def count_shed(reason: str) -> None:
    """Increment the shared shed counter. One helper so the admission
    layer, the chaos middleware, and the asyncio front-end can never
    drift onto differently-named/helped counters."""
    get_registry().counter(
        SHED_TOTAL_METRIC,
        "Scoring requests refused before any work, by reason "
        "(admission=budget exceeded, chaos=injected fault)",
    ).inc(reason=reason)


class SharedBudgetSlot:
    """One worker's slot in a cross-process admission budget.

    The budget is a ``multiprocessing.Array('i', n_workers)``: worker
    ``i`` only ever mutates ``array[i]`` (its own admitted count), and an
    admission decision compares ``sum(array)`` — the service-wide held
    work — against ``max_pending`` under the array's one lock. Per-slot
    accounting is what makes the budget SELF-HEALING: when a replica
    dies mid-request, the supervisor zeroes its slot before respawning,
    so a crash can never leak budget and slowly choke the fleet (a
    single shared counter would leak exactly the dead worker's unknown
    in-flight count, forever)."""

    def __init__(self, array, index: int):
        self.array = array
        self.index = int(index)

    def admit(self, max_pending: int) -> tuple[bool, int]:
        """Try to take one unit; returns ``(admitted, service_total)``."""
        with self.array.get_lock():
            total = sum(self.array)
            if total >= max_pending:
                return False, total
            self.array[self.index] += 1
            return True, total + 1

    def release(self) -> None:
        with self.array.get_lock():
            if self.array[self.index] > 0:
                self.array[self.index] -= 1

    def total(self) -> int:
        with self.array.get_lock():
            return sum(self.array)

    @staticmethod
    def clear(array, index: int) -> None:
        """Zero a (dead) worker's slot — the supervisor's reclaim hook."""
        with array.get_lock():
            array[index] = 0


class AdmissionController:
    """Bounded-pending admission with an EWMA queue-delay estimator.

    Request lifecycle::

        if not admission.try_admit():
            return 429 + Retry-After: admission.retry_after_s()
        t0 = time.perf_counter()
        try:
            ... parse, enqueue, score, serialize ...
        finally:
            admission.release(time.perf_counter() - t0)

    ``try_admit`` is the ONLY path that counts a shed, so callers cannot
    forget the metric; ``release`` is the only path that shrinks the
    depth, so a crashed handler leaks budget only if it skips its
    ``finally`` — which is why both engines wrap the whole handler.
    """

    def __init__(
        self,
        max_pending: int = DEFAULT_MAX_PENDING,
        ewma_alpha: float = 0.2,
        retry_after_min_s: float = 1.0,
        retry_after_max_s: float = 30.0,
        shared_slot: SharedBudgetSlot | None = None,
    ):
        if max_pending < 1:
            raise ValueError(f"max_pending must be >= 1, got {max_pending}")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError(f"ewma_alpha must be in (0, 1], got {ewma_alpha}")
        if not 0.0 < retry_after_min_s <= retry_after_max_s:
            raise ValueError(
                f"need 0 < retry_after_min_s <= retry_after_max_s, got "
                f"{retry_after_min_s}..{retry_after_max_s}"
            )
        self.max_pending = max_pending
        self.ewma_alpha = ewma_alpha
        self.retry_after_min_s = retry_after_min_s
        self.retry_after_max_s = retry_after_max_s
        self._lock = threading.Lock()
        self._pending = 0
        #: optional cross-process budget slot (:class:`SharedBudgetSlot`):
        #: when set, admission decisions compare the SERVICE-WIDE
        #: admitted count against ``max_pending`` — N SO_REUSEPORT
        #: replica processes behind one port become one benchmarkable
        #: unit with ONE budget (serve.multiproc wires it) instead of N
        #: independent budgets summing to N x max_pending. The local
        #: ``_pending`` keeps tracking this process's own contribution:
        #: the queue-depth gauge reports the LOCAL count, so the
        #: multiproc sum-aggregate /metrics merge still shows the true
        #: service total exactly once.
        self._shared = shared_slot
        self._draining = False
        self._depth_probe = None
        #: high-water mark of the pending depth — the budget-invariant
        #: witness the admission tests assert on (never > max_pending)
        self.max_observed_pending = 0
        self._ewma_delay_s: float | None = None
        self._shed_count = 0
        self._admitted_count = 0
        #: cost-priced shed (:meth:`configure_cost_shed`): when armed,
        #: each request is priced in estimated dispatch-seconds BEFORE
        #: any parse-side queueing, and shed (reason="cost") while the
        #: estimated cost of admitted-and-unfinished work exceeds the
        #: budget. Off by default — the count budget alone preserves
        #: historical behaviour.
        self._cost_pricer = None
        self._cost_budget_s: float | None = None
        self._pending_cost_s = 0.0
        #: per-admit estimates, consumed one per release. Releases can
        #: complete out of order, so an individual pop may misattribute
        #: WHICH estimate it retires — but every admit pushes exactly
        #: once and every release pops exactly once, so the pending SUM
        #: conserves (drains to zero when the queue does).
        self._cost_fifo: collections.deque = collections.deque()
        reg = get_registry()
        self._g_cost = reg.gauge(
            PENDING_COST_METRIC,
            "Estimated dispatch-seconds of admitted-and-unfinished work "
            "(cost-priced shed; 0 when unarmed)",
            aggregate="sum",
        )
        self._g_cost.set(0.0)
        self._g_depth = reg.gauge(
            QUEUE_DEPTH_METRIC,
            "Admitted-and-unfinished scoring requests (per worker; the "
            "multiproc aggregation sums replicas)",
            aggregate="sum",
        )
        self._g_depth.set(0.0)

    # -- admission ----------------------------------------------------------
    def attach_depth_probe(self, probe) -> None:
        """Register a zero-arg callable reporting work queued UPSTREAM
        of this controller (the aio engine's busy-connection count —
        see the module docstring). Folded into every admission
        decision, :attr:`queue_depth`, and :meth:`state`."""
        self._depth_probe = probe

    def _external_depth(self) -> int:
        probe = self._depth_probe
        if probe is None:
            return 0
        try:
            return max(0, int(probe()))
        except Exception:  # a broken probe must never break admission
            return 0

    def begin_drain(self) -> None:
        """Graceful-shutdown mode (SIGTERM): stop admitting NEW scoring
        work — every subsequent ``try_admit`` sheds (429 + Retry-After,
        counted ``reason="drain"``) — while in-flight requests keep
        their budget and release normally. One-way: the process is
        exiting."""
        with self._lock:
            self._draining = True

    @property
    def draining(self) -> bool:
        return self._draining

    def configure_cost_shed(self, pricer, budget_s: float | None) -> None:
        """Arm (or, with ``pricer=None``, disarm) the cost-priced shed:
        ``pricer(rows)`` returns the estimated dispatch-seconds of a
        request (``tune.costmodel.cost_pricer`` builds one from the
        learned cost model), and admission sheds (reason ``"cost"``)
        while the estimated cost of admitted-and-unfinished work would
        exceed ``budget_s``. A count budget bounds HOW MANY requests are
        held; the cost budget bounds how much device TIME they represent
        — under a mixed-row-count workload the two disagree, and the
        cost budget is the one that tracks the latency bound."""
        if pricer is not None and (budget_s is None or budget_s <= 0.0):
            raise ValueError(f"cost budget_s must be > 0, got {budget_s}")
        with self._lock:
            self._cost_pricer = pricer
            self._cost_budget_s = float(budget_s) if pricer is not None else None
            if pricer is None:
                self._pending_cost_s = 0.0
                self._cost_fifo.clear()
        self._g_cost.set(self._pending_cost_s)

    def _price(self, rows: int) -> float | None:
        """Estimated dispatch-seconds for a ``rows``-row request, or
        None when unarmed/broken (a broken pricer must never break
        admission — same contract as the depth probe)."""
        pricer = self._cost_pricer
        if pricer is None:
            return None
        try:
            est = float(pricer(rows))
        except Exception:
            return None
        return est if est >= 0.0 and math.isfinite(est) else None

    def try_admit(self, rows: int = 1) -> bool:
        """Admit one request against the pending budget. Returns False —
        and counts the shed — when the budget is exhausted, either by
        admitted-and-unfinished requests or by upstream backlog (the
        depth probe; ``>`` not ``>=`` because the probing request's own
        connection is part of that count), or when the controller is
        draining for shutdown. O(1), no allocation: this runs before
        any per-request work.

        ``rows`` (advisory, from the transport's cheap pre-parse hint)
        feeds the cost-priced shed when armed: the request's estimated
        dispatch cost is priced BEFORE parse-side queueing, and admission
        refuses (reason ``"cost"``) while pending estimated cost would
        exceed the configured budget. Callers that cannot know the row
        count pass the default 1 — the estimate degrades toward the
        count budget, it never blocks."""
        if self._draining:
            with self._lock:
                self._shed_count += 1
            count_shed("drain")
            return False
        est = self._price(rows)
        if est is not None:
            with self._lock:
                budget = self._cost_budget_s
                over = (
                    budget is not None
                    and self._pending_cost_s + est > budget
                    # never shed an EMPTY service on price alone: one
                    # oversized request must still make progress, else a
                    # budget below one request's cost is a full outage
                    and self._pending_cost_s > 0.0
                )
                if over:
                    self._shed_count += 1
            if over:
                count_shed("cost")
                return False
        external = self._external_depth()
        shared = self._shared
        if shared is not None:
            # service-wide budget first: the shared count is the sum of
            # every replica's admitted-and-unfinished work. One shared
            # lock acquisition + an O(n_workers) sum — the same cost
            # class as the local path (the kernel balances connections,
            # so contention is spread N ways).
            if external > self.max_pending:
                # shed on upstream backlog alone: don't touch the
                # cross-process lock on the path that exists to be cheap
                admitted, shared_total = False, 0
            else:
                admitted, shared_total = shared.admit(self.max_pending)
            with self._lock:
                if admitted:
                    self._pending += 1
                    self._admitted_count += 1
                    if shared_total > self.max_observed_pending:
                        self.max_observed_pending = shared_total
                    cost = self._cost_admit_locked(est)
                else:
                    self._shed_count += 1
                    cost = None
                depth = self._pending
            self._g_depth.set(float(depth))
            if cost is not None:
                self._g_cost.set(cost)
            if not admitted:
                count_shed("admission")
                return False
            return True
        with self._lock:
            if (
                self._pending >= self.max_pending
                or external > self.max_pending
            ):
                self._shed_count += 1
                shed = True
                cost = None
                depth = max(self._pending, external)
            else:
                self._pending += 1
                self._admitted_count += 1
                if self._pending > self.max_observed_pending:
                    self.max_observed_pending = self._pending
                cost = self._cost_admit_locked(est)
                depth = max(self._pending, external)
                shed = False
        self._g_depth.set(float(depth))
        if cost is not None:
            self._g_cost.set(cost)
        if shed:
            count_shed("admission")
            return False
        return True

    def _cost_admit_locked(self, est: float | None) -> float | None:
        """Record one admitted request's cost estimate (caller holds
        ``_lock``); returns the new pending cost, or None when the cost
        shed is unarmed / this request was unpriced."""
        if est is None:
            return None
        self._pending_cost_s += est
        self._cost_fifo.append(est)
        return self._pending_cost_s

    def release(self, observed_delay_s: float | None = None) -> None:
        """Return one unit of budget; ``observed_delay_s`` (admission ->
        response ready) feeds the EWMA estimator. Under load that delay
        includes the queueing the NEXT client would experience, which is
        exactly what its Retry-After should reflect."""
        shared = self._shared
        # the probe only matters for the local-budget depth fold — don't
        # pay it per release on the shared path (hot, by design cheap)
        external = self._external_depth() if shared is None else 0
        with self._lock:
            if self._pending > 0:
                self._pending -= 1
                if shared is not None:
                    shared.release()
                if self._cost_fifo:
                    # retire one admit's estimate; clamp so a mid-flight
                    # configure_cost_shed can only UNDER-count pending
                    # cost (degrade toward the count budget, never shed
                    # on phantom cost)
                    self._pending_cost_s = max(
                        0.0, self._pending_cost_s - self._cost_fifo.popleft()
                    )
            depth = (
                self._pending if shared is not None
                else max(self._pending, external)
            )
            cost = self._pending_cost_s
            if observed_delay_s is not None and observed_delay_s >= 0.0:
                if self._ewma_delay_s is None:
                    self._ewma_delay_s = float(observed_delay_s)
                else:
                    a = self.ewma_alpha
                    self._ewma_delay_s = (
                        a * float(observed_delay_s)
                        + (1.0 - a) * self._ewma_delay_s
                    )
        self._g_depth.set(float(depth))
        if self._cost_pricer is not None:
            self._g_cost.set(cost)

    # -- signals ------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        """Requests currently held anywhere: admitted-and-unfinished or
        queued upstream of admission (the depth probe). With a shared
        budget this is the SERVICE-WIDE admitted count."""
        external = self._external_depth()
        shared = self._shared
        if shared is not None:
            return max(shared.total(), external)
        with self._lock:
            return max(self._pending, external)

    @property
    def ewma_delay_s(self) -> float | None:
        with self._lock:
            return self._ewma_delay_s

    def retry_after_s(self) -> int:
        """The numeric ``Retry-After`` (whole seconds, HTTP-legal) every
        backpressure response carries — shed 429s AND the degraded-mode
        503s, so clients see ONE consistent hint. Derived from the EWMA
        queue delay, ceiled to a second, clamped to
        ``[retry_after_min_s, retry_after_max_s]``: a cold estimator
        answers the minimum (retry soon — nothing is known to be slow),
        a collapsed one cannot exile clients forever."""
        with self._lock:
            estimate = self._ewma_delay_s
        if estimate is None:
            estimate = 0.0
        clamped = min(
            max(estimate, self.retry_after_min_s), self.retry_after_max_s
        )
        return int(math.ceil(clamped))

    def state(self) -> dict:
        """The /healthz admission block (both engines): depth, budget,
        whether the service is currently at budget (shedding), the
        Retry-After it is handing out, and lifetime admit/shed counts.
        ``queue_depth`` is the total held work; ``pending`` and
        ``upstream_depth`` break it into admitted-and-unfinished vs
        still-queued-before-admission (the aio engine's connection
        backlog — zero on the threaded engine)."""
        external = self._external_depth()
        shared = self._shared
        shared_total = shared.total() if shared is not None else None
        with self._lock:
            pending = self._pending
            ewma = self._ewma_delay_s
            shed = self._shed_count
            admitted = self._admitted_count
            cost_armed = self._cost_pricer is not None
            pending_cost = self._pending_cost_s
            cost_budget = self._cost_budget_s
        budget_used = shared_total if shared_total is not None else pending
        depth = max(budget_used, external)
        return {
            "queue_depth": depth,
            "pending": pending,
            # service-wide admitted count when replicas share ONE budget
            # (serve --workers N); None on a per-process controller
            "shared_pending": shared_total,
            "upstream_depth": external,
            "max_pending": self.max_pending,
            # the exact try_admit predicate (`>` on the external probe:
            # the probing request's own connection is part of that
            # count) — /healthz must never claim "shedding" while
            # requests are still being admitted
            "shedding": (
                budget_used >= self.max_pending
                or external > self.max_pending
            ),
            "retry_after_s": self.retry_after_s(),
            "ewma_queue_delay_s": round(ewma, 6) if ewma is not None else None,
            "admitted_total": admitted,
            "shed_total": shed,
            # cost-priced shed (learned dispatch-cost model): None until
            # configure_cost_shed arms it
            "cost_shed": (
                {
                    "pending_cost_s": round(pending_cost, 6),
                    "budget_s": cost_budget,
                }
                if cost_armed else None
            ),
        }


def build_admission(
    server_engine: str,
    max_pending: int | None,
    retry_after_max_s: float | None = None,
    shared_slot=None,
):
    """The admission controller for a serving process, or ``None``.

    Admission is armed by an explicit ``max_pending`` on either engine,
    and BY DEFAULT (at :data:`DEFAULT_MAX_PENDING`) on the aio engine:
    an event-loop front exists to stay responsive past saturation, which
    it can only do by bounding the work it holds. The threaded engine
    keeps its historical admit-everything default — its thread pool is
    its own (cruder) bound, and the closed-loop parity benches must see
    an unchanged service.

    ``shared_slot`` (:class:`SharedBudgetSlot`) makes ``max_pending`` a
    SERVICE-WIDE budget shared by every replica process behind one
    SO_REUSEPORT port (``serve --workers N`` wires it): the fleet sheds
    as one unit, which is what makes an N-replica capacity record a
    number about ONE service rather than N accidental ones.

    Lives here (not ``serve.server``) so the disaggregated front-end
    processes (``serve.frontend``) can arm the same budget without
    importing the model-loading — and therefore JAX-importing — serving
    stack; ``serve.server`` re-exports it from its historical home.
    """
    if max_pending is None and server_engine != "aio":
        return None
    kwargs: dict = {}
    if max_pending is not None:
        kwargs["max_pending"] = max_pending
    if retry_after_max_s is not None:
        kwargs["retry_after_max_s"] = retry_after_max_s
    if shared_slot is not None:
        kwargs["shared_slot"] = shared_slot
    return AdmissionController(**kwargs)
