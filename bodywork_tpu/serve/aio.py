"""Asyncio event-loop front-end for the scoring service (ROADMAP item 2).

The threaded engine (werkzeug, ``serve.server``) spends one OS thread
per in-flight request. Under closed-loop benches that is invisible — the
client count bounds the thread count — but under open-loop arrival-rate
load every queued request pins a thread, and the server collapses by
context-switching long before the accelerator saturates. This module
replaces the thread-per-request front with a single event loop:

- **Request parsing on the event loop.** A hand-rolled HTTP/1.1 server
  over ``asyncio.start_server`` (stdlib only — no new dependencies):
  request line + headers + Content-Length body, keep-alive connections.
  Parsing a scoring request is microseconds of pure-Python work; the
  loop handles thousands of concurrent connections with one thread.
- **Admission before work** (``serve.admission``): each scoring request
  is admitted against the bounded pending budget FIRST. A shed request
  is answered 429 + ``Retry-After`` straight from the loop — no body
  parse, no coalescer enqueue, no device work, no thread.
- **The coalescer queue fed directly via futures.** An admitted
  single-row request enqueues into the existing
  :class:`~bodywork_tpu.serve.batcher.RequestCoalescer` with
  ``submit_nowait`` + an ``on_done`` callback that resolves an asyncio
  future via ``call_soon_threadsafe`` — the event loop never blocks on a
  batch, and the dispatcher thread never knows HTTP exists. Batch
  requests and the uncoalesced fallback run the padded device call on a
  small thread pool (``run_in_executor``), keeping the loop responsive.
- **Byte-identical responses.** Bodies are built by the same
  ``parse_features`` / ``single_score_payload`` / ``batch_score_payload``
  helpers the WSGI engine uses (``serve.app``), and coalesced batches go
  through the very same dispatcher — the response bytes are equal across
  engines by construction, which is what lets ``cli serve
  --server-engine`` be a pure operational choice.
- **Chaos composition.** When a fault plan is active
  (``chaos.plan.activate``), scoring requests consult it exactly as the
  WSGI :class:`~bodywork_tpu.chaos.http.FlakyScoringMiddleware` does —
  same decision streams, so seeds replay identically — and injected
  503/429s count as ``bodywork_tpu_serve_shed_total{reason="chaos"}``,
  never mistakable for admission sheds (``reason="admission"``).

:class:`AioServiceHandle` mirrors the :class:`~bodywork_tpu.serve.server.
ServiceHandle` lifecycle (``start``/``stop``/``wait``/``serve_forever``/
context manager), so ``serve_latest_model``, the pipeline serve stage,
the hot-reload watcher, and the multiproc supervisor drive either engine
through one interface. The hot-swap, degraded-boot, and coalescer
guarantees all live in :class:`~bodywork_tpu.serve.app.ScoringApp` and
the batcher, which this front-end reuses rather than reimplements.
"""
from __future__ import annotations

import asyncio
import itertools
import json
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
from werkzeug.exceptions import MethodNotAllowed, NotFound

from bodywork_tpu.obs import get_registry
from bodywork_tpu.obs.tracing import (
    TRACE_ID_HEADER,
    TRACEPARENT_HEADER,
    get_tracer,
    parse_traceparent,
)
from bodywork_tpu.serve.admission import count_shed
from bodywork_tpu.serve.batcher import CoalescerSaturated
from bodywork_tpu.serve.rowqueue import DispatcherUnavailable, SlotsExhausted
from bodywork_tpu.serve.wire import (
    BINARY_CONTENT_TYPE,
    MODEL_KEY_HEADER,
    parse_binary_rows,
    parse_features,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.aio")

__all__ = ["AioScoringServer", "AioServiceHandle"]

#: request line + headers cap (also the StreamReader limit)
MAX_HEADER_BYTES = 64 * 1024
#: request body cap — a 2048-row batch of float features is ~100 KB of
#: JSON; 16 MB leaves two orders of magnitude of headroom while bounding
#: a hostile Content-Length
MAX_BODY_BYTES = 16 * 1024 * 1024
#: ceiling on a coalesced prediction rendezvous (mirrors submit()'s)
COALESCE_TIMEOUT_S = 60.0

_REASONS = {
    200: "OK",
    400: "BAD REQUEST",
    404: "NOT FOUND",
    405: "METHOD NOT ALLOWED",
    408: "REQUEST TIMEOUT",
    411: "LENGTH REQUIRED",
    413: "PAYLOAD TOO LARGE",
    429: "TOO MANY REQUESTS",
    431: "REQUEST HEADER FIELDS TOO LARGE",
    500: "INTERNAL SERVER ERROR",
    503: "SERVICE UNAVAILABLE",
}


class _BadRequest(Exception):
    """Protocol-level parse failure: answer and close the connection."""

    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status
        self.message = message


class AioScoringServer:
    """The protocol + dispatch core, HTTP-server-agnostic: a callback
    per connection (``handle_connection``) suitable for
    ``asyncio.start_server``. Serves one or more replica
    :class:`~bodywork_tpu.serve.app.ScoringApp` instances round-robin
    (the in-process analogue of the k8s Service spreading connections),
    sharing their admission controller and coalescers."""

    def __init__(self, apps, admission=None, executor_workers: int = 4):
        self.apps = list(apps) if isinstance(apps, (list, tuple)) else [apps]
        assert self.apps, "need at least one replica app"
        for app in self.apps:
            # a ScoringApp (in-process scoring) or a FrontendApp
            # (disaggregated: is_frontend, enqueues to the dispatcher) —
            # duck-typed so this module never imports the JAX-heavy
            # serve.app just to check a type
            assert hasattr(app, "route_stream") or getattr(
                app, "is_frontend", False
            ), f"not a servable app: {type(app).__name__}"
        # ONE admission budget for the whole listener (replicas share the
        # port, so they share the backpressure boundary); default to the
        # apps' controller so create_app wiring needs no duplication
        self.admission = (
            admission if admission is not None else self.apps[0].admission
        )
        #: connections with a request being read, handled, or written —
        #: the event loop's OWN queue. When request handling saturates
        #: the loop, excess load backs up HERE (as unscheduled tasks),
        #: upstream of the app-level admission count, so the controller
        #: folds this number into its budget via the depth probe (see
        #: serve.admission). Loop-thread-only writes; no lock needed.
        self._busy_connections = 0
        if self.admission is not None:
            self.admission.attach_depth_probe(lambda: self._busy_connections)
        self._rr = itertools.count()
        # small pool for device dispatches the loop must not block on
        # (uncoalesced single rows, batch scoring, /metrics file reads)
        self._executor = ThreadPoolExecutor(
            max_workers=executor_workers, thread_name_prefix="aio-dispatch"
        )
        self._plan_getter = None

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._executor.shutdown(wait=False)

    # -- plumbing ----------------------------------------------------------
    def _next_app(self):
        return self.apps[next(self._rr) % len(self.apps)]

    def _active_plan(self):
        """The process-wide chaos fault plan, if any — resolved lazily so
        serving never imports the chaos subsystem unless one is armed
        (or could be: the getter import is a sys.modules hit after the
        first call)."""
        if self._plan_getter is None:
            from bodywork_tpu.chaos.plan import get_active_plan

            self._plan_getter = get_active_plan
        return self._plan_getter()

    # -- HTTP framing ------------------------------------------------------
    async def _read_request(self, reader):
        """One request off the connection: ``(method, path, headers,
        body)``, or None on a clean EOF between requests (keep-alive
        close)."""
        try:
            blob = await reader.readuntil(b"\r\n\r\n")
        except asyncio.IncompleteReadError as exc:
            if not exc.partial:
                return None  # clean close between keep-alive requests
            raise _BadRequest(400, "truncated request head")
        except asyncio.LimitOverrunError:
            raise _BadRequest(431, "request head too large")
        head = blob.decode("latin-1").split("\r\n")
        try:
            method, target, _version = head[0].split(" ", 2)
        except ValueError:
            raise _BadRequest(400, "malformed request line")
        headers: dict[str, str] = {}
        for line in head[1:]:
            if not line:
                continue
            name, sep, value = line.partition(":")
            if not sep:
                raise _BadRequest(400, f"malformed header line {line!r}")
            headers[name.strip().lower()] = value.strip()
        body = b""
        if "transfer-encoding" in headers:
            raise _BadRequest(400, "chunked request bodies not supported")
        if "content-length" in headers:
            try:
                length = int(headers["content-length"])
            except ValueError:
                raise _BadRequest(400, "malformed Content-Length")
            if length < 0:
                raise _BadRequest(400, "malformed Content-Length")
            if length > MAX_BODY_BYTES:
                raise _BadRequest(413, "request body too large")
            if length:
                try:
                    body = await reader.readexactly(length)
                except asyncio.IncompleteReadError:
                    raise _BadRequest(400, "truncated request body")
        elif method == "POST":
            raise _BadRequest(411, "POST requires Content-Length")
        # strip any query string: the WSGI router matches PATH_INFO only
        path = target.split("?", 1)[0]
        return method, path, headers, body

    @staticmethod
    def _encode_response(
        status: int, body: bytes, content_type: str,
        extra_headers=(), keep_alive: bool = True,
    ) -> bytes:
        reason = _REASONS.get(status, "UNKNOWN")
        lines = [
            f"HTTP/1.1 {status} {reason}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}",
            f"Connection: {'keep-alive' if keep_alive else 'close'}",
        ]
        lines += [f"{name}: {value}" for name, value in extra_headers]
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body

    async def handle_connection(self, reader, writer) -> None:
        """One keep-alive connection: read request -> dispatch -> write
        response, until the peer closes (or asks to)."""
        # a freshly-accepted connection counts as busy immediately: under
        # open-loop load its first request is already in flight toward
        # us, and connections whose handler task has not been scheduled
        # yet ARE the loop's backlog — exactly what the admission depth
        # probe must see. A keep-alive connection idling between
        # requests releases its slot (an idle closed-loop client is not
        # load) and re-takes it when the next request head arrives.
        self._busy_connections += 1
        busy = True
        try:
            while True:
                try:
                    request = await self._read_request(reader)
                except _BadRequest as exc:
                    body = json.dumps({"error": exc.message}).encode()
                    writer.write(self._encode_response(
                        exc.status, body, "application/json", keep_alive=False
                    ))
                    await writer.drain()
                    break
                if request is None:
                    break
                if not busy:
                    self._busy_connections += 1
                    busy = True
                method, path, headers, body = request
                status, payload, content_type, extra = await self._dispatch(
                    method, path, headers, body
                )
                keep_alive = headers.get("connection", "").lower() != "close"
                writer.write(self._encode_response(
                    status, payload, content_type, extra, keep_alive
                ))
                await writer.drain()
                if not keep_alive:
                    break
                self._busy_connections -= 1
                busy = False
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass  # peer went away (or shutdown): nothing to answer
        finally:
            if busy:
                self._busy_connections -= 1
            writer.close()
            try:
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    # -- dispatch ----------------------------------------------------------
    async def _dispatch(self, method: str, path: str, headers: dict,
                        body: bytes):
        """Route one request. Returns ``(status, body_bytes,
        content_type, extra_headers)``. Mirrors ``ScoringApp.__call__``'s
        routing/metrics semantics so dashboards see one request stream
        regardless of engine."""
        app = self._next_app()
        if path.startswith("/score/v1"):
            # chaos consults BEFORE the timed/counted handler, exactly
            # where FlakyScoringMiddleware sits on the WSGI engine
            # (outside the app): an injected response never increments
            # the request counter and injected latency never lands in
            # the scoring-latency histogram, so metrics stay
            # engine-comparable under an active fault plan
            injected, delay, chaos_retry_after = self._chaos_decision(path)
            if delay is not None:
                await asyncio.sleep(delay)
            if injected is not None:
                return (
                    injected,
                    json.dumps(
                        {"error": f"injected fault: HTTP {injected}"}
                    ).encode(),
                    "application/json",
                    (("Retry-After", str(chaos_retry_after)),),
                )
        t0 = time.perf_counter()
        scoring = path in ("/score/v1", "/score/v1/batch")
        # request-scoped tracing: same mint/sampling as the WSGI engine
        # (obs.tracing — the id is a pure function of (seed, body), so
        # one request traces identically on either front-end). Before
        # admission only an ingress traceparent creates a context (one
        # header lookup); minting from the body happens in
        # _score_common AFTER admission, so a shed never pays the hash
        # — the holder lets the handler publish the minted trace back
        # to this frame for the finish/header step.
        tracer = get_tracer()
        traced = scoring and method == "POST" and tracer.enabled
        trace_box: list = [None]
        if traced:
            traceparent = headers.get(TRACEPARENT_HEADER)
            if traceparent is not None and (
                parse_traceparent(traceparent) is not None
            ):
                trace_box[0] = tracer.begin(traceparent, b"")
        if getattr(app, "is_frontend", False):
            # disaggregated mode: scoring enqueues to the dispatcher
            # over the row-queue; healthz/metrics read the app directly
            # (FrontendApp exposes the same payload/metrics-dir seams)
            routes = {
                ("POST", "/score/v1"): self._fe_score_single,
                ("POST", "/score/v1/batch"): self._fe_score_batch,
                ("GET", "/healthz"): self._healthz,
                ("GET", "/metrics"): self._metrics,
            }
        else:
            routes = {
                ("POST", "/score/v1"): self._score_single,
                ("POST", "/score/v1/batch"): self._score_batch,
                ("GET", "/healthz"): self._healthz,
                ("GET", "/metrics"): self._metrics,
            }
        known_path = any(p == path for _m, p in routes)
        try:
            handler = routes.get((method, path))
            if handler is None:
                description = (
                    MethodNotAllowed.description if known_path
                    else NotFound.description
                )
                status, payload, content_type, extra = (
                    405 if known_path else 404,
                    json.dumps({"error": description}).encode(),
                    "application/json",
                    (),
                )
            else:
                status, payload, content_type, extra = await handler(
                    app, body, trace_box if traced else None,
                    headers.get("content-type", ""),
                )
        except Exception as exc:  # don't leak tracebacks to clients
            log.error(f"unhandled error serving {path}: {exc!r}")
            status, payload, content_type, extra = (
                500,
                json.dumps({"error": "internal server error"}).encode(),
                "application/json",
                (),
            )
        app._m_requests.inc(
            route=path if known_path else "unknown", status=str(status)
        )
        trace = trace_box[0]
        if scoring and status == 200:
            app._m_latency.observe(
                time.perf_counter() - t0,
                exemplar=(
                    trace.trace_id
                    if trace is not None and trace.sampled else None
                ),
            )
        if trace is not None:
            tracer.finish(trace, path if known_path else "unknown", status)
            extra = tuple(extra) + ((TRACE_ID_HEADER, trace.trace_id),)
        return status, payload, content_type, extra

    def _chaos_decision(self, path: str):
        """Consult the active fault plan for this scoring request: returns
        ``(injected_status_or_None, latency_delay_s_or_None,
        retry_after_s)``. Same decision streams as the WSGI middleware,
        so a chaos seed replays identical adversity on either engine."""
        plan = self._active_plan()
        if plan is None:
            return None, None, 0.0
        delay = plan.http_latency_delay(path)
        status = plan.http_error(path)
        if status is not None:
            count_shed("chaos")
        return status, delay, plan.http_retry_after_s

    async def _score_common(self, app, body, score, trace_box=None,
                            content_type: str = ""):
        """The shared scoring-request shell: admission, parse, canary
        routing, no-model 503, per-stream accounting — then the
        per-route ``score`` coroutine. (Chaos HTTP injection happens
        upstream in ``_dispatch``, middleware-style; the canary-stream
        latency injection happens HERE, awaited so the loop never
        stalls.) ``trace_box`` is ``_dispatch``'s one-slot trace holder:
        pre-admission it carries only an ingress-traceparent context;
        an ADMITTED request without one mints its deterministic id here
        — after admission, so sheds never pay the body hash."""
        trace = trace_box[0] if trace_box is not None else None
        admission = self.admission
        if admission is not None and not admission.try_admit():
            # shed BEFORE parsing: a refused request costs one counter
            # increment and ~200 bytes of response
            if trace is not None and trace.sampled:
                now = time.perf_counter()
                trace.add(
                    "admission-shed", now, now,
                    queue_depth=admission.queue_depth,
                )
            return (
                429,
                json.dumps(
                    {"error": "server over capacity; request shed"}
                ).encode(),
                "application/json",
                (("Retry-After", str(admission.retry_after_s())),),
            )
        if trace_box is not None and trace is None:
            trace = trace_box[0] = get_tracer().begin(None, body)
        sampled = trace is not None and trace.sampled
        t_admit = time.perf_counter()
        try:
            t0 = time.perf_counter()
            # binary row-batch framing rides the content type (the JSON
            # body stays the default) — same decode helpers as the WSGI
            # engine, so a request's array is identical across framings
            mimetype = (content_type or "").split(";", 1)[0].strip().lower()
            if mimetype == BINARY_CONTENT_TYPE:
                X, message = parse_binary_rows(body)
            else:
                try:
                    payload = json.loads(body) if body else None
                except ValueError:
                    payload = None
                X, message = parse_features(payload)
            t1 = time.perf_counter()
            app._m_parse.observe(t1 - t0)
            if sampled:
                trace.add("parse", t0, t1)
            if message is not None:
                return (
                    400,
                    json.dumps({"error": message}).encode(),
                    "application/json",
                    (),
                )
            # canary-aware routing: same seeded hash as the WSGI engine,
            # so one request routes identically on either front-end
            served, stream = app.route_stream(X)
            if served is None:
                return (
                    503,
                    json.dumps(
                        {"error": "no model loaded yet; retry shortly"}
                    ).encode(),
                    "application/json",
                    (("Retry-After", str(app.retry_after_s())),),
                )
            streamed = app.stream_metrics_active()
            if sampled:
                trace.annotate(
                    stream=stream, routed_model_key=served.model_key
                )
            t_stream = time.perf_counter()
            if streamed:
                app.count_stream_request(served, stream)
            delay = app.canary_chaos_delay(stream)
            if delay is not None:
                await asyncio.sleep(delay)
            try:
                result = await score(app, served, stream, X, trace)
            except Exception:
                if streamed:
                    app.count_stream_error(served, stream)
                raise
            if streamed:
                app.observe_stream_latency(
                    served, stream, time.perf_counter() - t_stream,
                    exemplar=trace.trace_id if sampled else None,
                )
            return result
        finally:
            if admission is not None:
                admission.release(time.perf_counter() - t_admit)

    async def _score_single(self, app, body: bytes, trace_box=None,
                            content_type: str = ""):
        async def score(app, served, stream, X, trace):
            sampled = trace is not None and trace.sampled
            X = np.array(X, ndmin=2)  # scalar -> (1, 1), as the reference
            loop = asyncio.get_running_loop()
            prediction0 = None
            if app.batcher is not None and X.shape[0] == 1:
                future = loop.create_future()

                def _resolve(sub) -> None:
                    # dispatcher thread -> event loop handoff; the loop
                    # may already be gone on shutdown
                    def _set() -> None:
                        if future.cancelled():
                            return
                        if sub.error is not None:
                            future.set_exception(sub.error)
                        else:
                            future.set_result(sub.result)

                    try:
                        loop.call_soon_threadsafe(_set)
                    except RuntimeError:
                        pass

                try:
                    app.batcher.submit_nowait(
                        served, X[0], on_done=_resolve,
                        trace=trace if sampled else None,
                    )
                except CoalescerSaturated:
                    app._m_fallbacks.inc()
                else:
                    try:
                        prediction0 = await asyncio.wait_for(
                            future, COALESCE_TIMEOUT_S
                        )
                    except asyncio.TimeoutError:
                        return (
                            500,
                            json.dumps(
                                {"error": "internal server error"}
                            ).encode(),
                            "application/json",
                            (),
                        )
            if prediction0 is None:
                t0 = time.perf_counter()
                predictions = await loop.run_in_executor(
                    self._executor, served.predictor.predict, X
                )
                prediction0 = float(predictions[0])
                t1 = time.perf_counter()
                app._m_dispatch.observe(t1 - t0)
                if sampled:
                    trace.add("device-dispatch", t0, t1, coalesced=False)
            # prediction-sanity firewall: the cheap precheck runs inline
            # (pure numpy on one float); the fallback dispatch — a device
            # call — rides the executor so the loop never blocks on it
            reason = app.sanity_reason(served, prediction0)
            if reason is not None:
                served, fallback = await loop.run_in_executor(
                    self._executor,
                    app.firewall, served, stream, X, prediction0, reason,
                    trace,
                )
                prediction0 = float(np.asarray(fallback).ravel()[0])
            t0 = time.perf_counter()
            # pre-serialized framing (serve.wire.SingleResponseTemplate,
            # cached on the answering bundle): byte-identical to the
            # full json.dumps(single_score_payload(...)) it replaces
            payload = served.single_template.render(prediction0)
            t1 = time.perf_counter()
            app._m_serialize.observe(t1 - t0)
            if sampled:
                trace.add("serialize", t0, t1)
            extra = (
                ((MODEL_KEY_HEADER, served.model_key),)
                if served.model_key else ()
            )
            return 200, payload, "application/json", extra

        return await self._score_common(app, body, score, trace_box,
                                        content_type)

    async def _score_batch(self, app, body: bytes, trace_box=None,
                           content_type: str = ""):
        async def score(app, served, stream, X, trace):
            sampled = trace is not None and trace.sampled
            if X.ndim == 0:
                X = X[None]
            loop = asyncio.get_running_loop()
            t0 = time.perf_counter()
            predictions = await loop.run_in_executor(
                self._executor, served.predictor.predict, X
            )
            t1 = time.perf_counter()
            app._m_dispatch.observe(t1 - t0)
            if sampled:
                trace.add("device-dispatch", t0, t1, coalesced=False)
            reason = app.sanity_reason(served, predictions)
            if reason is not None:
                served, predictions = await loop.run_in_executor(
                    self._executor,
                    app.firewall, served, stream, X, predictions, reason,
                    trace,
                )
            t0 = time.perf_counter()
            # pre-serialized framing (serve.wire.BatchResponseTemplate,
            # cached on the answering bundle): byte-identical to the
            # full json.dumps(batch_score_payload(...)) it replaces
            payload = served.batch_template.render(predictions)
            t1 = time.perf_counter()
            app._m_serialize.observe(t1 - t0)
            if sampled:
                trace.add("serialize", t0, t1)
            extra = (
                ((MODEL_KEY_HEADER, served.model_key),)
                if served.model_key else ()
            )
            return 200, payload, "application/json", extra

        return await self._score_common(app, body, score, trace_box,
                                        content_type)

    # -- disaggregated front-end handlers ----------------------------------
    async def _fe_score_single(self, app, body: bytes, trace_box=None,
                               content_type: str = ""):
        return await self._fe_score(app, body, trace_box, content_type,
                                    single=True)

    async def _fe_score_batch(self, app, body: bytes, trace_box=None,
                              content_type: str = ""):
        return await self._fe_score(app, body, trace_box, content_type,
                                    single=False)

    async def _fe_score(self, app, body, trace_box, content_type,
                        single: bool):
        """The disaggregated scoring shell: admission (shed BEFORE
        parse, as everywhere), parse via the shared wire helpers, then a
        row-queue submit bridged to the loop exactly like a coalescer
        submission — the dispatcher's reply renders through the
        FrontendApp core, so responses are byte-identical to the
        in-process engines'."""
        trace = trace_box[0] if trace_box is not None else None
        admission = self.admission
        if admission is not None and not admission.try_admit():
            if trace is not None and trace.sampled:
                now = time.perf_counter()
                trace.add(
                    "admission-shed", now, now,
                    queue_depth=admission.queue_depth,
                )
            status, payload, extra = app.shed_parts()
            return status, payload, "application/json", extra
        if trace_box is not None and trace is None:
            trace = trace_box[0] = get_tracer().begin(None, body)
        sampled = trace is not None and trace.sampled
        t_admit = time.perf_counter()
        try:
            t0 = time.perf_counter()
            X, message = app.parse_rows(body, content_type)
            t1 = time.perf_counter()
            app._m_parse.observe(t1 - t0)
            if sampled:
                trace.add("parse", t0, t1)
            if message is not None:
                return (
                    400,
                    json.dumps({"error": message}).encode(),
                    "application/json",
                    (),
                )
            loop = asyncio.get_running_loop()
            future = loop.create_future()

            def _resolve(outcome) -> None:
                # reader thread -> event loop handoff; the loop may
                # already be gone on shutdown
                def _set() -> None:
                    if future.cancelled():
                        return
                    if isinstance(outcome, Exception):
                        future.set_exception(outcome)
                    else:
                        future.set_result(outcome)

                try:
                    loop.call_soon_threadsafe(_set)
                except RuntimeError:
                    pass

            t_submit = time.perf_counter()
            try:
                app.submit(
                    X, single, _resolve,
                    trace_id=trace.trace_id if sampled else None,
                )
            except DispatcherUnavailable:
                status, payload, extra = app.unavailable_parts()
                return status, payload, "application/json", extra
            except SlotsExhausted:
                count_shed("rowqueue")
                status, payload, extra = app.shed_parts()
                return status, payload, "application/json", extra
            try:
                reply = await asyncio.wait_for(future, COALESCE_TIMEOUT_S)
            except DispatcherUnavailable:
                # died mid-request: the epoch bump failed the wait
                status, payload, extra = app.unavailable_parts()
                return status, payload, "application/json", extra
            except asyncio.TimeoutError:
                return (
                    500,
                    json.dumps({"error": "internal server error"}).encode(),
                    "application/json",
                    (),
                )
            if sampled:
                trace.add("rowqueue", t_submit, time.perf_counter())
            status, payload, extra = app.render_reply(reply, single)
            return status, payload, "application/json", extra
        finally:
            if admission is not None:
                admission.release(time.perf_counter() - t_admit)

    async def _healthz(self, app, body: bytes, trace_box=None,
                       content_type: str = ""):
        payload, status, retry_after = app.healthz_payload()
        extra = (
            (("Retry-After", str(retry_after)),) if retry_after is not None
            else ()
        )
        return status, json.dumps(payload).encode(), "application/json", extra

    async def _metrics(self, app, body: bytes, trace_box=None,
                       content_type: str = ""):
        from bodywork_tpu.obs.multiproc import aggregated_render

        loop = asyncio.get_running_loop()
        text = await loop.run_in_executor(
            self._executor, aggregated_render, get_registry(), app.metrics_dir
        )
        return (
            200,
            text.encode(),
            "text/plain; version=0.0.4; charset=utf-8",
            (),
        )


class AioServiceHandle:
    """A scoring service on an asyncio event loop, with the
    :class:`~bodywork_tpu.serve.server.ServiceHandle` lifecycle: the
    loop runs on a background thread (``start``) or in the calling
    thread (``serve_forever``); ``stop`` is thread-safe and runs the
    registered cleanups (watcher stops, coalescer drains)."""

    def __init__(
        self,
        app,
        host: str = "127.0.0.1",
        port: int = 5000,
        admission=None,
        sock: socket.socket | None = None,
    ):
        apps = list(app) if isinstance(app, (list, tuple)) else [app]
        self.server = AioScoringServer(apps, admission=admission)
        #: the in-process entry tests and the chaos harness use
        #: (``.test_client()``); scoring through it bypasses the socket
        #: front exactly as it does for the threaded engine
        self.app = app if not isinstance(app, (list, tuple)) else apps[0]
        self.host = host
        self.port = port
        self._sock = sock
        self._cleanups: list = []
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stop_event: asyncio.Event | None = None
        self._ready = threading.Event()
        self._startup_error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._run_loop, name="aio-scoring-service", daemon=True
        )

    # -- ServiceHandle interface -------------------------------------------
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/score/v1"

    def add_cleanup(self, fn) -> None:
        self._cleanups.append(fn)

    async def _serve_main(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        try:
            if self._sock is not None:
                server = await asyncio.start_server(
                    self.server.handle_connection,
                    sock=self._sock,
                    limit=MAX_HEADER_BYTES,
                )
            else:
                server = await asyncio.start_server(
                    self.server.handle_connection,
                    self.host,
                    self.port,
                    limit=MAX_HEADER_BYTES,
                )
            self.port = server.sockets[0].getsockname()[1]
        except BaseException as exc:
            self._startup_error = exc
            self._ready.set()
            raise
        self._ready.set()
        try:
            async with server:
                await self._stop_event.wait()
        finally:
            self.server.close()

    def _run_loop(self) -> None:
        try:
            asyncio.run(self._serve_main())
        except BaseException as exc:
            if self._startup_error is None and not self._ready.is_set():
                self._startup_error = exc
                self._ready.set()
                return  # start()/serve_forever() surface it as startup failure
            # post-startup crash: propagate. In serve_forever (pod
            # entrypoint) this exits the process non-zero — a crashed
            # service must never report success to its supervisor (the
            # ServiceHandle invariant); on the background thread it dies
            # loudly via the thread excepthook instead of silently.
            raise

    def start(self) -> "AioServiceHandle":
        self._thread.start()
        self._ready.wait(timeout=30)
        if self._startup_error is not None:
            raise RuntimeError(
                f"asyncio scoring service failed to start: "
                f"{self._startup_error!r}"
            ) from self._startup_error
        if not self._ready.is_set():
            raise TimeoutError("asyncio scoring service not ready within 30s")
        log.info(f"scoring service (aio engine) listening on {self.url}")
        return self

    def serve_forever(self) -> None:
        """Serve in the calling thread (pod-entrypoint mode)."""
        log.info(f"scoring service (aio engine) starting on {self.url}")
        self._run_loop()
        if self._startup_error is not None:
            raise RuntimeError(
                f"asyncio scoring service failed: {self._startup_error!r}"
            ) from self._startup_error

    def wait(self) -> None:
        self._thread.join()

    def stop(self) -> None:
        for fn in self._cleanups:
            fn()
        loop, stop_event = self._loop, self._stop_event
        if loop is not None and stop_event is not None:
            try:
                loop.call_soon_threadsafe(stop_event.set)
            except RuntimeError:
                pass  # loop already closed
        if self._thread.ident is not None:
            self._thread.join(timeout=10)
        log.info("scoring service (aio engine) stopped")

    def __enter__(self) -> "AioServiceHandle":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
