"""HTTP scoring service (reference C3, ``stage_2_serve_model.py``).

The public HTTP contract is frozen to the reference's API:

    POST /score/v1   {"X": 50}  ->  {"prediction": 54.57..., "model_info": "..."}

(``stage_2_serve_model.py:11-21,73-80``). The input is coerced with
``np.array(features, ndmin=2)`` semantics exactly as the reference does, so a
scalar scores one instance — but the response additionally carries
``model_date`` (the artefact version being served), fixing the reference's
inability to tell *which* model answered.

Implementation: a self-contained WSGI application on werkzeug primitives
(the reference uses the Flask dev server; this framework owns its serving
layer — the same app object runs under the threaded dev server, a test
client, or any production WSGI container).

TPU-native additions beyond parity:

- ``POST /score/v1/batch`` — score many rows in one request through the
  shape-bucketed predictor (BASELINE.json config 4: 1k-row predict requests).
- ``GET /healthz`` — readiness probe for the orchestrator (the reference
  relies on k8s TCP probes only). Carries the degraded-mode channel: a
  service serving its last-good model after a failed hot reload answers
  200 with ``degraded: true`` + reason (it IS serving — readiness must
  keep routing traffic; the flag and the
  ``bodywork_tpu_serve_degraded_state`` gauge are the operator signal),
  while a service with no model loaded yet answers 503 + ``Retry-After``.
- degraded-mode serving: an app may boot with NO model (``model=None`` —
  e.g. ``serve --reload-interval`` against a store whose first
  checkpoint has not landed). Scoring answers 503 + ``Retry-After``
  instead of the process dying, and the first successful
  :meth:`ScoringApp.swap_model` brings it live.
- opt-in cross-request micro-batching (``serve.batcher``): concurrent
  single-row ``/score/v1`` requests coalesce into shared padded device
  calls, so per-worker throughput under load scales with bucket size
  instead of request count. Off by default; responses are byte-identical
  either way (each output row depends only on its own input row).

Params live in TPU HBM from model load; per-request work is one padded
device call (shared across requests when the coalescer is on).
"""
from __future__ import annotations

import json
import time
from datetime import date

import numpy as np
from werkzeug.exceptions import HTTPException, MethodNotAllowed, NotFound
from werkzeug.wrappers import Request, Response

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.obs import get_registry
from bodywork_tpu.serve.batcher import CoalescerSaturated
from bodywork_tpu.serve.predictor import PaddedPredictor
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.app")

#: parse/serialize are µs-scale host work — the default latency buckets
#: would dump them all into the first bucket
_FAST_PHASE_BUCKETS = (
    0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1,
)

#: routes whose successful requests count into the scoring-latency
#: histogram (the "requests scored" accounting the bench cross-checks)
_SCORING_ROUTES = ("/score/v1", "/score/v1/batch")

#: Retry-After hint (seconds) on 503s from a not-yet-loaded service
#: WITHOUT an admission controller — long enough for a checkpoint-watcher
#: poll to land a model, short enough that a retrying client converges
#: quickly. With admission enabled, every backpressure response (shed
#: 429 AND degraded 503) instead derives its Retry-After from the EWMA
#: queue-delay estimate (``serve.admission``), clamped — one consistent
#: numeric hint per service.
RETRY_AFTER_S = 5


def _json_response(payload: dict, status: int = 200) -> Response:
    return Response(
        json.dumps(payload), status=status, mimetype="application/json"
    )


def parse_features(payload):
    """Validate a decoded request body into a float32 feature array.

    Returns ``(X, None)`` or ``(None, error_message)``. Factored out of
    the WSGI handler so BOTH front-ends (threaded werkzeug and the
    asyncio event loop, ``serve.aio``) validate with the same code and
    answer malformed input with byte-identical 400 bodies."""
    if not isinstance(payload, dict) or "X" not in payload:
        return None, "request body must be a JSON object with an 'X' field"
    try:
        X = np.asarray(payload["X"], dtype=np.float32)
    except (TypeError, ValueError):
        return None, "'X' must be numeric"
    if X.size == 0:
        return None, "'X' must be non-empty"
    if not np.all(np.isfinite(X)):
        return None, "'X' must be finite"
    return X, None


def single_score_payload(served, prediction0: float) -> dict:
    """The ``/score/v1`` response body. One constructor for both
    front-ends: key order and value formatting are what make coalesced
    responses byte-identical across engines."""
    return {
        "prediction": prediction0,
        "model_info": served.model_info,
        "model_date": served.model_date,
    }


def batch_score_payload(served, predictions) -> dict:
    """The ``/score/v1/batch`` response body (see
    :func:`single_score_payload` for why this is factored)."""
    return {
        "predictions": [float(p) for p in predictions],
        "n": int(len(predictions)),
        "model_info": served.model_info,
        "model_date": served.model_date,
    }


class _Served:
    """One served model: predictor + identity, swapped as a unit so a
    request can never pair one model's prediction with another's info.
    ``model_key`` is the artefact key the model was loaded from and
    ``source`` how it was resolved (``"production"`` via the registry
    alias, ``"latest"`` via the date-key fallback, None when the caller
    didn't say) — surfaced on ``/healthz`` and the served-model info
    gauge so an operator can see WHAT serves and under WHOSE authority."""

    __slots__ = ("predictor", "model_info", "model_date", "model_key", "source")

    def __init__(
        self,
        predictor,
        model_info: str,
        model_date: str | None,
        model_key: str | None = None,
        source: str | None = None,
    ):
        self.predictor = predictor
        self.model_info = model_info
        self.model_date = model_date
        self.model_key = model_key
        self.source = source


class ScoringApp:
    """WSGI scoring application over a shape-bucketed predictor.

    The served model is held as one immutable bundle behind a single
    attribute, so :meth:`swap_model` (hot reload) is an atomic pointer
    swap under the GIL — in-flight requests finish on the model they
    started with."""

    def __init__(
        self,
        model: Regressor | None,
        model_date: date | None = None,
        buckets: tuple[int, ...] | None = None,
        predictor=None,
        batcher=None,
        metrics_dir: str | None = None,
        model_key: str | None = None,
        model_source: str | None = None,
        admission=None,
    ):
        if model is None:
            # degraded boot: no checkpoint exists yet. Scoring answers
            # 503 + Retry-After until the first swap_model (the
            # checkpoint watcher's job) — the server never dies for
            # having started before its first artefact.
            assert predictor is None, "a predictor needs a model"
            self._served = None
        else:
            # a custom predictor (e.g. parallel.DataParallelPredictor
            # over a device mesh) replaces the single-device bucketed
            # default
            predictor = predictor or (
                PaddedPredictor(model, buckets) if buckets else PaddedPredictor(model)
            )
            self._served = _Served(
                predictor, model.info,
                str(model_date) if model_date else None,
                model_key=model_key, source=model_source,
            )
        #: reason the service is degraded (serving last-good after a
        #: failed reload), or None when healthy; surfaced in /healthz
        self._degraded_reason: str | None = None
        # opt-in request coalescer (serve.batcher.RequestCoalescer);
        # None = every request dispatches its own padded device call
        self.batcher = batcher
        #: opt-in admission controller (serve.admission): scoring POSTs
        #: are admitted against its bounded pending budget BEFORE the
        #: body is even parsed — a shed costs a counter bump and a tiny
        #: 429, never coalescer or device work. None = admit everything
        #: (the pre-admission behaviour, byte-identical).
        self.admission = admission
        #: shared snapshot dir for multi-worker /metrics aggregation
        #: (serve.multiproc); None = this process's registry alone
        self.metrics_dir = metrics_dir
        # hot-path phase instrumentation (obs.registry; the registry is
        # process-global, so replica apps in one process share metrics —
        # exactly as one k8s pod exposes one scrape target)
        reg = get_registry()
        self._m_requests = reg.counter(
            "bodywork_tpu_http_requests_total",
            "HTTP requests served, by route and status",
        )
        self._m_latency = reg.histogram(
            "bodywork_tpu_scoring_latency_seconds",
            "End-to-end handler time of successful scoring requests",
        )
        self._m_parse = reg.histogram(
            "bodywork_tpu_request_parse_seconds",
            "Request-parse phase: JSON body -> validated feature array",
            buckets=_FAST_PHASE_BUCKETS,
        )
        self._m_dispatch = reg.histogram(
            "bodywork_tpu_device_dispatch_seconds",
            "Device-dispatch phase: one padded predictor call",
        )
        self._m_serialize = reg.histogram(
            "bodywork_tpu_response_serialize_seconds",
            "Serialization phase: prediction -> JSON response",
            buckets=_FAST_PHASE_BUCKETS,
        )
        self._m_swaps = reg.counter(
            "bodywork_tpu_model_hot_swaps_total",
            "Served-model hot swaps (serve.reload checkpoint watcher)",
        )
        self._m_fallbacks = reg.counter(
            "bodywork_tpu_coalescer_fallback_total",
            "Requests degraded to a direct dispatch (coalescer saturated)",
        )
        self._g_degraded = reg.gauge(
            "bodywork_tpu_serve_degraded_state",
            "Serving degradation: 0=healthy, 1=serving last-good model "
            "after a failed reload, 2=no model loaded",
            aggregate="max",
        )
        self._g_degraded.set(2.0 if self._served is None else 0.0)
        # served-model-version info gauge: the CURRENT served artefact's
        # sample is 1 and its resolution source rides as a label
        # ("production" = registry alias, "latest" = date-key fallback);
        # a swap zeroes the superseded sample so a scrape shows exactly
        # one live version per process
        self._g_model_version = reg.gauge(
            "bodywork_tpu_serve_model_version_info",
            "Served model version: 1 on the (model_key, source) sample "
            "currently serving, 0 on superseded ones",
            aggregate="max",
        )
        self._model_version_labels: dict | None = None
        self._record_model_version()
        self._routes = {
            ("POST", "/score/v1"): self.score_data_instance,
            ("POST", "/score/v1/batch"): self.score_batch,
            ("GET", "/healthz"): self.healthz,
            ("GET", "/metrics"): self.metrics_endpoint,
        }

    def _record_model_version(self) -> None:
        served = self._served
        if served is None or served.model_key is None:
            return
        labels = {
            "model_key": served.model_key,
            "source": served.source or "unspecified",
        }
        old = self._model_version_labels
        if old is not None and old != labels:
            self._g_model_version.set(0.0, **old)
        self._g_model_version.set(1.0, **labels)
        self._model_version_labels = labels

    # -- served-model access (single read point for atomic swaps) ----------
    @property
    def served_bundle(self):
        """The immutable served-model bundle (predictor + identity), or
        None before the first model. ONE read is stable across a hot
        swap — the asyncio front-end (serve.aio) scores against this
        exactly as the WSGI handlers below do."""
        return self._served

    @property
    def predictor(self):
        served = self._served
        return None if served is None else served.predictor

    @property
    def model_info(self) -> str | None:
        served = self._served
        return None if served is None else served.model_info

    @property
    def model_date(self) -> str | None:
        served = self._served
        return None if served is None else served.model_date

    @property
    def model_key(self) -> str | None:
        served = self._served
        return None if served is None else served.model_key

    @property
    def model_source(self) -> str | None:
        served = self._served
        return None if served is None else served.source

    # -- degraded-mode channel (serve.reload drives it) --------------------
    def set_degraded(self, reason: str) -> None:
        """Flag the service as serving its last-good model (a hot reload
        failed). The service keeps answering — the flag rides /healthz
        and the state gauge so operators see the stall."""
        self._degraded_reason = reason
        if self._served is not None:
            self._g_degraded.set(1.0)

    def clear_degraded(self) -> None:
        self._degraded_reason = None
        self._g_degraded.set(0.0 if self._served is not None else 2.0)

    def swap_model(
        self,
        model: Regressor,
        model_date: date | None = None,
        predictor=None,
        model_key: str | None = None,
        model_source: str | None = None,
    ) -> None:
        """Atomically replace the served model (hot reload). The caller is
        responsible for warming the new predictor OFF the request path
        first (``serve.reload.CheckpointWatcher`` does). A successful
        swap clears the degraded flag — and brings a model-less app
        (degraded boot) live. ``model_key``/``model_source`` update the
        /healthz identity and the served-model-version info gauge."""
        if predictor is None:
            old = self._served
            predictor = (
                PaddedPredictor(model, old.predictor.buckets)
                if old is not None
                else PaddedPredictor(model)
            )
        self._served = _Served(
            predictor, model.info, str(model_date) if model_date else None,
            model_key=model_key, source=model_source,
        )
        self._record_model_version()
        if self.batcher is not None:
            # the coalescer's bundle-grouping already guarantees no batch
            # mixes generations; draining here additionally flushes every
            # ALREADY-ENQUEUED old-model row before the swap returns.
            # (Request threads that read the old bundle but have not yet
            # enqueued finish on the model they started with — the same
            # in-flight semantics as the unbatched app above.)
            if not self.batcher.drain():
                # correctness is unaffected (queued old-bundle rows still
                # score on their own generation) — but the prompt-flush
                # promise did not hold, and silence would hide a wedged
                # dispatcher
                log.warning(
                    "hot-swap proceeded before the request coalescer "
                    "fully drained; old-model rows may still be in flight"
                )
        self._m_swaps.inc()
        self.clear_degraded()
        log.info(f"hot-swapped served model -> {model.info} ({model_date})")

    def close(self) -> None:
        """Release app-owned background resources (the coalescer's
        dispatcher thread). Idempotent; the app still serves afterwards,
        just without coalescing."""
        if self.batcher is not None:
            self.batcher.stop()

    # -- WSGI plumbing -----------------------------------------------------
    def __call__(self, environ, start_response):
        request = Request(environ)
        t0 = time.perf_counter()
        # admission runs FIRST — before parsing, before the no-model
        # check, before anything that costs per-request work. A shed
        # request must leave zero footprint beyond its counter: that is
        # the property that keeps an overloaded server serving its
        # admitted queue instead of drowning with it.
        admission = self.admission
        admitted = False
        if (
            admission is not None
            and request.method == "POST"
            and request.path in _SCORING_ROUTES
        ):
            if not admission.try_admit():
                response = self.shed_response()
                self._m_requests.inc(
                    route=request.path, status=str(response.status_code)
                )
                return response(environ, start_response)
            admitted = True
        try:
            handler = self._routes.get((request.method, request.path))
            if handler is None:
                if any(path == request.path for _m, path in self._routes):
                    raise MethodNotAllowed()
                raise NotFound()
            response = handler(request)
        except HTTPException as exc:
            response = _json_response({"error": exc.description}, exc.code)
        except Exception as exc:  # don't leak tracebacks to clients
            log.error(f"unhandled error serving {request.path}: {exc!r}")
            response = _json_response({"error": "internal server error"}, 500)
        finally:
            if admitted:
                # the observed delay (admission -> response ready) is
                # the EWMA sample behind every Retry-After hint
                admission.release(time.perf_counter() - t0)
        route = (
            request.path
            if any(path == request.path for _m, path in self._routes)
            else "unknown"
        )
        self._m_requests.inc(route=route, status=str(response.status_code))
        if request.path in _SCORING_ROUTES and response.status_code == 200:
            # count == requests successfully scored (the invariant the
            # bench cross-checks against client-side latencies)
            self._m_latency.observe(time.perf_counter() - t0)
        return response(environ, start_response)

    def test_client(self):
        from werkzeug.test import Client

        return Client(self)

    # -- shared parsing ----------------------------------------------------
    def _features_from(self, request: Request):
        t0 = time.perf_counter()
        try:
            return self._parse_features(request)
        finally:
            self._m_parse.observe(time.perf_counter() - t0)

    def _parse_features(self, request: Request):
        X, message = parse_features(request.get_json(silent=True))
        if message is not None:
            return None, _json_response({"error": message}, 400)
        return X, None

    def retry_after_s(self) -> int:
        """The ONE numeric Retry-After every backpressure response from
        this app carries (shed 429s and degraded/no-model 503s): the
        admission layer's clamped EWMA estimate when admission is on,
        the static watcher-poll default otherwise."""
        if self.admission is not None:
            return self.admission.retry_after_s()
        return RETRY_AFTER_S

    def shed_response(self) -> Response:
        """The admission-shed 429 (load shedding, serve.admission)."""
        response = _json_response(
            {"error": "server over capacity; request shed"}, 429
        )
        response.headers["Retry-After"] = str(self.retry_after_s())
        return response

    def _no_model_response(self) -> Response:
        response = _json_response(
            {"error": "no model loaded yet; retry shortly"}, 503
        )
        response.headers["Retry-After"] = str(self.retry_after_s())
        return response

    # -- routes ------------------------------------------------------------
    def score_data_instance(self, request: Request) -> Response:
        """Single-instance scoring; reference-parity contract
        (``stage_2:73-80``)."""
        X, err = self._features_from(request)
        if err is not None:
            # validation precedes the no-model check: a malformed request
            # can never succeed, so it must get its non-retryable 400
            # even from a model-less server (a 503 would make clients
            # burn their whole Retry-After budget on it)
            return err
        served = self._served  # one read: stable across a hot swap
        if served is None:
            return self._no_model_response()
        X = np.array(X, ndmin=2)  # scalar -> (1, 1), as the reference
        prediction0 = None
        if self.batcher is not None and X.shape[0] == 1:
            try:
                # the submission carries ITS served bundle: the batch it
                # lands in is built from one model generation only, and
                # the response pairs that generation's prediction with
                # that generation's identity fields below. Queue-wait and
                # device-dispatch phases are recorded by the coalescer.
                prediction0 = self.batcher.submit(served, X[0])
            except CoalescerSaturated:
                # overload/shutdown: degrade to a direct dispatch
                self._m_fallbacks.inc()
        if prediction0 is None:
            t0 = time.perf_counter()
            prediction0 = float(served.predictor.predict(X)[0])
            self._m_dispatch.observe(time.perf_counter() - t0)
        t0 = time.perf_counter()
        response = _json_response(single_score_payload(served, prediction0))
        self._m_serialize.observe(time.perf_counter() - t0)
        return response

    def score_batch(self, request: Request) -> Response:
        """Batched scoring: one padded device call for up to bucket-size rows."""
        X, err = self._features_from(request)
        if err is not None:
            return err  # 400 before 503: see score_data_instance
        served = self._served  # one read: stable across a hot swap
        if served is None:
            return self._no_model_response()
        if X.ndim == 0:
            X = X[None]
        t0 = time.perf_counter()
        predictions = served.predictor.predict(X)
        self._m_dispatch.observe(time.perf_counter() - t0)
        t0 = time.perf_counter()
        response = _json_response(batch_score_payload(served, predictions))
        self._m_serialize.observe(time.perf_counter() - t0)
        return response

    def healthz_payload(self) -> tuple[dict, int, int | None]:
        """``(payload, status, retry_after_s-or-None)`` — the health
        document BOTH front-ends serve (the threaded route below, the
        asyncio engine directly), so operators see one schema per
        service regardless of engine."""
        served = self._served  # one read: stable across a hot swap
        admission = self.admission
        # queue depth surfaces even without admission: the coalescer's
        # pending rows are the next-best saturation signal
        if admission is not None:
            queue_depth = admission.queue_depth
            admission_state = admission.state()
        else:
            queue_depth = (
                self.batcher.pending_depth() if self.batcher is not None else 0
            )
            admission_state = None
        if served is None:
            return (
                {
                    "status": "no model loaded",
                    "degraded": True,
                    "reason": "no model has been loaded yet",
                    "model_info": None,
                    "model_date": None,
                    "model_key": None,
                    "model_source": None,
                    "queue_depth": queue_depth,
                    "admission": admission_state,
                },
                503,
                self.retry_after_s(),
            )
        reason = self._degraded_reason
        payload = {
            # 200 + "ok" even when degraded: the service IS serving, so
            # readiness must keep routing; the flag/reason (and the
            # state gauge) carry the operator signal
            "status": "ok",
            "model_info": served.model_info,
            "model_date": served.model_date,
            # WHAT serves and under WHOSE authority: the artefact key
            # plus how it was resolved — "production" (registry alias,
            # gated), "latest" (registry-less date-key fallback), None
            # (caller never said). A degraded service additionally
            # carries the degraded flag + reason below.
            "model_key": served.model_key,
            "model_source": served.source,
            "degraded": reason is not None,
            # saturation channel (serve.admission): current depth plus —
            # when admission is on — budget, shedding state, and the
            # Retry-After currently handed out. Shedding deliberately
            # does NOT flip the 200: an at-budget replica is doing its
            # job; pulling it from the endpoints would dogpile its load
            # onto the siblings (readiness semantics, pipeline/k8s.py).
            "queue_depth": queue_depth,
            "admission": admission_state,
        }
        if reason is not None:
            payload["reason"] = reason
        return payload, 200, None

    def healthz(self, request: Request) -> Response:
        payload, status, retry_after = self.healthz_payload()
        response = _json_response(payload, status)
        if retry_after is not None:
            response.headers["Retry-After"] = str(retry_after)
        return response

    def metrics_endpoint(self, request: Request) -> Response:
        """Prometheus text exposition of this process's registry, merged
        with sibling workers' flushed snapshots when ``metrics_dir`` is
        set (``serve --workers N --metrics`` exposes ONE coherent view
        regardless of which replica the kernel hands the scrape to)."""
        from bodywork_tpu.obs.multiproc import aggregated_render

        return Response(
            aggregated_render(get_registry(), self.metrics_dir),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )


def create_app(
    model: Regressor | None,
    model_date: date | None = None,
    buckets: tuple[int, ...] | None = None,
    warmup: bool = True,
    warmup_sync: bool = True,
    predictor=None,
    batch_window_ms: float | None = None,
    batch_max_rows: int | None = None,
    metrics_dir: str | None = None,
    model_key: str | None = None,
    model_source: str | None = None,
    admission=None,
) -> ScoringApp:
    """``batch_window_ms`` > 0 opts into cross-request micro-batching
    (``serve.batcher``): concurrent single-row ``/score/v1`` requests
    coalesce into one padded device call, flushed when ``batch_max_rows``
    accumulate or the window elapses, whichever first.

    ``metrics_dir`` points ``GET /metrics`` at a shared snapshot
    directory so multi-process replicas expose one aggregated view
    (``serve.multiproc`` wires it; single-process serving needs nothing —
    the endpoint always exposes this process's registry).

    ``admission`` (serve.admission.AdmissionController) opts into load
    shedding: scoring requests beyond its pending budget answer 429 +
    Retry-After before any work happens. Replica apps sharing one port
    should share ONE controller (one budget per serving process)."""
    batcher = None
    if batch_window_ms and batch_window_ms > 0:
        from bodywork_tpu.serve.batcher import DEFAULT_MAX_ROWS, RequestCoalescer

        batcher = RequestCoalescer(
            window_ms=batch_window_ms,
            max_rows=batch_max_rows or DEFAULT_MAX_ROWS,
        ).start()
    app = ScoringApp(model, model_date, buckets, predictor=predictor,
                     batcher=batcher, metrics_dir=metrics_dir,
                     model_key=model_key, model_source=model_source,
                     admission=admission)
    if warmup and app.predictor is not None:
        app.predictor.warmup(sync=warmup_sync)
    return app
