"""HTTP scoring service (reference C3, ``stage_2_serve_model.py``).

The public HTTP contract is frozen to the reference's API:

    POST /score/v1   {"X": 50}  ->  {"prediction": 54.57..., "model_info": "..."}

(``stage_2_serve_model.py:11-21,73-80``). The input is coerced with
``np.array(features, ndmin=2)`` semantics exactly as the reference does, so a
scalar scores one instance — but the response additionally carries
``model_date`` (the artefact version being served), fixing the reference's
inability to tell *which* model answered.

Implementation: a self-contained WSGI application on werkzeug primitives
(the reference uses the Flask dev server; this framework owns its serving
layer — the same app object runs under the threaded dev server, a test
client, or any production WSGI container).

TPU-native additions beyond parity:

- ``POST /score/v1/batch`` — score many rows in one request through the
  shape-bucketed predictor (BASELINE.json config 4: 1k-row predict requests).
- ``GET /healthz`` — readiness probe for the orchestrator (the reference
  relies on k8s TCP probes only). Carries the degraded-mode channel: a
  service serving its last-good model after a failed hot reload answers
  200 with ``degraded: true`` + reason (it IS serving — readiness must
  keep routing traffic; the flag and the
  ``bodywork_tpu_serve_degraded_state`` gauge are the operator signal),
  while a service with no model loaded yet answers 503 + ``Retry-After``.
- degraded-mode serving: an app may boot with NO model (``model=None`` —
  e.g. ``serve --reload-interval`` against a store whose first
  checkpoint has not landed). Scoring answers 503 + ``Retry-After``
  instead of the process dying, and the first successful
  :meth:`ScoringApp.swap_model` brings it live.
- opt-in cross-request micro-batching (``serve.batcher``): concurrent
  single-row ``/score/v1`` requests coalesce into shared padded device
  calls, so per-worker throughput under load scales with bucket size
  instead of request count. Off by default; responses are byte-identical
  either way (each output row depends only on its own input row).

Params live in TPU HBM from model load; per-request work is one padded
device call (shared across requests when the coalescer is on).
"""
from __future__ import annotations

import hashlib
import json
import time
from datetime import date

import numpy as np
from werkzeug.exceptions import HTTPException, MethodNotAllowed, NotFound
from werkzeug.wrappers import Request, Response

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.obs import get_registry
from bodywork_tpu.obs.tracing import (
    TRACE_ID_HEADER,
    TRACEPARENT_HEADER,
    get_tracer,
    parse_traceparent,
    reset_active_span,
    set_active_span,
)
from bodywork_tpu.serve.batcher import CoalescerSaturated
from bodywork_tpu.serve.predictor import PaddedPredictor

# the wire formats (request validation, response payloads, binary
# framing, the pre-serialized response template) live in serve.wire — a
# JAX-free leaf the disaggregated front-end processes import without
# paying the accelerator runtime. Re-exported here because this module
# is their historical home and both engines (and many tests) import
# them from serve.app.
from bodywork_tpu.serve.wire import (  # noqa: F401  (re-exports)
    BINARY_CONTENT_TYPE,
    MODEL_KEY_HEADER,
    BatchResponseTemplate,
    SingleResponseTemplate,
    parse_binary_rows,
    parse_features,
    single_score_payload,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.app")

#: parse/serialize are µs-scale host work — the default latency buckets
#: would dump them all into the first bucket
_FAST_PHASE_BUCKETS = (
    0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1,
)

#: routes whose successful requests count into the scoring-latency
#: histogram (the "requests scored" accounting the bench cross-checks)
_SCORING_ROUTES = ("/score/v1", "/score/v1/batch")

#: Retry-After hint (seconds) on 503s from a not-yet-loaded service
#: WITHOUT an admission controller — long enough for a checkpoint-watcher
#: poll to land a model, short enough that a retrying client converges
#: quickly. With admission enabled, every backpressure response (shed
#: 429 AND degraded 503) instead derives its Retry-After from the EWMA
#: queue-delay estimate (``serve.admission``), clamped — one consistent
#: numeric hint per service.
RETRY_AFTER_S = 5


def _json_response(payload: dict, status: int = 200) -> Response:
    return Response(
        json.dumps(payload), status=status, mimetype="application/json"
    )


class PredictionSanityError(RuntimeError):
    """A PRODUCTION prediction failed the sanity firewall (non-finite).
    There is no healthier model to answer from, so the request fails
    (500) rather than serialising garbage to the client."""


def routes_to_canary(seed: int, fraction: float, X) -> bool:
    """The canary routing decision for one request: a pure function of
    ``(seed, request features)`` — no RNG state, no wall clock — so the
    SAME request routes to the same stream on every replica, every
    engine, and every replay of a seeded traffic log. The hash's top 64
    bits are compared against ``fraction`` of the 2^64 space, giving an
    unbiased fraction over any non-adversarial request distribution."""
    if fraction >= 1.0:
        return True
    if fraction <= 0.0:
        return False
    digest = hashlib.sha256(
        str(int(seed)).encode("ascii")
        + b"|"
        + np.ascontiguousarray(np.asarray(X, dtype=np.float32)).tobytes()
    ).digest()
    return int.from_bytes(digest[:8], "big") < int(fraction * 2.0**64)


def as_bounds(bounds) -> tuple[float, float] | None:
    """Normalise a registry ``prediction_bounds`` value (``{"lo", "hi"}``
    dict or ``(lo, hi)`` pair) into a float tuple; malformed/absent ->
    None (the firewall then only checks finiteness)."""
    if bounds is None:
        return None
    try:
        if isinstance(bounds, dict):
            lo, hi = float(bounds["lo"]), float(bounds["hi"])
        else:
            lo, hi = float(bounds[0]), float(bounds[1])
    except (KeyError, IndexError, TypeError, ValueError):
        return None
    if not (np.isfinite(lo) and np.isfinite(hi) and lo <= hi):
        return None
    return lo, hi


def sanity_violation(predictions, bounds: tuple[float, float] | None) -> str | None:
    """The prediction-sanity firewall's verdict for one response's worth
    of model output: ``"non_finite"`` (NaN/inf anywhere), ``"out_of_range"``
    (outside the training-label band recorded in the registry), or None
    (sane). Runs BEFORE serialization on every scoring path — a
    violating prediction is never written to a client."""
    arr = np.asarray(predictions, dtype=np.float64)
    if not np.all(np.isfinite(arr)):
        return "non_finite"
    if bounds is not None:
        lo, hi = bounds
        if np.any(arr < lo) or np.any(arr > hi):
            return "out_of_range"
    return None


def _predictor_mesh(predictor) -> dict | None:
    """The device-mesh shape a predictor dispatches over, or None for
    single-device predictors — the /healthz ``mesh`` block."""
    mesh = getattr(predictor, "mesh", None)
    if mesh is None:
        return None
    return {
        "data": int(mesh.shape["data"]),
        "model": int(mesh.shape["model"]),
    }


class _Served:
    """One served model: predictor + identity, swapped as a unit so a
    request can never pair one model's prediction with another's info.
    ``model_key`` is the artefact key the model was loaded from and
    ``source`` how it was resolved (``"production"`` via the registry
    alias, ``"latest"`` via the date-key fallback, None when the caller
    didn't say) — surfaced on ``/healthz`` and the served-model info
    gauge so an operator can see WHAT serves and under WHOSE authority."""

    __slots__ = (
        "predictor", "model_info", "model_date", "model_key", "source",
        "bounds", "single_template", "batch_template",
    )

    def __init__(
        self,
        predictor,
        model_info: str,
        model_date: str | None,
        model_key: str | None = None,
        source: str | None = None,
        bounds: tuple[float, float] | None = None,
    ):
        self.predictor = predictor
        self.model_info = model_info
        self.model_date = model_date
        self.model_key = model_key
        self.source = source
        #: (lo, hi) prediction-sanity band from the registry record's
        #: training-label statistics; None = finiteness checks only
        self.bounds = bounds
        #: pre-serialized /score/v1 response framing (serve.wire): the
        #: body's invariant bytes are fixed per bundle, so the hot path
        #: splices only the prediction instead of a full json.dumps.
        #: Living ON the bundle gives invalidation for free — a swap
        #: builds a new _Served, and with it a new template.
        self.single_template = SingleResponseTemplate(model_info, model_date)
        #: same framing for the /score/v1/batch body
        self.batch_template = BatchResponseTemplate(model_info, model_date)


class ScoringApp:
    """WSGI scoring application over a shape-bucketed predictor.

    The served model is held as one immutable bundle behind a single
    attribute, so :meth:`swap_model` (hot reload) is an atomic pointer
    swap under the GIL — in-flight requests finish on the model they
    started with."""

    def __init__(
        self,
        model: Regressor | None,
        model_date: date | None = None,
        buckets: tuple[int, ...] | None = None,
        predictor=None,
        batcher=None,
        metrics_dir: str | None = None,
        model_key: str | None = None,
        model_source: str | None = None,
        admission=None,
        model_bounds=None,
    ):
        if model is None:
            # degraded boot: no checkpoint exists yet. Scoring answers
            # 503 + Retry-After until the first swap_model (the
            # checkpoint watcher's job) — the server never dies for
            # having started before its first artefact.
            assert predictor is None, "a predictor needs a model"
            self._served = None
        else:
            # a custom predictor (e.g. parallel.DataParallelPredictor
            # over a device mesh) replaces the single-device bucketed
            # default
            predictor = predictor or (
                PaddedPredictor(model, buckets) if buckets else PaddedPredictor(model)
            )
            self._served = _Served(
                predictor, model.info,
                str(model_date) if model_date else None,
                model_key=model_key, source=model_source,
                bounds=as_bounds(model_bounds),
            )
        #: reason the service is degraded (serving last-good after a
        #: failed reload), or None when healthy; surfaced in /healthz
        self._degraded_reason: str | None = None
        #: the live canary bundle + routing knobs (serve.reload syncs
        #: them from the registry's alias document). One attribute each:
        #: a request thread reads them at most once per request, so a
        #: concurrent abort/promote is an atomic pointer change exactly
        #: like a production hot swap.
        self._canary: _Served | None = None
        self._canary_fraction: float = 0.0
        self._canary_seed: int = 0
        #: the SLO watchdog's latest evaluation (ops/slo.py publishes
        #: it); rides /healthz so probes and the traffic harness can see
        #: the release loop's state without scraping /metrics
        self.slo_state: dict | None = None
        #: the online tune controller's latest state (tune/online.py
        #: publishes it every poll); rides /healthz next to the
        #: watchdog block for the same reason
        self.tune_state: dict | None = None
        self._plan_getter = None  # lazy chaos-plan resolver (canary latency)
        # opt-in request coalescer (serve.batcher.RequestCoalescer);
        # None = every request dispatches its own padded device call
        self.batcher = batcher
        #: opt-in admission controller (serve.admission): scoring POSTs
        #: are admitted against its bounded pending budget BEFORE the
        #: body is even parsed — a shed costs a counter bump and a tiny
        #: 429, never coalescer or device work. None = admit everything
        #: (the pre-admission behaviour, byte-identical).
        self.admission = admission
        #: shared snapshot dir for multi-worker /metrics aggregation
        #: (serve.multiproc); None = this process's registry alone
        self.metrics_dir = metrics_dir
        #: doc_digest of the applied tuned serving config
        #: (tune/config.py resolve_serving_knobs; the serving wiring
        #: sets it), or None when serving hand-set/built-in knobs —
        #: rides /healthz effective_config so a deployed tuned config
        #: is verifiable without log archaeology
        self.tuned_config_digest: str | None = None
        #: the process-wide request tracer (obs.tracing): scoring
        #: requests get a W3C-compatible trace id (ingress traceparent
        #: or deterministically minted), head-sampled spans, and the
        #: X-Bodywork-Trace-Id response header. Fraction 0 = off,
        #: zero per-request work.
        self.tracer = get_tracer()
        # hot-path phase instrumentation (obs.registry; the registry is
        # process-global, so replica apps in one process share metrics —
        # exactly as one k8s pod exposes one scrape target)
        reg = get_registry()
        self._m_requests = reg.counter(
            "bodywork_tpu_http_requests_total",
            "HTTP requests served, by route and status",
        )
        self._m_latency = reg.histogram(
            "bodywork_tpu_scoring_latency_seconds",
            "End-to-end handler time of successful scoring requests",
        )
        self._m_parse = reg.histogram(
            "bodywork_tpu_request_parse_seconds",
            "Request-parse phase: JSON body -> validated feature array",
            buckets=_FAST_PHASE_BUCKETS,
        )
        self._m_dispatch = reg.histogram(
            "bodywork_tpu_device_dispatch_seconds",
            "Device-dispatch phase: one padded predictor call",
        )
        self._m_serialize = reg.histogram(
            "bodywork_tpu_response_serialize_seconds",
            "Serialization phase: prediction -> JSON response",
            buckets=_FAST_PHASE_BUCKETS,
        )
        self._m_swaps = reg.counter(
            "bodywork_tpu_model_hot_swaps_total",
            "Served-model hot swaps (serve.reload checkpoint watcher)",
        )
        self._m_fallbacks = reg.counter(
            "bodywork_tpu_coalescer_fallback_total",
            "Requests degraded to a direct dispatch (coalescer saturated)",
        )
        # Per-model-key stream accounting, observed ONLY while a canary
        # is live (zero hot-path cost otherwise): the SLO watchdog reads
        # these to compare baseline and canary on comparable traffic.
        self._m_stream_requests = reg.counter(
            "bodywork_tpu_serve_model_requests_total",
            "Scoring requests routed per served model while a canary is "
            "live, by model_key and stream (production|canary)",
        )
        self._m_stream_errors = reg.counter(
            "bodywork_tpu_serve_model_errors_total",
            "Scoring requests that errored per served model while a "
            "canary is live, by model_key and stream",
        )
        self._m_stream_latency = reg.histogram(
            "bodywork_tpu_serve_model_latency_seconds",
            "Scoring latency per served model while a canary is live, "
            "by model_key and stream — the SLO watchdog's p99 source",
        )
        self._m_sanity = reg.counter(
            "bodywork_tpu_serve_sanity_violations_total",
            "Predictions caught by the sanity firewall before "
            "serialization, by model_key, stream, and reason "
            "(non_finite|out_of_range)",
        )
        self._g_degraded = reg.gauge(
            "bodywork_tpu_serve_degraded_state",
            "Serving degradation: 0=healthy, 1=serving last-good model "
            "after a failed reload, 2=no model loaded",
            aggregate="max",
        )
        self._g_degraded.set(2.0 if self._served is None else 0.0)
        # served-model-version info gauge: the CURRENT served artefact's
        # sample is 1 and its resolution source rides as a label
        # ("production" = registry alias, "latest" = date-key fallback);
        # a swap zeroes the superseded sample so a scrape shows exactly
        # one live version per process
        self._g_model_version = reg.gauge(
            "bodywork_tpu_serve_model_version_info",
            "Served model version: 1 on the (model_key, source) sample "
            "currently serving, 0 on superseded ones",
            aggregate="max",
        )
        self._model_version_labels: dict | None = None
        self._record_model_version()
        self._routes = {
            ("POST", "/score/v1"): self.score_data_instance,
            ("POST", "/score/v1/batch"): self.score_batch,
            ("GET", "/healthz"): self.healthz,
            ("GET", "/metrics"): self.metrics_endpoint,
        }

    def _record_model_version(self) -> None:
        served = self._served
        if served is None or served.model_key is None:
            return
        labels = {
            "model_key": served.model_key,
            "source": served.source or "unspecified",
        }
        old = self._model_version_labels
        if old is not None and old != labels:
            self._g_model_version.set(0.0, **old)
        self._g_model_version.set(1.0, **labels)
        self._model_version_labels = labels

    # -- served-model access (single read point for atomic swaps) ----------
    @property
    def served_bundle(self):
        """The immutable served-model bundle (predictor + identity), or
        None before the first model. ONE read is stable across a hot
        swap — the asyncio front-end (serve.aio) scores against this
        exactly as the WSGI handlers below do."""
        return self._served

    @property
    def predictor(self):
        served = self._served
        return None if served is None else served.predictor

    @property
    def model_info(self) -> str | None:
        served = self._served
        return None if served is None else served.model_info

    @property
    def model_date(self) -> str | None:
        served = self._served
        return None if served is None else served.model_date

    @property
    def model_key(self) -> str | None:
        served = self._served
        return None if served is None else served.model_key

    @property
    def model_source(self) -> str | None:
        served = self._served
        return None if served is None else served.source

    # -- degraded-mode channel (serve.reload drives it) --------------------
    def set_degraded(self, reason: str) -> None:
        """Flag the service as serving its last-good model (a hot reload
        failed). The service keeps answering — the flag rides /healthz
        and the state gauge so operators see the stall."""
        self._degraded_reason = reason
        if self._served is not None:
            self._g_degraded.set(1.0)

    def clear_degraded(self) -> None:
        self._degraded_reason = None
        self._g_degraded.set(0.0 if self._served is not None else 2.0)

    def swap_model(
        self,
        model: Regressor,
        model_date: date | None = None,
        predictor=None,
        model_key: str | None = None,
        model_source: str | None = None,
        model_bounds=None,
    ) -> None:
        """Atomically replace the served model (hot reload). The caller is
        responsible for warming the new predictor OFF the request path
        first (``serve.reload.CheckpointWatcher`` does). A successful
        swap clears the degraded flag — and brings a model-less app
        (degraded boot) live. ``model_key``/``model_source`` update the
        /healthz identity and the served-model-version info gauge."""
        if predictor is None:
            old = self._served
            predictor = (
                PaddedPredictor(model, old.predictor.buckets)
                if old is not None
                else PaddedPredictor(model)
            )
            # a predictor built HERE was warmed by nobody: compile (and
            # run) every bucket BEFORE the swap pointer publishes, so a
            # caller skipping the watcher path (tests, ad-hoc swaps)
            # still never lands a compile — or a device fault — on the
            # first scoring request. With the process-wide executable
            # cache a same-architecture swap makes this free (pure
            # cache hits); sync=False because surfacing execution
            # faults synchronously is the WATCHER's pre-swap contract,
            # not this fallback's.
            predictor.warmup(sync=False)
        self._served = _Served(
            predictor, model.info, str(model_date) if model_date else None,
            model_key=model_key, source=model_source,
            bounds=as_bounds(model_bounds),
        )
        self._record_model_version()
        if self.batcher is not None:
            # the coalescer's bundle-grouping already guarantees no batch
            # mixes generations; draining here additionally flushes every
            # ALREADY-ENQUEUED old-model row before the swap returns.
            # (Request threads that read the old bundle but have not yet
            # enqueued finish on the model they started with — the same
            # in-flight semantics as the unbatched app above.)
            if not self.batcher.drain():
                # correctness is unaffected (queued old-bundle rows still
                # score on their own generation) — but the prompt-flush
                # promise did not hold, and silence would hide a wedged
                # dispatcher
                log.warning(
                    "hot-swap proceeded before the request coalescer "
                    "fully drained; old-model rows may still be in flight"
                )
        self._m_swaps.inc()
        self.clear_degraded()
        log.info(f"hot-swapped served model -> {model.info} ({model_date})")

    # -- canary routing + prediction-sanity firewall -----------------------

    @property
    def canary_key(self) -> str | None:
        canary = self._canary
        return None if canary is None else canary.model_key

    @property
    def canary_fraction(self) -> float:
        return self._canary_fraction if self._canary is not None else 0.0

    def set_canary(
        self,
        model: Regressor,
        model_date: date | None = None,
        predictor=None,
        model_key: str | None = None,
        fraction: float = 0.1,
        seed: int = 0,
        bounds=None,
    ) -> None:
        """Install (or replace) the canary bundle: ``fraction`` of
        scoring traffic routes to it by seeded request hash
        (:func:`routes_to_canary`), measured under per-model-key labels
        so the SLO watchdog can compare it against production. The
        caller (``serve.reload``) warms the predictor first, exactly as
        for a production hot swap."""
        if predictor is None:
            base = self._served
            predictor = (
                PaddedPredictor(model, base.predictor.buckets)
                if base is not None
                else PaddedPredictor(model)
            )
            # same warm-before-publish contract as swap_model: a canary
            # start must not land its first-bucket compile (or a device
            # fault) on the first scoring request that routes to it
            predictor.warmup(sync=False)
        old = self._canary
        self._canary_fraction = float(fraction)
        self._canary_seed = int(seed)
        self._canary = _Served(
            predictor, model.info, str(model_date) if model_date else None,
            model_key=model_key, source="canary", bounds=as_bounds(bounds),
        )
        # the canary is a SECOND live version: show it on the info gauge
        # next to production (the pre-canary blind spot where the gauge
        # only ever carried one live key)
        if old is not None and old.model_key and old.model_key != model_key:
            self._g_model_version.set(
                0.0, model_key=old.model_key, source="canary"
            )
        if model_key:
            self._g_model_version.set(
                1.0, model_key=model_key, source="canary"
            )
        log.info(
            f"canary live: {model.info} ({model_key}) at fraction "
            f"{fraction} (seed {seed})"
        )

    def clear_canary(self) -> None:
        """Stop routing to the canary (abort/repair path). Requests that
        already read the canary bundle finish on it — the same in-flight
        semantics as a production hot swap."""
        old = self._canary
        self._canary = None
        self._canary_fraction = 0.0
        if old is not None:
            if old.model_key:
                self._g_model_version.set(
                    0.0, model_key=old.model_key, source="canary"
                )
            log.info(f"canary cleared: {old.model_key}")

    def promote_canary_bundle(self) -> None:
        """Graduate the in-process canary bundle to production (the SLO
        watchdog's healthy-window action, after its alias CAS landed):
        the already-loaded, already-warm canary predictor starts taking
        100% of traffic immediately — no store round-trip, no reload
        window where the alias and the serving process disagree."""
        bundle = self._canary
        if bundle is None:
            return
        self.clear_canary()
        self._served = _Served(
            bundle.predictor, bundle.model_info, bundle.model_date,
            model_key=bundle.model_key, source="production",
            bounds=bundle.bounds,
        )
        self._record_model_version()
        if self.batcher is not None and not self.batcher.drain():
            log.warning(
                "canary promotion proceeded before the request coalescer "
                "fully drained; old-model rows may still be in flight"
            )
        self._m_swaps.inc()
        self.clear_degraded()
        log.info(
            f"canary promoted in-process -> {bundle.model_info} "
            f"({bundle.model_key})"
        )

    def route_stream(self, X):
        """The (bundle, stream) a request's features route to:
        ``("production"|"canary")``. One read of each pointer — stable
        across concurrent swaps/aborts."""
        served = self._served
        canary = self._canary
        if canary is None or served is None:
            return served, "production"
        if routes_to_canary(self._canary_seed, self._canary_fraction, X):
            return canary, "canary"
        return served, "production"

    def stream_metrics_active(self) -> bool:
        """Whether per-model-key stream accounting is on (a canary is
        live) — the check both engines make before paying labelled
        metric writes on the hot path."""
        return self._canary is not None

    def count_stream_request(self, served, stream: str) -> None:
        self._m_stream_requests.inc(
            model_key=served.model_key or "unknown", stream=stream
        )

    def count_stream_error(self, served, stream: str) -> None:
        self._m_stream_errors.inc(
            model_key=served.model_key or "unknown", stream=stream
        )

    def observe_stream_latency(self, served, stream: str, seconds: float,
                               exemplar: str | None = None) -> None:
        self._m_stream_latency.observe(
            seconds, exemplar=exemplar,
            model_key=served.model_key or "unknown", stream=stream,
        )

    def sanity_reason(self, served, predictions) -> str | None:
        """Cheap precheck (pure numpy) both engines run on every scored
        prediction; the expensive fallback path only runs when this is
        non-None."""
        return sanity_violation(predictions, served.bounds)

    def count_sanity_violation(self, served, stream: str, reason: str) -> None:
        self._m_sanity.inc(
            model_key=served.model_key or "unknown",
            stream=stream,
            reason=reason,
        )

    def firewall(self, served, stream: str, X, predictions, reason: str,
                 trace=None):
        """Apply the prediction-sanity firewall AFTER a violation was
        detected: a canary violation is answered from the PRODUCTION
        model (counted — the violation is the watchdog's abort signal —
        but the client gets a sane prediction from the baseline, and the
        violating value is never serialized); a production non-finite
        raises :class:`PredictionSanityError` (500 — there is no
        healthier model to answer from); a production out-of-range is
        counted and served (the band is statistical; refusing real
        production traffic on it would turn a drifted day into an
        outage). Returns ``(answering_bundle, predictions)``. A sampled
        ``trace`` records the fallback re-predict as a child span — the
        flight-recorder evidence that a canary request was answered by
        production."""
        self.count_sanity_violation(served, stream, reason)
        if stream == "canary":
            production = self._served
            log.warning(
                f"canary prediction sanity violation ({reason}) on "
                f"{served.model_key}; answering from production"
            )
            t0 = time.perf_counter()
            # X arrives exactly as the route handed it to the canary's
            # predictor (2-D for single, 1-D or 2-D for batch) — the
            # predictor's own shape normalisation applies, so fallback
            # predictions are byte-identical to a production-routed call
            fallback = production.predictor.predict(X)
            t1 = time.perf_counter()
            self._m_dispatch.observe(t1 - t0)
            if trace is not None and trace.sampled:
                trace.add(
                    "firewall-fallback", t0, t1,
                    reason=reason,
                    violating_model_key=served.model_key,
                    answered_by=production.model_key,
                )
            if sanity_violation(fallback, None) is not None:
                # production's answer is itself non-finite: nothing sane
                # to serialize — the zero-garbage guarantee holds by 500
                self.count_sanity_violation(production, "production", "non_finite")
                raise PredictionSanityError("non_finite")
            return production, fallback
        if reason == "non_finite":
            log.error(
                f"production prediction non-finite on {served.model_key}; "
                "refusing to serialize"
            )
            raise PredictionSanityError(reason)
        log.warning(
            f"production prediction out of sanity band on "
            f"{served.model_key} (served anyway; band is statistical)"
        )
        return served, predictions

    def canary_chaos_delay(self, stream: str) -> float | None:
        """The active fault plan's canary-stream latency injection
        (``FaultPlan.canary_latency_delay``), or None. Decide-only so
        the asyncio engine can ``await`` it; the threaded engine sleeps
        via :meth:`apply_canary_chaos`. Adversity addressed to the
        canary stream ONLY — production requests never consult it."""
        if stream != "canary":
            return None
        if self._plan_getter is None:
            from bodywork_tpu.chaos.plan import get_active_plan

            self._plan_getter = get_active_plan
        plan = self._plan_getter()
        if plan is None:
            return None
        canary = self._canary
        return plan.canary_latency_delay(
            canary.model_key if canary is not None else "unknown"
        )

    def apply_canary_chaos(self, stream: str) -> None:
        delay = self.canary_chaos_delay(stream)
        if delay is not None:
            time.sleep(delay)

    def close(self) -> None:
        """Release app-owned background resources (the coalescer's
        dispatcher thread). Idempotent; the app still serves afterwards,
        just without coalescing."""
        if self.batcher is not None:
            self.batcher.stop()

    # -- WSGI plumbing -----------------------------------------------------
    def __call__(self, environ, start_response):
        request = Request(environ)
        t0 = time.perf_counter()
        scoring_post = (
            request.method == "POST" and request.path in _SCORING_ROUTES
        )
        # request-scoped tracing (obs.tracing). BEFORE admission only a
        # request that ARRIVED with a valid traceparent gets a context
        # (one header lookup — its id needs no body); minting for the
        # rest happens AFTER admission, so a shed request never reads or
        # hashes its body and the zero-footprint shed invariant below
        # holds. Traceparent-carrying sheds still answer with their id
        # and record the shed span. Fraction 0 skips all of it.
        trace = None
        tracer = self.tracer
        traced = scoring_post and tracer.enabled
        if traced:
            traceparent = request.headers.get(TRACEPARENT_HEADER)
            if traceparent is not None and (
                parse_traceparent(traceparent) is not None
            ):
                trace = tracer.begin(traceparent, b"")
        # admission runs FIRST — before parsing, before the no-model
        # check, before anything that costs per-request work. A shed
        # request must leave zero footprint beyond its counter: that is
        # the property that keeps an overloaded server serving its
        # admitted queue instead of drowning with it.
        admission = self.admission
        admitted = False
        if admission is not None and scoring_post:
            if not admission.try_admit():
                response = self.shed_response()
                if trace is not None:
                    if trace.sampled:
                        now = time.perf_counter()
                        trace.add(
                            "admission-shed", now, now,
                            queue_depth=admission.queue_depth,
                        )
                    tracer.finish(trace, request.path, response.status_code)
                    response.headers[TRACE_ID_HEADER] = trace.trace_id
                self._m_requests.inc(
                    route=request.path, status=str(response.status_code)
                )
                return response(environ, start_response)
            admitted = True
        if traced and trace is None:
            # admitted without ingress context: mint deterministically
            # from the body bytes (the same buffered bytes get_json
            # reads later — werkzeug caches, so no second socket read)
            trace = tracer.begin(
                None, request.get_data(cache=True, parse_form_data=False)
            )
        try:
            handler = self._routes.get((request.method, request.path))
            if handler is None:
                if any(path == request.path for _m, path in self._routes):
                    raise MethodNotAllowed()
                raise NotFound()
            response = handler(request, trace)
        except HTTPException as exc:
            response = _json_response({"error": exc.description}, exc.code)
        except Exception as exc:  # don't leak tracebacks to clients
            log.error(f"unhandled error serving {request.path}: {exc!r}")
            response = _json_response({"error": "internal server error"}, 500)
        finally:
            if admitted:
                # the observed delay (admission -> response ready) is
                # the EWMA sample behind every Retry-After hint
                admission.release(time.perf_counter() - t0)
        route = (
            request.path
            if any(path == request.path for _m, path in self._routes)
            else "unknown"
        )
        self._m_requests.inc(route=route, status=str(response.status_code))
        if request.path in _SCORING_ROUTES and response.status_code == 200:
            # count == requests successfully scored (the invariant the
            # bench cross-checks against client-side latencies); sampled
            # requests leave their trace id as the bucket's exemplar
            self._m_latency.observe(
                time.perf_counter() - t0,
                exemplar=(
                    trace.trace_id
                    if trace is not None and trace.sampled else None
                ),
            )
        if trace is not None:
            tracer.finish(trace, route, response.status_code)
            # the id rides ONLY this header, never a body — the chaos
            # comparator ignores it exactly like the model-key header
            response.headers[TRACE_ID_HEADER] = trace.trace_id
        return response(environ, start_response)

    def test_client(self):
        from werkzeug.test import Client

        return Client(self)

    # -- shared parsing ----------------------------------------------------
    def _features_from(self, request: Request, trace=None):
        t0 = time.perf_counter()
        try:
            return self._parse_features(request)
        finally:
            t1 = time.perf_counter()
            self._m_parse.observe(t1 - t0)
            if trace is not None and trace.sampled:
                trace.add("parse", t0, t1)

    def _parse_features(self, request: Request):
        # binary row-batch framing rides the content type; the JSON
        # body stays the default. Both decode through serve.wire, so a
        # request's array — and with it canary routing, predictions,
        # and response bytes — is identical across framings.
        if request.mimetype == BINARY_CONTENT_TYPE:
            X, message = parse_binary_rows(
                request.get_data(cache=True, parse_form_data=False)
            )
        else:
            X, message = parse_features(request.get_json(silent=True))
        if message is not None:
            return None, _json_response({"error": message}, 400)
        return X, None

    def retry_after_s(self) -> int:
        """The ONE numeric Retry-After every backpressure response from
        this app carries (shed 429s and degraded/no-model 503s): the
        admission layer's clamped EWMA estimate when admission is on,
        the static watcher-poll default otherwise."""
        if self.admission is not None:
            return self.admission.retry_after_s()
        return RETRY_AFTER_S

    def shed_response(self) -> Response:
        """The admission-shed 429 (load shedding, serve.admission)."""
        response = _json_response(
            {"error": "server over capacity; request shed"}, 429
        )
        response.headers["Retry-After"] = str(self.retry_after_s())
        return response

    def _no_model_response(self) -> Response:
        response = _json_response(
            {"error": "no model loaded yet; retry shortly"}, 503
        )
        response.headers["Retry-After"] = str(self.retry_after_s())
        return response

    # -- routes ------------------------------------------------------------
    def score_data_instance(self, request: Request, trace=None) -> Response:
        """Single-instance scoring; reference-parity contract
        (``stage_2:73-80``)."""
        X, err = self._features_from(request, trace)
        if err is not None:
            # validation precedes the no-model check: a malformed request
            # can never succeed, so it must get its non-retryable 400
            # even from a model-less server (a 503 would make clients
            # burn their whole Retry-After budget on it)
            return err
        # canary-aware routing: one pointer read each — a request scores
        # entirely against the bundle it routed to, across swaps/aborts
        served, stream = self.route_stream(X)
        if served is None:
            return self._no_model_response()
        routed = served  # metrics stay attributed to the ROUTED bundle
        streamed = self.stream_metrics_active()
        sampled = trace is not None and trace.sampled
        if sampled:
            trace.annotate(stream=stream, routed_model_key=served.model_key)
        t_stream = time.perf_counter()
        if streamed:
            self.count_stream_request(routed, stream)
        X = np.array(X, ndmin=2)  # scalar -> (1, 1), as the reference
        try:
            self.apply_canary_chaos(stream)
            prediction0 = None
            if self.batcher is not None and X.shape[0] == 1:
                try:
                    # the submission carries ITS served bundle: the batch
                    # it lands in is built from one model generation only
                    # (canary rows batch with canary rows), and the
                    # response pairs that generation's prediction with
                    # that generation's identity fields below. Queue-wait
                    # and device-dispatch phases (and their spans, for a
                    # sampled request) are recorded by the coalescer.
                    prediction0 = self.batcher.submit(
                        served, X[0], trace=trace if sampled else None
                    )
                except CoalescerSaturated:
                    # overload/shutdown: degrade to a direct dispatch
                    self._m_fallbacks.inc()
            if prediction0 is None:
                prediction0, _ = self._traced_dispatch(
                    served, X, trace if sampled else None
                )
                prediction0 = float(np.asarray(prediction0).ravel()[0])
            # the prediction-sanity firewall: BEFORE serialization, on
            # every path (coalesced included) — a violating value never
            # reaches a client
            reason = self.sanity_reason(served, prediction0)
            if reason is not None:
                served, fallback = self.firewall(
                    served, stream, X, prediction0, reason, trace=trace
                )
                prediction0 = float(np.asarray(fallback).ravel()[0])
        except Exception:
            if streamed:
                self.count_stream_error(routed, stream)
            raise
        t0 = time.perf_counter()
        # pre-serialized framing: the bundle-invariant bytes are cached
        # on the _Served (serve.wire.SingleResponseTemplate) — only the
        # prediction is serialized per response, byte-identical to the
        # full json.dumps(single_score_payload(...)) it replaces
        response = Response(
            served.single_template.render(prediction0),
            mimetype="application/json",
        )
        t1 = time.perf_counter()
        self._m_serialize.observe(t1 - t0)
        if sampled:
            trace.add("serialize", t0, t1)
        if served.model_key:
            # the ANSWERING model (post-fallback) — what the traffic
            # harness attributes the response to
            response.headers[MODEL_KEY_HEADER] = served.model_key
        if streamed:
            # latency stays on the routed stream: a fallen-back canary
            # request still COST its caller the canary's time
            self.observe_stream_latency(
                routed, stream, time.perf_counter() - t_stream,
                exemplar=trace.trace_id if sampled else None,
            )
        return response

    def _traced_dispatch(self, served, X, trace):
        """One direct (uncoalesced) padded device dispatch, with the
        phase histogram observation both paths already made — plus, for
        a sampled request, a device-dispatch span installed as the
        ACTIVE span so the predictor's AOT-cache seam can annotate it
        (obs.tracing.annotate_active)."""
        span = token = None
        if trace is not None:
            span = trace.start_span("device-dispatch", coalesced=False)
            token = set_active_span(span)
        t0 = time.perf_counter()
        try:
            predictions = served.predictor.predict(X)
        finally:
            self._m_dispatch.observe(time.perf_counter() - t0)
            if span is not None:
                reset_active_span(token)
                trace.end_span(span)
        return predictions, span

    def score_batch(self, request: Request, trace=None) -> Response:
        """Batched scoring: one padded device call for up to bucket-size rows."""
        X, err = self._features_from(request, trace)
        if err is not None:
            return err  # 400 before 503: see score_data_instance
        served, stream = self.route_stream(X)  # whole batch, one stream
        if served is None:
            return self._no_model_response()
        routed = served
        streamed = self.stream_metrics_active()
        sampled = trace is not None and trace.sampled
        if sampled:
            trace.annotate(
                stream=stream, routed_model_key=served.model_key,
                rows=int(np.atleast_1d(X).shape[0]),
            )
        t_stream = time.perf_counter()
        if streamed:
            self.count_stream_request(routed, stream)
        if X.ndim == 0:
            X = X[None]
        try:
            self.apply_canary_chaos(stream)
            predictions, _ = self._traced_dispatch(
                served, X, trace if sampled else None
            )
            reason = self.sanity_reason(served, predictions)
            if reason is not None:
                served, predictions = self.firewall(
                    served, stream, X, predictions, reason, trace=trace
                )
        except Exception:
            if streamed:
                self.count_stream_error(routed, stream)
            raise
        t0 = time.perf_counter()
        # pre-serialized framing (serve.wire.BatchResponseTemplate):
        # byte-identical to json.dumps(batch_score_payload(...))
        response = Response(
            served.batch_template.render(predictions),
            mimetype="application/json",
        )
        t1 = time.perf_counter()
        self._m_serialize.observe(t1 - t0)
        if sampled:
            trace.add("serialize", t0, t1)
        if served.model_key:
            response.headers[MODEL_KEY_HEADER] = served.model_key
        if streamed:
            self.observe_stream_latency(
                routed, stream, time.perf_counter() - t_stream,
                exemplar=trace.trace_id if sampled else None,
            )
        return response

    def effective_config(self) -> dict:
        """The knob values ACTUALLY live in this process — read from the
        live objects (coalescer, admission controller, predictor), not
        from whatever configuration named them, so /healthz reports what
        is running even if a tuned config was partially applied or a
        knob degraded. ``tuned_config`` is the applied document's
        doc_digest (null = hand-set/built-in values)."""
        served = self._served
        predictor = served.predictor if served is not None else None
        batcher = self.batcher
        admission = self.admission
        buckets = getattr(predictor, "buckets", None)
        return {
            "batch_window_ms": (
                round(batcher.window_s * 1e3, 3) if batcher is not None
                else None
            ),
            "batch_max_rows": (
                batcher.max_rows if batcher is not None else None
            ),
            "buckets": list(buckets) if buckets else None,
            "max_pending": (
                admission.max_pending if admission is not None else None
            ),
            "dtype": (
                getattr(predictor, "dtype", "float32")
                if predictor is not None else None
            ),
            "tuned_config": self.tuned_config_digest,
        }

    def healthz_payload(self) -> tuple[dict, int, int | None]:
        """``(payload, status, retry_after_s-or-None)`` — the health
        document BOTH front-ends serve (the threaded route below, the
        asyncio engine directly), so operators see one schema per
        service regardless of engine."""
        served = self._served  # one read: stable across a hot swap
        admission = self.admission
        # queue depth surfaces even without admission: the coalescer's
        # pending rows are the next-best saturation signal
        if admission is not None:
            queue_depth = admission.queue_depth
            admission_state = admission.state()
        else:
            queue_depth = (
                self.batcher.pending_depth() if self.batcher is not None else 0
            )
            admission_state = None
        canary = self._canary
        if served is None:
            return (
                {
                    "status": "no model loaded",
                    "degraded": True,
                    "reason": "no model has been loaded yet",
                    "model_info": None,
                    "model_date": None,
                    "model_key": None,
                    "model_source": None,
                    "serving_dtype": None,
                    "mesh": None,
                    # a degraded boot can still hold a live canary (the
                    # watcher loads it independently of production) —
                    # probes must see the release loop's real state
                    "canary_key": (
                        canary.model_key if canary is not None else None
                    ),
                    "canary_fraction": (
                        self._canary_fraction if canary is not None else None
                    ),
                    "watchdog": self.slo_state,
                    "tuning": self.tune_state,
                    "queue_depth": queue_depth,
                    "admission": admission_state,
                    # live knob values (coalescer/admission exist even
                    # before the first model): a deployed tuned config
                    # is verifiable during a degraded boot too
                    "effective_config": self.effective_config(),
                    "latency_exemplars": self._m_latency.exemplars() or None,
                },
                503,
                self.retry_after_s(),
            )
        reason = self._degraded_reason
        payload = {
            # 200 + "ok" even when degraded: the service IS serving, so
            # readiness must keep routing; the flag/reason (and the
            # state gauge) carry the operator signal
            "status": "ok",
            "model_info": served.model_info,
            "model_date": served.model_date,
            # WHAT serves and under WHOSE authority: the artefact key
            # plus how it was resolved — "production" (registry alias,
            # gated), "latest" (registry-less date-key fallback), None
            # (caller never said). A degraded service additionally
            # carries the degraded flag + reason below.
            "model_key": served.model_key,
            "model_source": served.source,
            # the serving precision actually live ("float32" after a
            # quantization-gate rejection — the operator-visible proof
            # that --dtype never silently costs quality)
            "serving_dtype": getattr(served.predictor, "dtype", "float32"),
            # the serving mesh actually live ({"data": D, "model": M}
            # for a sharded predictor, None single-device) — the
            # operator-visible proof that the --mesh-data/--mesh-model
            # knobs took effect, and what bench config 12 reads to
            # confirm each sweep point really dispatched sharded
            "mesh": _predictor_mesh(served.predictor),
            # the live-release channel: WHICH canary takes a fraction of
            # traffic (None = no canary) and the SLO watchdog's latest
            # verdict — so probes and the traffic harness attribute
            # per-version behaviour without scraping /metrics
            "canary_key": canary.model_key if canary is not None else None,
            "canary_fraction": (
                self._canary_fraction if canary is not None else None
            ),
            "watchdog": self.slo_state,
            # the config-release channel (tune/online.py): drift /
            # guard / revert state, same rationale as the watchdog block
            "tuning": self.tune_state,
            "degraded": reason is not None,
            # saturation channel (serve.admission): current depth plus —
            # when admission is on — budget, shedding state, and the
            # Retry-After currently handed out. Shedding deliberately
            # does NOT flip the 200: an at-budget replica is doing its
            # job; pulling it from the endpoints would dogpile its load
            # onto the siblings (readiness semantics, pipeline/k8s.py).
            "queue_depth": queue_depth,
            "admission": admission_state,
            # the knob values ACTUALLY applied (window/max_rows/buckets/
            # max_pending/dtype + the tuned-config digest or null) — the
            # operator's proof that a deployed tuned config (or a
            # kubectl-set-env knob) took effect, without log archaeology
            "effective_config": self.effective_config(),
            # tracing exemplars: the last sampled trace id per scoring-
            # latency bucket — a probe reading a fat p99 bucket gets the
            # trace id to replay through `cli trace show` (None when
            # tracing is off or nothing sampled yet)
            "latency_exemplars": self._m_latency.exemplars() or None,
        }
        if reason is not None:
            payload["reason"] = reason
        return payload, 200, None

    def healthz(self, request: Request, trace=None) -> Response:
        payload, status, retry_after = self.healthz_payload()
        response = _json_response(payload, status)
        if retry_after is not None:
            response.headers["Retry-After"] = str(retry_after)
        return response

    def metrics_endpoint(self, request: Request, trace=None) -> Response:
        """Prometheus text exposition of this process's registry, merged
        with sibling workers' flushed snapshots when ``metrics_dir`` is
        set (``serve --workers N --metrics`` exposes ONE coherent view
        regardless of which replica the kernel hands the scrape to)."""
        from bodywork_tpu.obs.multiproc import aggregated_render

        return Response(
            aggregated_render(get_registry(), self.metrics_dir),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )


def create_app(
    model: Regressor | None,
    model_date: date | None = None,
    buckets: tuple[int, ...] | None = None,
    warmup: bool = True,
    warmup_sync: bool = True,
    predictor=None,
    batch_window_ms: float | None = None,
    batch_max_rows: int | None = None,
    metrics_dir: str | None = None,
    model_key: str | None = None,
    model_source: str | None = None,
    admission=None,
    model_bounds=None,
) -> ScoringApp:
    """``batch_window_ms`` > 0 opts into cross-request micro-batching
    (``serve.batcher``): concurrent single-row ``/score/v1`` requests
    coalesce into one padded device call, flushed when ``batch_max_rows``
    accumulate or the window elapses, whichever first.

    ``metrics_dir`` points ``GET /metrics`` at a shared snapshot
    directory so multi-process replicas expose one aggregated view
    (``serve.multiproc`` wires it; single-process serving needs nothing —
    the endpoint always exposes this process's registry).

    ``admission`` (serve.admission.AdmissionController) opts into load
    shedding: scoring requests beyond its pending budget answer 429 +
    Retry-After before any work happens. Replica apps sharing one port
    should share ONE controller (one budget per serving process)."""
    batcher = None
    if batch_window_ms and batch_window_ms > 0:
        from bodywork_tpu.serve.batcher import DEFAULT_MAX_ROWS, RequestCoalescer

        batcher = RequestCoalescer(
            window_ms=batch_window_ms,
            max_rows=batch_max_rows or DEFAULT_MAX_ROWS,
        ).start()
    app = ScoringApp(model, model_date, buckets, predictor=predictor,
                     batcher=batcher, metrics_dir=metrics_dir,
                     model_key=model_key, model_source=model_source,
                     admission=admission, model_bounds=model_bounds)
    if warmup and app.predictor is not None:
        app.predictor.warmup(sync=warmup_sync)
    return app
