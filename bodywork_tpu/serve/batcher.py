"""Cross-request micro-batching for the scoring service.

Without it, every concurrent ``/score/v1`` request executes its OWN
bucket-padded device call: N threads of single-row traffic become N
serialized one-row dispatches, so per-worker throughput is bounded by
dispatch rate instead of the accelerator's batch dimension. The standard
accelerator-serving answer is request coalescing — hold a single-row
request for a tiny window, stack it with its concurrent neighbours, issue
ONE padded device call, scatter results back — trading a bounded latency
cost (at most the flush window) for throughput that scales with bucket
size under load.

Design:

- :class:`RequestCoalescer` owns a bounded pending list and one
  dispatcher thread. ``submit()`` blocks the calling request thread until
  its row's prediction is back.
- **Flush policy** (adaptive): the dispatcher flushes as soon as a batch
  reaches ``max_rows`` OR ``window_ms`` has elapsed since it started
  assembling one, whichever happens first. An idle service therefore pays
  at most one window of extra latency per request; a saturated one
  flushes full buckets back-to-back with no window wait at all.
- **Hot-swap safety**: every submission captures the app's served-model
  bundle (predictor + identity) at enqueue time, and a flush only takes
  the queue's leading run of submissions that share ONE bundle. A
  checkpoint swap landing mid-queue splits the queue into an old-model
  batch and a new-model batch — two device calls, each internally
  consistent — so a batch can never mix parameters from two model
  generations. ``drain()`` additionally lets the hot-swap path block
  until everything enqueued before the swap has been dispatched.
- **Overload**: when the pending list is full, ``submit()`` raises
  :class:`CoalescerSaturated` and the caller falls back to a direct
  per-request dispatch — backpressure degrades to the uncoalesced
  behaviour instead of dropping or deadlocking requests.
- A batch whose device call raises fails ONLY that batch: the error is
  scattered to its submitters (each request 500s) and the dispatcher
  keeps serving.

The coalescer is deliberately ignorant of HTTP and of predictor
internals: it stacks rows, calls ``served.predictor.predict`` once, and
indexes the result. The existing shape-bucket/pad/chunk algebra
(``serve.predictor``) is reused untouched, which is also why responses
are byte-identical with the batcher on or off — each output row of the
padded apply depends only on its own input row.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from bodywork_tpu.obs import get_registry
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.batcher")

#: default flush window: ~1-2 ms captures concurrent arrivals under load
#: while staying negligible next to the reference's 8.22 ms/score
DEFAULT_WINDOW_MS = 2.0
#: default batch cap; aligned with a mid-size predictor bucket so a full
#: flush pads to exactly one compiled shape
DEFAULT_MAX_ROWS = 64


class CoalescerSaturated(RuntimeError):
    """The pending queue is full (or the coalescer is stopped); the
    caller should fall back to a direct per-request dispatch."""


class _Submission:
    """One enqueued row: the input, the served bundle it must be scored
    by, and the rendezvous the request thread waits on. ``on_done`` is
    the OPTIONAL push-style completion channel (fired on the dispatcher
    thread right after ``event`` is set): the asyncio front-end sets it
    to hand the result back to its event loop without parking a thread
    on ``event.wait`` — the threaded engine keeps the blocking wait.
    ``trace`` is the submitting request's SAMPLED span context (None for
    unsampled/untraced requests — the common case pays one attribute):
    the dispatcher records the queue-wait and the shared device-dispatch
    span into each sampled member's trace, linked across the batch."""

    __slots__ = (
        "row", "served", "event", "result", "error", "enqueued_at", "on_done",
        "trace", "enqueued_perf", "source",
    )

    def __init__(self, row: np.ndarray, served, on_done=None, trace=None,
                 source=None):
        self.row = row
        self.served = served
        self.event = threading.Event()
        self.result: float | None = None
        self.error: BaseException | None = None
        self.enqueued_at = time.monotonic()
        self.on_done = on_done
        self.trace = trace
        # perf_counter twin of enqueued_at: trace spans live on the
        # perf_counter timeline (obs.tracing); only taken when traced
        self.enqueued_perf = time.perf_counter() if trace is not None else 0.0
        #: which ingress this row arrived through (a front-end id in the
        #: disaggregated split; None in-process) — flush accounting uses
        #: it to PROVE batches merge rows across front-ends
        self.source = source


class RequestCoalescer:
    """Batches concurrent single-row predictions into shared device calls.

    Thread-safe; one dispatcher thread per instance (one instance per
    worker process — replicas never share one, exactly as they never
    share a predictor).
    """

    def __init__(
        self,
        window_ms: float = DEFAULT_WINDOW_MS,
        max_rows: int = DEFAULT_MAX_ROWS,
        max_pending: int = 4096,
    ):
        if window_ms <= 0:
            raise ValueError(f"window_ms must be > 0, got {window_ms}")
        if max_rows < 1:
            raise ValueError(f"max_rows must be >= 1, got {max_rows}")
        self.window_s = window_ms / 1000.0
        self.max_rows = max_rows
        self.max_pending = max_pending
        self._cond = threading.Condition()
        self._pending: list[_Submission] = []
        #: submissions taken by the dispatcher but not yet scattered —
        #: kept as objects (not a count) so drain() can wait on exactly
        #: the submissions that existed when it was called
        self._inflight: list[_Submission] = []
        self._stopped = False
        self._started = False
        # observability: the dispatches-vs-requests ratio IS the payoff
        self.rows_submitted = 0
        self.batches_dispatched = 0
        self.rows_dispatched = 0
        self.max_batch_rows = 0
        # cross-ingress merge accounting: the disaggregated split's
        # whole point is that ONE coalescer sees every front-end's rows,
        # so flushes mixing sources are the direct evidence that fleet
        # scale-out concentrates batches instead of fragmenting them
        self.multi_source_flushes = 0
        self.sources_seen: set = set()
        # phase histograms (obs.registry): queue wait is the latency the
        # coalescer COSTS, device dispatch the work it AMORTISES — the
        # same bodywork_tpu_device_dispatch_seconds the app's direct
        # (uncoalesced) path observes into, so the two paths compare
        reg = get_registry()
        self._m_queue_wait = reg.histogram(
            "bodywork_tpu_queue_wait_seconds",
            "Coalescer queue wait: row enqueue -> batch execution start",
        )
        self._m_dispatch = reg.histogram(
            "bodywork_tpu_device_dispatch_seconds",
            "Device-dispatch phase: one padded predictor call",
        )
        self._m_batch_rows = reg.histogram(
            "bodywork_tpu_coalesced_batch_rows",
            "Rows per coalesced device dispatch (amortisation factor)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512),
        )
        self._m_saturated = reg.counter(
            "bodywork_tpu_coalescer_saturated_total",
            "submit() rejections: pending queue full or coalescer stopped",
        )
        # flush telemetry (the tuner's primary window/max_rows signal,
        # tune/collect.py): occupancy says whether flushes FILL (window
        # too small / max_rows too big leaves capacity on the table;
        # ~1.0 under load means max_rows is the binding constraint), the
        # reason split says WHICH policy edge is firing
        self._m_occupancy = reg.histogram(
            "bodywork_tpu_serve_batch_occupancy_ratio",
            "Coalesced-flush occupancy: rows flushed / max_rows",
            buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
        )
        self._m_flush_reason = reg.counter(
            "bodywork_tpu_serve_batch_flush_total",
            "Coalesced-batch flushes by triggering policy edge "
            "(window=deadline elapsed, max_rows=batch filled during the "
            "window, saturation=a full batch was already queued — no "
            "window wait at all)",
        )
        self._m_multisource = reg.counter(
            "bodywork_tpu_coalesced_multisource_flush_total",
            "Coalesced flushes whose batch merged rows from more than "
            "one ingress source (disaggregated mode: cross-front-end "
            "batch formation actually happening)",
        )
        self._thread = threading.Thread(
            target=self._run, name="request-coalescer", daemon=True
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "RequestCoalescer":
        with self._cond:
            if self._started:
                return self
            self._started = True
        self._thread.start()
        log.info(
            f"request coalescer on: window={self.window_s * 1e3:.1f}ms "
            f"max_rows={self.max_rows}"
        )
        return self

    def reconfigure(self, window_ms: float | None = None,
                    max_rows: int | None = None) -> dict:
        """Mutate the live coalescing policy in place (the online tuning
        controller's apply path): the dispatcher reads ``window_s`` /
        ``max_rows`` fresh on every loop iteration under ``_cond``, so a
        change here takes effect on the NEXT batch boundary — no drain,
        no dropped submissions, in-flight batches finish under the
        policy they started with. Validation matches the constructor
        (``window_ms`` must stay > 0: coalescing on/off is an app-level
        topology decision — a dispatcher thread cannot un-exist — so
        the 0=off transition is deliberately NOT live-mutable and the
        controller pins that in its mutable-live contract). Returns the
        applied values."""
        if window_ms is not None and window_ms <= 0:
            raise ValueError(f"window_ms must be > 0, got {window_ms}")
        if max_rows is not None and max_rows < 1:
            raise ValueError(f"max_rows must be >= 1, got {max_rows}")
        with self._cond:
            if window_ms is not None:
                self.window_s = window_ms / 1000.0
            if max_rows is not None:
                self.max_rows = int(max_rows)
            # wake the dispatcher so a SHORTENED window re-arms its
            # deadline now instead of after the old (longer) wait
            self._cond.notify_all()
            applied = {
                "window_ms": round(self.window_s * 1e3, 3),
                "max_rows": self.max_rows,
            }
        log.info(
            f"coalescer reconfigured live: window="
            f"{applied['window_ms']}ms max_rows={applied['max_rows']}"
        )
        return applied

    def stop(self) -> None:
        """Flush everything already enqueued, then stop the dispatcher.
        Late ``submit()`` calls raise :class:`CoalescerSaturated` (the
        caller's direct-dispatch fallback), so shutdown never strands a
        request thread."""
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread.ident is not None:
            self._thread.join(timeout=10)

    # -- request path ------------------------------------------------------
    def submit_nowait(self, served, row: np.ndarray, on_done=None,
                      trace=None, source=None) -> _Submission:
        """Enqueue one row WITHOUT waiting: returns the submission whose
        ``event`` (pull) or ``on_done`` callback (push — must be set
        HERE, before the enqueue, or the dispatcher can complete the
        batch first and the callback never fires) signals completion.
        The asyncio front-end's bridge into the coalescer; raises
        :class:`CoalescerSaturated` exactly as :meth:`submit` does.
        ``trace``: the request's sampled span context, or None.
        ``source``: the ingress this row arrived through (the serving
        dispatcher tags each row with its front-end id)."""
        sub = _Submission(np.asarray(row, dtype=np.float32), served, on_done,
                          trace, source)
        with self._cond:
            if self._stopped or not self._started:
                self._m_saturated.inc()
                raise CoalescerSaturated("coalescer is not running")
            if len(self._pending) >= self.max_pending:
                self._m_saturated.inc()
                raise CoalescerSaturated(
                    f"{len(self._pending)} requests already pending"
                )
            self._pending.append(sub)
            self.rows_submitted += 1
            self._cond.notify_all()
        return sub

    def pending_depth(self) -> int:
        """Rows enqueued or mid-dispatch — the coalescer's contribution
        to the queue-depth picture (/healthz surfaces it when no
        admission controller owns the number)."""
        with self._cond:
            return len(self._pending) + len(self._inflight)

    def submit(self, served, row: np.ndarray, timeout_s: float = 60.0,
               trace=None) -> float:
        """Enqueue one ``(1, n_features)``-shaped row against ``served``
        (the app's immutable served-model bundle) and block until its
        prediction returns. Raises :class:`CoalescerSaturated` when the
        queue is full/stopped, or the batch's own error if the device
        call failed."""
        sub = self.submit_nowait(served, row, trace=trace)
        if not sub.event.wait(timeout_s):
            raise TimeoutError(
                f"coalesced prediction not ready within {timeout_s:.0f}s"
            )
        if sub.error is not None:
            raise sub.error
        return sub.result

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Block until every submission enqueued before this call has
        been dispatched and scattered — the hot-swap path calls this
        after an atomic model swap so no ALREADY-ENQUEUED old-model row
        is still queued when the swap returns. (A request thread that
        snapshotted the old bundle but has not yet enqueued is the same
        in-flight case as the unbatched app: it finishes on the model it
        started with — the swap bounds, it does not eliminate, the old
        generation's lifetime.) Only the submissions present at call
        time are waited on (their completion events fire on scatter,
        success or error): new traffic arriving mid-drain never extends
        the wait, so a swap under sustained load still returns promptly.
        Returns False on timeout."""
        with self._cond:
            targets = self._pending + self._inflight
        deadline = time.monotonic() + timeout_s
        for sub in targets:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not sub.event.wait(remaining):
                return False
        return True

    # -- dispatcher --------------------------------------------------------
    def _take_batch_locked(self) -> list[_Submission]:
        """The queue's leading run of submissions sharing one served
        bundle AND one row shape, up to ``max_rows``. Grouping by bundle
        identity is the hot-swap guarantee (a batch can never span a
        model swap); grouping by shape keeps a concurrent odd-width row
        (e.g. a multi-feature payload scored for its first row) from
        failing the whole stack for its neighbours."""
        head = self._pending[0]
        n = 1
        while (
            n < len(self._pending)
            and n < self.max_rows
            and self._pending[n].served is head.served
            and self._pending[n].row.shape == head.row.shape
        ):
            n += 1
        batch, self._pending = self._pending[:n], self._pending[n:]
        self._inflight.extend(batch)
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._stopped:
                    self._cond.wait()
                if not self._pending and self._stopped:
                    return
                # assemble: wait out the window for neighbours unless the
                # batch fills (or a swap boundary caps it) first. The
                # deadline is anchored to the HEAD's enqueue time, not
                # this loop iteration: a row left behind by a previous
                # partial take (shape/bundle split, max_rows cap) has
                # already aged and flushes the moment its own window is
                # up — "at most one window of extra latency" holds for
                # every request, not just batch heads. A stopping
                # coalescer flushes immediately.
                # pre-wait depth classifies the flush: a backlog already
                # holding a full batch means this flush waited for
                # nothing (saturation — back-to-back full flushes)
                initial_depth = len(self._pending)
                deadline = self._pending[0].enqueued_at + self.window_s
                while not self._stopped and len(self._pending) < self.max_rows:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch = self._take_batch_locked()
            if initial_depth >= self.max_rows:
                reason = "saturation"
            elif len(batch) >= self.max_rows:
                reason = "max_rows"
            else:
                reason = "window"
            self._execute(batch, reason)
            with self._cond:
                # single dispatcher: the in-flight set IS this batch
                self._inflight.clear()

    def _execute(self, batch: list[_Submission],
                 reason: str = "window") -> None:
        served = batch[0].served
        now = time.monotonic()
        t_exec = time.perf_counter()
        for sub in batch:
            self._m_queue_wait.observe(now - sub.enqueued_at)
        self._m_batch_rows.observe(len(batch))
        self._m_occupancy.observe(len(batch) / self.max_rows)
        self._m_flush_reason.inc(reason=reason)
        sources = {sub.source for sub in batch if sub.source is not None}
        if sources:
            self.sources_seen.update(sources)
            if len(sources) > 1:
                self.multi_source_flushes += 1
                self._m_multisource.inc()
        # trace fan-in: each SAMPLED member gets its queue-wait span and
        # the batch's shared device-dispatch span, the latter carrying
        # every member's request span id as links — one coalesced
        # dispatch explains N request traces (obs.tracing)
        traced = [sub for sub in batch if sub.trace is not None]
        links = [sub.trace.root_span_id for sub in traced]
        try:
            X = np.vstack([sub.row for sub in batch])
            t0 = time.perf_counter()
            predictions = served.predictor.predict(X)
            t1 = time.perf_counter()
            self._m_dispatch.observe(t1 - t0)
            for sub in traced:
                sub.trace.add(
                    "queue-wait", sub.enqueued_perf, t_exec,
                )
                sub.trace.add(
                    "device-dispatch", t0, t1,
                    coalesced=True, batch_rows=len(batch), links=links,
                )
            for i, sub in enumerate(batch):
                sub.result = float(predictions[i])
        except BaseException as exc:  # scatter, don't kill the dispatcher
            log.error(
                f"coalesced batch of {len(batch)} failed: {exc!r}"
            )
            for sub in batch:
                sub.error = exc
        finally:
            self.batches_dispatched += 1
            self.rows_dispatched += len(batch)
            self.max_batch_rows = max(self.max_batch_rows, len(batch))
            for sub in batch:
                sub.event.set()
                if sub.on_done is not None:
                    try:
                        # push-style completion (the asyncio bridge); a
                        # broken callback must not strand the REST of
                        # the batch or kill the dispatcher
                        sub.on_done(sub)
                    except Exception as exc:
                        log.error(f"submission on_done callback failed: {exc!r}")

    def stats(self) -> dict:
        """Dispatch accounting: ``rows_dispatched / batches_dispatched``
        is the realised mean batch size — the amortisation factor."""
        with self._cond:
            return {
                "rows_submitted": self.rows_submitted,
                "batches_dispatched": self.batches_dispatched,
                "rows_dispatched": self.rows_dispatched,
                "max_batch_rows": self.max_batch_rows,
                "window_ms": round(self.window_s * 1e3, 3),
                "max_rows": self.max_rows,
                "multi_source_flushes": self.multi_source_flushes,
                "sources_seen": sorted(self.sources_seen),
            }
