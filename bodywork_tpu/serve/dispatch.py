"""The device-owning dispatcher of the disaggregated serving split.

``serve --frontends N`` runs exactly ONE of these processes per service.
It owns everything accelerator-shaped — the predictor and its AOT cache,
the canary bundles, the checkpoint watcher, the prediction-sanity
firewall, and the :class:`~bodywork_tpu.serve.batcher.RequestCoalescer`
— and serves the shared-memory row-queue (``serve.rowqueue``) instead of
HTTP. The N front-end processes (``serve.frontend``) parse and admit;
this process scores.

Why the coalescer moves here: under ``--workers N`` each SO_REUSEPORT
replica coalesces only its own kernel-balanced connection share, so
scale-out FRAGMENTS batches — N workers at the same offered load flush
batches 1/N the size. Dispatcher-side, the coalescer sees the union of
every front-end's rows: adding front-ends (more parse capacity)
CONCENTRATES batches instead. Each submission is tagged with its
front-end id (``source=``), so the coalescer's flush accounting can
prove cross-front-end merging, and the
``bodywork_tpu_serve_batch_occupancy_ratio`` histogram the tuner already
reads now describes service-wide batch formation.

Coalescing therefore defaults ON here (the in-process engines keep their
opt-in default): a dispatcher without a coalescer would serialize every
front-end's single rows through one process and be strictly worse than
``--workers``. An explicit ``batch_window_ms=0`` still disables it.

Scoring semantics are the in-process path's, run against the same
``ScoringApp``: canary routing by the same seeded hash, stream
accounting, coalescer-saturated fallback to direct dispatch, firewall
before any prediction is written back. The reply carries predictions +
the ANSWERING bundle's identity; the front-end renders bytes from them
through the shared wire helpers — which is how disaggregated responses
stay byte-identical to in-process ones.

Liveness: the supervisor (``serve.multiproc``) clears ``queue.up`` and
bumps ``queue.epoch`` when this process dies, which fails every
in-flight front-end wait into 503 + Retry-After; on respawn this module
re-arms ``up`` only after the model is loaded and the queue loop is
about to run. Stale descriptors from before the death are dropped by the
generation guard — a respawned dispatcher can never tear a response.
"""
from __future__ import annotations

import os
import signal
import sys
import time

import numpy as np

from bodywork_tpu.serve.rowqueue import KIND_SINGLE, RowQueueServer
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.dispatch")

__all__ = ["DispatchServer", "dispatcher_main"]


class DispatchServer:
    """Pumps the row-queue into a :class:`~bodywork_tpu.serve.app.
    ScoringApp`: poll a submission, score it exactly as the in-process
    engines would, reply with predictions + the answering bundle."""

    def __init__(self, app, queue, server=None):
        from bodywork_tpu.serve.app import PredictionSanityError
        from bodywork_tpu.serve.batcher import CoalescerSaturated

        self.app = app
        # transport-agnostic: any server with the RowQueueServer
        # poll/reply surface pumps here (serve.netqueue passes the
        # socket one for the cross-host split)
        self.server = server if server is not None else RowQueueServer(queue)
        self._sanity_error = PredictionSanityError
        self._saturated = CoalescerSaturated
        self._stopping = False

    def stop(self) -> None:
        self._stopping = True

    def serve_forever(self, poll_timeout_s: float = 0.2) -> None:
        while not self._stopping:
            sub = self.server.poll(poll_timeout_s)
            if sub is not None:
                self.process(sub)

    # -- scoring -----------------------------------------------------------
    def process(self, sub) -> None:
        """Score one submission. Every exit path writes a reply — a
        front-end must never be left waiting on a slot this process has
        already given up on."""
        app = self.app
        try:
            X = sub.X
            served, stream = app.route_stream(X)
            if served is None:
                self.server.reply(sub, 503)
                return
            if app.stream_metrics_active():
                app.count_stream_request(served, stream)
            if sub.kind == KIND_SINGLE:
                X2 = np.array(X, ndmin=2)  # scalar -> (1, 1), as the reference
                if app.batcher is not None and X2.shape[0] == 1:
                    try:
                        # tagged with the submitting front-end: the
                        # flush accounting proves batches merge rows
                        # ACROSS front-ends (the split's whole point)
                        app.batcher.submit_nowait(
                            served, X2[0],
                            on_done=lambda s, sub=sub, served=served,
                            stream=stream, X2=X2: self._coalesced_done(
                                sub, served, stream, X2, s
                            ),
                            source=f"frontend-{sub.frontend_id}",
                        )
                        return  # replied from the coalescer's callback
                    except self._saturated:
                        app._m_fallbacks.inc()
                predictions = self._predict(served, X2)
                prediction0 = float(np.asarray(predictions).ravel()[0])
                self._finish_single(sub, served, stream, X2, prediction0)
            else:
                X2 = X if X.ndim else X[None]
                predictions = self._predict(served, X2)
                reason = app.sanity_reason(served, predictions)
                if reason is not None:
                    served, predictions = app.firewall(
                        served, stream, X2, predictions, reason
                    )
                self.server.reply(sub, 200, predictions, served)
        except self._sanity_error:
            # production non-finite: the zero-garbage guarantee holds by
            # 500, exactly as in-process (app.firewall already counted)
            self.server.reply(sub, 500)
        except Exception as exc:
            log.error(f"dispatcher failed scoring a submission: {exc!r}")
            self.server.reply(sub, 500)

    def _predict(self, served, X):
        t0 = time.perf_counter()
        try:
            return served.predictor.predict(X)
        finally:
            self.app._m_dispatch.observe(time.perf_counter() - t0)

    def _coalesced_done(self, sub, served, stream, X2, submission) -> None:
        """Runs on the coalescer's dispatcher thread. A batch error maps
        to the same 500 the in-process engines answer."""
        try:
            if submission.error is not None:
                self.server.reply(sub, 500)
                return
            self._finish_single(sub, served, stream, X2, submission.result)
        except Exception as exc:
            log.error(f"dispatcher reply after coalesced batch failed: "
                      f"{exc!r}")
            self.server.reply(sub, 500)

    def _finish_single(self, sub, served, stream, X2, prediction0) -> None:
        """Firewall + reply for a single-row prediction (both the
        coalesced and the direct path end here)."""
        app = self.app
        reason = app.sanity_reason(served, prediction0)
        if reason is not None:
            try:
                served, fallback = app.firewall(
                    served, stream, X2, prediction0, reason
                )
            except self._sanity_error:
                self.server.reply(sub, 500)
                return
            prediction0 = float(np.asarray(fallback).ravel()[0])
        self.server.reply(sub, 200, [prediction0], served)


def dispatcher_main(store_path: str, queue, ready,
                    engine: str = "xla",
                    watch_interval_s: float | None = None,
                    buckets=None,
                    batch_window_ms: float | None = None,
                    batch_max_rows: int | None = None,
                    metrics_dir: str | None = None,
                    dtype: str = "float32",
                    tuned_config: str | None = None,
                    transport: str = "shm",
                    dispatcher_addr=None,
                    standby: bool = False,
                    leader_ttl_s: float | None = None):
    """The dispatcher process entrypoint (mirrors ``multiproc._worker_main``
    minus HTTP): load the serving checkpoint, build the predictor, arm
    the dispatcher-side coalescer, pump the row-queue. ``up`` flips to 1
    only once a model is loaded — front-end ``/healthz`` stays 503 until
    the service can actually score.

    ``transport`` selects the queue the dispatcher serves: ``"shm"``
    pumps the shared-memory ``queue`` (same-host fleet); ``"tcp"`` /
    ``"unix"`` bind a :class:`~bodywork_tpu.serve.netqueue.NetQueueServer`
    at ``dispatcher_addr`` instead, and ``queue`` may be ``None`` (the
    standalone k8s dispatcher Deployment has no shm arena to share).
    ``ready`` may be ``None`` too when there is no supervising parent.

    ``standby=True`` (socket transports only) runs this dispatcher as a
    WARM leadership candidate (``serve.leadership``): load the model,
    warm the predictor, signal ``ready`` — then block campaigning for
    the CAS lease on the artefact store and only bind the listen
    address after WINNING it, announcing the lease fence in every
    HELLO. Takeover therefore costs zero compiles: the standby's only
    cold step is the bind. A lost lease (renew fails past TTL) stops
    the serve loop so the process exits and respawns as a fresh
    candidate rather than serving as a zombie."""
    from bodywork_tpu.models.checkpoint import load_model, resolve_serving_key
    from bodywork_tpu.serve.app import create_app
    from bodywork_tpu.serve.batcher import DEFAULT_WINDOW_MS
    from bodywork_tpu.serve.server import (
        _registry_bounds,
        build_serving_predictor,
    )
    from bodywork_tpu.store import open_scoped_store

    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(0))
    store = open_scoped_store(store_path)
    # the tuned document's serving knobs are DISPATCHER-SCOPED in the
    # split (tune.config.DISPATCHER_SCOPED_KNOBS): window/max_rows shape
    # the one coalescer that exists, buckets shape the one predictor.
    # max_pending resolves here too but is applied by the SUPERVISOR to
    # the front-ends' shared admission budget — admission must stay
    # upstream of the queue.
    tuned_digest = None
    if tuned_config:
        from bodywork_tpu.tune.config import resolve_serving_knobs

        resolved = resolve_serving_knobs(
            store, tuned_config,
            batch_window_ms=batch_window_ms,
            batch_max_rows=batch_max_rows,
            buckets=tuple(buckets) if buckets else None,
            max_pending=None,
        )
        batch_window_ms = resolved.batch_window_ms
        batch_max_rows = resolved.batch_max_rows
        buckets = resolved.buckets
        tuned_digest = resolved.tuned_digest
    served_key, served_source = resolve_serving_key(store)
    model, model_date = load_model(store, served_key)
    predictor, _served_dtype = build_serving_predictor(
        store, model, None, engine, buckets=buckets, dtype=dtype,
    )
    # coalescing defaults ON dispatcher-side (see module docstring);
    # explicit 0 disables
    window = batch_window_ms if batch_window_ms is not None else (
        DEFAULT_WINDOW_MS
    )
    app = create_app(model, model_date, predictor=predictor,
                     buckets=buckets,
                     batch_window_ms=window,
                     batch_max_rows=batch_max_rows,
                     metrics_dir=metrics_dir,
                     model_key=served_key, model_source=served_source,
                     model_bounds=_registry_bounds(store, served_key))
    app.tuned_config_digest = tuned_digest
    flusher = None
    if metrics_dir is not None:
        # the dispatcher's metrics (coalescer occupancy, handoff
        # histogram, queue depth) flush into the shared dir, so ANY
        # front-end's /metrics scrape exposes them service-wide
        from bodywork_tpu.obs import get_registry
        from bodywork_tpu.obs.multiproc import MetricsFlusher

        flusher = MetricsFlusher(get_registry(), metrics_dir).start()
    watcher = None
    if watch_interval_s:
        from bodywork_tpu.ops.slo import SloWatchdog, policy_from_env
        from bodywork_tpu.serve.reload import CheckpointWatcher

        watcher = CheckpointWatcher(
            app, store, poll_interval_s=watch_interval_s,
            engine=engine, served_key=served_key, buckets=buckets,
            slo_watchdog=SloWatchdog(store, [app],
                                     policy=policy_from_env()),
            dtype=dtype,
        ).start()
    net_server = None
    election = None
    try:
        if standby:
            if transport not in ("tcp", "unix"):
                raise ValueError(
                    "standby leadership needs a socket transport "
                    "(tcp/unix) — the shm queue is single-host, its "
                    "supervisor respawn is already the takeover"
                )
            from bodywork_tpu.serve.leadership import LeaderElection

            # WARM standby: everything above (model, predictor, AOT
            # warmup, coalescer) is already paid. Signal ready BEFORE
            # campaigning — the losing candidate parks here and must
            # not trip the supervisor's startup timeout.
            if ready is not None:
                ready.put(os.getpid())
            addr_str = (
                dispatcher_addr[1] if dispatcher_addr[0] == "unix"
                else f"{dispatcher_addr[1]}:{dispatcher_addr[2]}"
            )
            election = LeaderElection(
                store, ttl_s=leader_ttl_s, address=addr_str,
            )
            log.info(
                "dispatcher warm, campaigning for the serve lease "
                f"(owner {election.lease.owner})"
            )
            election.campaign()
            from bodywork_tpu.serve.netqueue import NetQueueServer

            # bind only AFTER winning: the listen address itself is the
            # readiness signal (k8s tcpSocket probe routes to the
            # leader), and the HELLO fence refuses zombie ex-leaders
            net_server = NetQueueServer(
                dispatcher_addr, fence=election.fence
            )
            dispatch = DispatchServer(app, queue, server=net_server)
            # a lost lease stops the serve loop: exit and re-candidate
            # beats serving split-brain
            election.on_lost = dispatch.stop
            election.start_renewer()
        elif transport in ("tcp", "unix"):
            from bodywork_tpu.serve.netqueue import NetQueueServer

            # bind BEFORE signalling ready: a front-end told to connect
            # must find a listener, not a race
            net_server = NetQueueServer(dispatcher_addr)
            dispatch = DispatchServer(app, queue, server=net_server)
        else:
            dispatch = DispatchServer(app, queue)
        if queue is not None:
            queue.up.value = 1
        if ready is not None and not standby:
            ready.put(os.getpid())
        log.info(
            f"dispatcher serving the {transport} row-queue "
            f"(model {served_key}, window={window}ms"
            + (f", fence {election.fence}" if election else "")
            + ")"
        )
        dispatch.serve_forever()
    finally:  # pragma: no cover - only on signal teardown
        if queue is not None:
            queue.up.value = 0
        if election is not None:
            election.stop()
        if net_server is not None:
            net_server.close()
        if watcher is not None:
            watcher.stop()
        if flusher is not None:
            flusher.stop()
        app.close()
