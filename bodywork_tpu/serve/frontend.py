"""The disaggregated serving front-end: parse + admission, no model.

``serve --frontends N`` splits the serving plane that ``--workers N``
replicates: N of THESE processes own the HTTP socket (SO_REUSEPORT) and
do request parse, feature validation, and admission, while exactly one
dispatcher process (``serve.dispatch``) owns the predictor, the AOT
cache, the canary bundles, and the request coalescer. The two halves
meet over the shared-memory row-queue (``serve.rowqueue``): a front-end
writes a request's rows once and enqueues a descriptor; the dispatcher
reads them zero-copy, scores, and replies with predictions plus the
answering bundle's identity.

What a front-end process deliberately does NOT have: JAX (a guard test
pins that importing this module never imports it), a model, a
coalescer. What it keeps, unchanged from the in-process engines:

- **Admission-shed-BEFORE-parse.** The :class:`~bodywork_tpu.serve.
  admission.AdmissionController` (with its cross-process
  ``SharedBudgetSlot`` budget) runs first, upstream of body parse — a
  shed request never touches the row-queue (``rows_submitted`` stays
  untouched; a regression test pins it), exactly the zero-footprint
  invariant the in-process engines hold.
- **Byte-identical responses.** Success bodies are rendered from the
  reply's bundle identity through the same ``serve.wire`` helpers and
  the same pre-serialized single-row template; error bodies reuse the
  in-process strings. The bench pins disaggregated == in-process bytes
  over real HTTP.
- **Degrade, never wedge.** A dead dispatcher turns scoring into
  503 + Retry-After (``/healthz`` flips 503 so probes see it) the
  moment the supervisor observes the death; in-flight waits are failed
  by the row-queue epoch bump. The supervisor's respawn flips it back —
  front-ends hold no dispatcher state beyond the shared handles, so
  healing requires nothing from them.
"""
from __future__ import annotations

import json
import threading
import time

from werkzeug.exceptions import HTTPException, MethodNotAllowed, NotFound
from werkzeug.wrappers import Request, Response

from bodywork_tpu.obs import get_registry
from bodywork_tpu.obs.tracing import (
    TRACE_ID_HEADER,
    TRACEPARENT_HEADER,
    get_tracer,
    parse_traceparent,
)
from bodywork_tpu.serve.admission import count_shed
from bodywork_tpu.serve.rowqueue import (
    KIND_BATCH,
    KIND_SINGLE,
    DispatcherUnavailable,
    SlotsExhausted,
)
from bodywork_tpu.serve.wire import (
    BINARY_CONTENT_TYPE,
    MODEL_KEY_HEADER,
    BatchResponseTemplate,
    SingleResponseTemplate,
    parse_binary_rows,
    parse_features,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.frontend")

__all__ = ["FrontendApp"]

#: mirrors serve.app.RETRY_AFTER_S (the no-admission fallback hint);
#: duplicated rather than imported because serve.app imports JAX — a
#: guard test pins the two equal
RETRY_AFTER_S = 5

#: ceiling on one row-queue rendezvous — mirrors the coalescer's
#: COALESCE_TIMEOUT_S; the epoch-bump failure path makes hitting it
#: near-impossible (a dead dispatcher fails waits in <1s)
DISPATCH_TIMEOUT_S = 60.0

_SCORING_ROUTES = ("/score/v1", "/score/v1/batch")

#: parse/serialize phase buckets — MUST stay equal to serve.app's
#: _FAST_PHASE_BUCKETS (same histogram names; the registry rejects a
#: re-registration with different buckets)
_FAST_PHASE_BUCKETS = (
    0.00001, 0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1,
)


def _json_response(payload: dict, status: int = 200) -> Response:
    return Response(
        json.dumps(payload), status=status, mimetype="application/json"
    )


class FrontendApp:
    """WSGI front-end over a :class:`~bodywork_tpu.serve.rowqueue.
    RowQueueClient`. Route set, admission placement, metrics names, and
    response bytes all mirror :class:`~bodywork_tpu.serve.app.
    ScoringApp`; the scoring handlers enqueue instead of predict.

    The transport-agnostic core (``parse_rows`` / ``submit`` /
    ``render_reply`` and the canned backpressure parts) is also what the
    asyncio engine's front-end handlers drive — one implementation of
    the wire behaviour, two HTTP fronts, exactly as in-process serving
    splits ScoringApp from its engines."""

    #: how serve.aio tells a front-end app from a scoring app without
    #: importing either (isinstance would force the import)
    is_frontend = True

    def __init__(self, client, admission=None, metrics_dir=None):
        self.client = client
        self.admission = admission
        self.metrics_dir = metrics_dir
        self.tracer = get_tracer()
        reg = get_registry()
        # same metric families as ScoringApp: dashboards see one request
        # stream regardless of the serving topology
        self._m_requests = reg.counter(
            "bodywork_tpu_http_requests_total",
            "HTTP requests served, by route and status",
        )
        self._m_latency = reg.histogram(
            "bodywork_tpu_scoring_latency_seconds",
            "End-to-end handler time of successful scoring requests",
        )
        self._m_parse = reg.histogram(
            "bodywork_tpu_request_parse_seconds",
            "Request-parse phase: JSON body -> validated feature array",
            buckets=_FAST_PHASE_BUCKETS,
        )
        self._m_serialize = reg.histogram(
            "bodywork_tpu_response_serialize_seconds",
            "Serialization phase: prediction -> JSON response",
            buckets=_FAST_PHASE_BUCKETS,
        )
        # single-row templates per answering-bundle identity: the
        # dispatcher names the bundle in each reply; invalidation is
        # structural (a hot swap changes the identity, hence the key)
        self._templates: dict = {}
        self._templates_lock = threading.Lock()
        self._routes = {
            ("POST", "/score/v1"): self.score_single,
            ("POST", "/score/v1/batch"): self.score_batch,
            ("GET", "/healthz"): self.healthz,
            ("GET", "/metrics"): self.metrics_endpoint,
        }

    # -- transport-agnostic core (shared with the aio engine) --------------
    def retry_after_s(self) -> int:
        if self.admission is not None:
            return self.admission.retry_after_s()
        return RETRY_AFTER_S

    def parse_rows(self, body: bytes, content_type: str):
        """Decode a scoring request body — JSON ``{"X": [...]}`` or the
        binary row framing, selected by content type — into ``(X,
        error_message)``. Same helpers, hence same arrays and same 400
        strings, as the in-process engines."""
        mimetype = (content_type or "").split(";", 1)[0].strip().lower()
        if mimetype == BINARY_CONTENT_TYPE:
            return parse_binary_rows(body)
        try:
            payload = json.loads(body) if body else None
        except ValueError:
            payload = None
        return parse_features(payload)

    def submit(self, X, single: bool, on_done, trace_id=None) -> None:
        """Enqueue one parsed request; raises
        :class:`DispatcherUnavailable` / :class:`SlotsExhausted` when
        nothing was enqueued (the caller maps them to 503/429)."""
        self.client.submit(
            X, KIND_SINGLE if single else KIND_BATCH, on_done,
            trace_id=trace_id,
        )

    def _template_for(self, reply, single: bool):
        key = (reply.model_info, reply.model_date, single)
        template = self._templates.get(key)
        if template is None:
            cls = SingleResponseTemplate if single else BatchResponseTemplate
            with self._templates_lock:
                template = self._templates.setdefault(
                    key, cls(reply.model_info, reply.model_date),
                )
        return template

    def render_reply(self, reply, single: bool):
        """A dispatcher reply -> ``(status, body_bytes, extra_headers)``,
        byte-identical to the in-process response for the same request:
        same template splice on the single-row path, same payload
        builders, same error strings and Retry-After placement."""
        if reply.status == 200:
            t0 = time.perf_counter()
            if single:
                body = self._template_for(reply, True).render(
                    float(reply.predictions[0])
                )
            else:
                # same pre-serialized splice on the batch path
                # (serve.wire.BatchResponseTemplate) — byte-identical
                # to json.dumps(batch_score_payload(...))
                body = self._template_for(reply, False).render(
                    reply.predictions
                )
            self._m_serialize.observe(time.perf_counter() - t0)
            extra = (
                ((MODEL_KEY_HEADER, reply.model_key),)
                if reply.model_key else ()
            )
            return 200, body, extra
        if reply.status == 503:
            return (
                503,
                json.dumps(
                    {"error": "no model loaded yet; retry shortly"}
                ).encode(),
                (("Retry-After", str(self.retry_after_s())),),
            )
        return (
            500,
            json.dumps({"error": "internal server error"}).encode(),
            (),
        )

    def unavailable_parts(self):
        """The dead-dispatcher 503: honest about WHY (distinct from the
        no-model-yet 503 — an operator must tell "still warming" from
        "the singleton died"), still retryable."""
        return (
            503,
            json.dumps(
                {"error": "scoring dispatcher unavailable; retry shortly"}
            ).encode(),
            (("Retry-After", str(self.retry_after_s())),),
        )

    def shed_parts(self):
        return (
            429,
            json.dumps(
                {"error": "server over capacity; request shed"}
            ).encode(),
            (("Retry-After", str(self.retry_after_s())),),
        )

    def healthz_payload(self):
        """``(payload, status, retry_after_s-or-None)``: 503 while the
        dispatcher is down — a front-end that cannot score must leave
        the endpoints so load concentrates on healthy pods (unlike the
        in-process degraded-but-serving 200)."""
        stats = self.client.stats()
        admission = self.admission
        payload = {
            "status": "ok" if stats["dispatcher_up"]
            else "scoring dispatcher unavailable",
            "role": "frontend",
            "dispatcher_up": stats["dispatcher_up"],
            "queue_depth": (
                admission.queue_depth if admission is not None
                else stats["in_flight"]
            ),
            "admission": admission.state() if admission is not None else None,
            "rowqueue": stats,
            # which transport the handoff rides and how it's doing:
            # kind/connected/reconnects/credit window — one schema for
            # shm and socket clients (both implement transport_state),
            # the operator's first read in the §14 runbook
            "transport": (
                self.client.transport_state()
                if hasattr(self.client, "transport_state") else None
            ),
        }
        if stats["dispatcher_up"]:
            return payload, 200, None
        return payload, 503, self.retry_after_s()

    # -- WSGI plumbing (mirrors ScoringApp.__call__) -----------------------
    def __call__(self, environ, start_response):
        request = Request(environ)
        t0 = time.perf_counter()
        scoring_post = (
            request.method == "POST" and request.path in _SCORING_ROUTES
        )
        trace = None
        tracer = self.tracer
        traced = scoring_post and tracer.enabled
        if traced:
            traceparent = request.headers.get(TRACEPARENT_HEADER)
            if traceparent is not None and (
                parse_traceparent(traceparent) is not None
            ):
                trace = tracer.begin(traceparent, b"")
        # admission FIRST — a shed request must leave zero footprint:
        # no body read, no parse, and (the split's own invariant) no
        # row-queue slot — rows_submitted stays exactly where it was
        admission = self.admission
        admitted = False
        if admission is not None and scoring_post:
            if not admission.try_admit():
                status, body, extra = self.shed_parts()
                response = Response(
                    body, status=status, mimetype="application/json"
                )
                for name, value in extra:
                    response.headers[name] = value
                if trace is not None:
                    if trace.sampled:
                        now = time.perf_counter()
                        trace.add(
                            "admission-shed", now, now,
                            queue_depth=admission.queue_depth,
                        )
                    tracer.finish(trace, request.path, status)
                    response.headers[TRACE_ID_HEADER] = trace.trace_id
                self._m_requests.inc(
                    route=request.path, status=str(status)
                )
                return response(environ, start_response)
            admitted = True
        try:
            # inside the try: reading the body can raise (client abort,
            # bad Content-Length), and the finally below must still
            # release the admission unit — this is the service-wide
            # shared budget, so one leak here would shrink it forever
            if traced and trace is None:
                trace = tracer.begin(
                    None, request.get_data(cache=True, parse_form_data=False)
                )
            handler = self._routes.get((request.method, request.path))
            if handler is None:
                if any(path == request.path for _m, path in self._routes):
                    raise MethodNotAllowed()
                raise NotFound()
            response = handler(request, trace)
        except HTTPException as exc:
            response = _json_response({"error": exc.description}, exc.code)
        except Exception as exc:  # don't leak tracebacks to clients
            log.error(f"unhandled error serving {request.path}: {exc!r}")
            response = _json_response({"error": "internal server error"}, 500)
        finally:
            if admitted:
                admission.release(time.perf_counter() - t0)
        route = (
            request.path
            if any(path == request.path for _m, path in self._routes)
            else "unknown"
        )
        self._m_requests.inc(route=route, status=str(response.status_code))
        if request.path in _SCORING_ROUTES and response.status_code == 200:
            self._m_latency.observe(
                time.perf_counter() - t0,
                exemplar=(
                    trace.trace_id
                    if trace is not None and trace.sampled else None
                ),
            )
        if trace is not None:
            tracer.finish(trace, route, response.status_code)
            response.headers[TRACE_ID_HEADER] = trace.trace_id
        return response(environ, start_response)

    def test_client(self):
        from werkzeug.test import Client

        return Client(self)

    # -- routes ------------------------------------------------------------
    def score_single(self, request: Request, trace=None) -> Response:
        return self._score(request, trace, single=True)

    def score_batch(self, request: Request, trace=None) -> Response:
        return self._score(request, trace, single=False)

    def _score(self, request: Request, trace, single: bool) -> Response:
        sampled = trace is not None and trace.sampled
        t0 = time.perf_counter()
        X, message = self.parse_rows(
            request.get_data(cache=True, parse_form_data=False),
            request.mimetype,
        )
        t1 = time.perf_counter()
        self._m_parse.observe(t1 - t0)
        if sampled:
            trace.add("parse", t0, t1)
        if message is not None:
            return _json_response({"error": message}, 400)
        done = threading.Event()
        box: list = [None]

        def on_done(outcome) -> None:
            box[0] = outcome
            done.set()

        t_submit = time.perf_counter()
        try:
            self.submit(
                X, single, on_done,
                trace_id=trace.trace_id if sampled else None,
            )
        except DispatcherUnavailable:
            status, body, extra = self.unavailable_parts()
            return self._respond(status, body, extra)
        except SlotsExhausted:
            # queue backpressure sheds exactly like a budget refusal
            count_shed("rowqueue")
            status, body, extra = self.shed_parts()
            return self._respond(status, body, extra)
        if not done.wait(DISPATCH_TIMEOUT_S):
            # slot reclamation belongs to the reader/epoch machinery —
            # never free here, or a late reply could tear a reused slot
            log.error("row-queue rendezvous timed out")
            return _json_response({"error": "internal server error"}, 500)
        outcome = box[0]
        if sampled:
            trace.add("rowqueue", t_submit, time.perf_counter())
        if isinstance(outcome, Exception):
            # the dispatcher died mid-request: degraded, not wedged
            status, body, extra = self.unavailable_parts()
            return self._respond(status, body, extra)
        status, body, extra = self.render_reply(outcome, single)
        return self._respond(status, body, extra)

    @staticmethod
    def _respond(status: int, body: bytes, extra) -> Response:
        response = Response(body, status=status, mimetype="application/json")
        for name, value in extra:
            response.headers[name] = value
        return response

    def healthz(self, request: Request, trace=None) -> Response:
        payload, status, retry_after = self.healthz_payload()
        response = _json_response(payload, status)
        if retry_after is not None:
            response.headers["Retry-After"] = str(retry_after)
        return response

    def metrics_endpoint(self, request: Request, trace=None) -> Response:
        """One coherent service-wide scrape regardless of which process
        answers: the front-end merges its live registry with every
        sibling's (and the dispatcher's) flushed snapshots — which is
        how dispatcher-side coalescer metrics stay visible from any
        front-end."""
        from bodywork_tpu.obs.multiproc import aggregated_render

        return Response(
            aggregated_render(get_registry(), self.metrics_dir),
            content_type="text/plain; version=0.0.4; charset=utf-8",
        )
