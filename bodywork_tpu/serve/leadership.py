"""CAS-leased dispatcher leadership: warm-standby failover (ISSUE 19).

The disaggregated split (PR 16/18) put every request behind exactly ONE
device-owning dispatcher, and the committed SIGKILL drill showed the
bill: every request is a 503 until the supervisor respawns it, and
goodput only recovered to 0.92 of pre-kill. This module turns that
blackout into a bounded blip: one or more WARM standby dispatchers —
predictor loaded, AOT buckets compiled, zero compiles left to pay at
takeover — watch a lease document on the artefact store and take over
the moment the active leader's lease expires.

The lease is the PR 7 run-journal construction applied to the serving
plane: an ``(owner, expires_at, fence)`` document at
``serve/dispatcher-leader.json`` (:func:`~bodywork_tpu.store.schema.
dispatcher_leader_key`), mutated EXCLUSIVELY through the store's
compare-and-swap primitive (``put_bytes_if_match``). The active leader
renews it every :attr:`LeaderElection.renew_interval_s`; a standby
finding the lease expired takes over by bumping the fence. Split-brain
is impossible by the same argument the journal makes:

- at most one writer ever holds a given fence (CAS arbitration picks
  exactly one winner per takeover);
- a fenced-out ex-leader's next renew CAS fails against the bumped
  document and raises :class:`LeadershipLost` — it stops serving and
  exits (the supervisor respawns it as a fresh standby candidate);
- the fence rides the netqueue HELLO (``serve.netqueue``), so a client
  that has seen fence N refuses any dispatcher offering fence < N at
  the handshake — a zombie ex-leader that has not yet noticed its lost
  lease can be CONNECTED to but never TRUSTED.

Blackout bound: a dead leader's lease blocks takeover for at most
``ttl_s``; the local supervisor shortens even that by CAS-expiring the
lease of a dispatcher it has OBSERVED dead (:meth:`DispatcherLease.
expire_dead_owner` — safe precisely because the observation is of a
dead process, not a partition). Client-observed blackout is therefore
bounded by lease TTL + one reconnect backoff (docs/RESILIENCE.md).

Steady-state cost: leadership is exactly one CAS renew per renew
interval and ZERO raw puts (a CountingStore test pins this) — the
store never sees an unconditional write from this module.

Metrics: ``bodywork_tpu_serve_leader_state`` (1 leading / 0 standby)
and ``bodywork_tpu_serve_leader_takeovers_total{reason}``
(``fresh`` / ``expired`` / ``released``).

Deliberately jax-free: elections run before (and independently of) any
accelerator work, and tests drive them with injected clocks.
"""
from __future__ import annotations

import json
import os
import socket
import threading
import time
import uuid

from bodywork_tpu.store.base import ArtefactNotFound, CasConflict
from bodywork_tpu.store.schema import dispatcher_leader_key
from bodywork_tpu.utils.logging import get_logger
from bodywork_tpu.utils.retry import full_jitter_delay

log = get_logger("serve.leadership")

__all__ = [
    "DEFAULT_LEADER_TTL_S",
    "LEADER_SCHEMA",
    "DispatcherLease",
    "LeaderElection",
    "LeadershipLost",
    "leader_owner",
    "leader_ttl_from_env",
]

LEADER_SCHEMA = "bodywork_tpu.dispatcher_leader/1"

#: default leader-lease time-to-live. Much shorter than the run
#: journal's 900 s: a run lease guards a DAG step (minutes), this one
#: bounds the serving BLACKOUT a dead leader can cause — it must be
#: renewable cheaply (one CAS) and expirable fast. Env
#: ``BODYWORK_TPU_LEADER_TTL_S`` overrides; size it well above the
#: renew interval (ttl/3) plus your store's worst-case CAS latency.
DEFAULT_LEADER_TTL_S = 5.0

#: renew cadence as a fraction of the TTL: two missed renews still
#: leave slack before expiry, so one slow CAS never costs leadership
RENEW_FRACTION = 1.0 / 3.0

#: standby election poll backoff bounds — drawn through the shared
#: full-jitter helper (utils.retry), so N standbys watching one lease
#: decorrelate exactly like N reconnecting front-ends do
ELECTION_POLL_BASE_S = 0.05
ELECTION_POLL_MAX_S = 1.0

#: CAS attempts per lease write before conceding the race is real
_CAS_ATTEMPTS = 4


class LeadershipLost(RuntimeError):
    """This process's leadership is gone — another dispatcher holds (or
    took over) the lease. The loser must stop serving immediately and
    exit; its supervisor respawns it as a fresh standby candidate."""


def leader_owner() -> str:
    """Identity unique per dispatcher process: ``host:pid:nonce`` (the
    journal's owner shape — the supervisor parses host+pid back out to
    expire the lease of a dispatcher it observed die)."""
    return f"{socket.gethostname()}:{os.getpid()}:{uuid.uuid4().hex[:8]}"


def leader_ttl_from_env(default: float = DEFAULT_LEADER_TTL_S) -> float:
    from bodywork_tpu.utils.env import positive_float_env

    return positive_float_env("BODYWORK_TPU_LEADER_TTL_S", default)


def _count_takeover(reason: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_serve_leader_takeovers_total",
        "Dispatcher leadership acquisitions by reason (fresh: no prior "
        "lease; expired: took over a dead leader's expired lease; "
        "released: prior leader released cleanly)",
    ).inc(reason=reason)


def _leader_state_gauge():
    from bodywork_tpu.obs import get_registry

    return get_registry().gauge(
        "bodywork_tpu_serve_leader_state",
        "Dispatcher leadership role of this process: 1 = active "
        "leader, 0 = warm standby (docs/RESILIENCE.md failover runbook)",
    )


class DispatcherLease:
    """The lease document protocol: CAS reads/writes of
    ``serve/dispatcher-leader.json``, no threads, injectable clock —
    the unit-testable core :class:`LeaderElection` drives.

    Every mutation follows the journal-reader discipline: version token
    read BEFORE payload, conditional write against it, conflict →
    re-read and re-decide. A corrupt document is repaired by the next
    acquire's CAS overwrite (its token is kept), never blindly."""

    def __init__(self, store, owner: str | None = None,
                 ttl_s: float | None = None,
                 address: str | None = None,
                 clock=time.time):
        self.store = store
        self.key = dispatcher_leader_key()
        self.owner = owner or leader_owner()
        self.ttl_s = ttl_s if ttl_s is not None else leader_ttl_from_env()
        #: the listener address the leader publishes (operator-facing:
        #: `cat serve/dispatcher-leader.json` names who is serving where)
        self.address = address
        self.clock = clock
        self.fence = 0
        self._token = None

    # -- reads -------------------------------------------------------------
    def _load(self):
        """``(doc_or_None, version_token)`` — token first, so a CAS
        against it can only win if nothing changed since the read. A
        present-but-corrupt document reads as ``(None, token)``: the
        next acquire CAS-repairs it in place."""
        token = self.store.version_token(self.key)
        try:
            raw = self.store.get_bytes(self.key)
        except ArtefactNotFound:
            return None, None
        try:
            doc = json.loads(raw.decode("utf-8"))
            if isinstance(doc, dict) and doc.get("schema") == LEADER_SCHEMA:
                return doc, token
        except (UnicodeDecodeError, ValueError):
            pass
        log.warning(f"corrupt dispatcher-leader doc at {self.key!r}; "
                    "the next acquire CAS-repairs it")
        return None, token

    def peek(self) -> dict | None:
        """The current lease document (or None) — read-only, for
        introspection (supervisor leader resolution, healthz)."""
        doc, _token = self._load()
        return doc

    def _live_foreign(self, doc: dict | None) -> dict | None:
        if not doc:
            return None
        if (
            doc.get("owner")
            and doc["owner"] != self.owner
            and doc.get("expires_at", 0) > self.clock()
        ):
            return doc
        return None

    def _block(self, fence: int) -> bytes:
        return json.dumps({
            "schema": LEADER_SCHEMA,
            "owner": self.owner,
            "expires_at": self.clock() + self.ttl_s,
            "fence": fence,
            "address": self.address,
        }, sort_keys=True).encode("utf-8")

    # -- the lease protocol ------------------------------------------------
    def try_acquire(self) -> int | None:
        """One acquisition attempt: returns the new fence on success,
        None while a live foreign lease blocks us. CAS races re-read
        and re-decide, bounded by ``_CAS_ATTEMPTS``."""
        for _attempt in range(_CAS_ATTEMPTS):
            doc, token = self._load()
            holder = self._live_foreign(doc)
            if holder is not None:
                return None
            prior_fence = int((doc or {}).get("fence", 0))
            prior_owner = (doc or {}).get("owner")
            fence = prior_fence + 1
            try:
                self._token = self.store.put_bytes_if_match(
                    self.key, self._block(fence), token
                )
            except CasConflict:
                continue  # someone raced this takeover: re-decide
            self.fence = fence
            if doc is None:
                reason = "fresh"
            elif prior_owner and prior_owner != self.owner:
                reason = "expired"
            else:
                reason = "released"
            _count_takeover(reason)
            log.info(
                f"dispatcher leadership acquired (fence {fence}, "
                f"reason {reason}, owner {self.owner})"
            )
            return fence
        return None

    def renew(self) -> None:
        """Extend the held lease by ``ttl_s`` — ONE conditional write
        in the steady state. A conflict whose re-read shows any other
        writer raises :class:`LeadershipLost`: our exclusivity is gone
        the moment someone else touched the document."""
        assert self.fence > 0, "acquire before renewing"
        try:
            self._token = self.store.put_bytes_if_match(
                self.key, self._block(self.fence), self._token
            )
            return
        except CasConflict:
            pass
        doc, token = self._load()
        if doc is not None and doc.get("owner") == self.owner and (
            int(doc.get("fence", 0)) == self.fence
        ):
            # our own write raced a token refresh (e.g. a repair read):
            # re-anchor and renew against the fresh token
            try:
                self._token = self.store.put_bytes_if_match(
                    self.key, self._block(self.fence), token
                )
                return
            except CasConflict:
                pass
        raise LeadershipLost(
            f"dispatcher lease (fence {self.fence}) was taken over; "
            "stopping"
        )

    def release(self) -> None:
        """Clear ownership, KEEPING the fence (the next leader still
        bumps past us). Best-effort: a conflict means someone already
        took over, which is the same outcome."""
        if self.fence <= 0:
            return
        try:
            self._token = self.store.put_bytes_if_match(
                self.key,
                json.dumps({
                    "schema": LEADER_SCHEMA,
                    "owner": None,
                    "expires_at": 0.0,
                    "fence": self.fence,
                    "address": None,
                }, sort_keys=True).encode("utf-8"),
                self._token,
            )
        except Exception:
            pass

    def expire_dead_owner(self, host: str, pid: int) -> bool:
        """Supervisor hook: CAS-expire the lease of an owner OBSERVED
        dead (host+pid parsed back out of the journal-shaped owner
        string), so the standby takes over on its next poll instead of
        waiting out the TTL. Safe by construction — the caller holds
        evidence of a dead process, not a partition guess. Fence is
        KEPT: the takeover still bumps it."""
        doc, token = self._load()
        owner = (doc or {}).get("owner") or ""
        parts = owner.rsplit(":", 2)
        if len(parts) != 3 or parts[0] != host:
            return False
        try:
            if int(parts[1]) != pid:
                return False
        except ValueError:
            return False
        expired = dict(doc)
        expired["expires_at"] = 0.0
        try:
            self.store.put_bytes_if_match(
                self.key,
                json.dumps(expired, sort_keys=True).encode("utf-8"),
                token,
            )
            log.warning(
                f"expired the dispatcher lease of dead owner {owner!r} "
                "(first death observation)"
            )
            return True
        except CasConflict:
            return False  # someone else already moved the document


class LeaderElection:
    """The dispatcher-side driver over :class:`DispatcherLease`: a
    blocking campaign, a renew heartbeat, and the ``on_lost`` unwind.

    Lifecycle (``serve.dispatch.dispatcher_main``)::

        election = LeaderElection(store, address=..., on_lost=stop_fn)
        fence = election.campaign()        # WARM standby blocks here
        ... bind the listener with `fence` in its HELLO, serve ...
        election.start_renewer()           # heartbeat thread
        ...
        election.stop()                    # teardown: release + join

    ``on_lost`` fires (once, from the renewer thread) when a renew
    discovers the lease was taken over — the dispatcher must stop
    serving and let its process exit; a fenced-out zombie that keeps
    its listener bound is refused by every client at the HELLO anyway.
    """

    def __init__(self, store, owner: str | None = None,
                 ttl_s: float | None = None,
                 renew_interval_s: float | None = None,
                 address: str | None = None,
                 on_lost=None,
                 clock=time.time,
                 sleep=time.sleep):
        self.lease = DispatcherLease(
            store, owner=owner, ttl_s=ttl_s, address=address, clock=clock
        )
        self.renew_interval_s = (
            renew_interval_s if renew_interval_s is not None
            else self.lease.ttl_s * RENEW_FRACTION
        )
        self.on_lost = on_lost
        self.clock = clock
        self._sleep = sleep
        self._last_renew: float | None = None
        self._won_at: float | None = None
        self.takeovers = 0
        self._stopping = threading.Event()
        self._renewer: threading.Thread | None = None
        self._gauge = _leader_state_gauge()
        self._gauge.set(0.0)

    @property
    def fence(self) -> int:
        return self.lease.fence

    @property
    def leading(self) -> bool:
        return self._won_at is not None and not self._stopping.is_set()

    # -- election ----------------------------------------------------------
    def campaign(self, stop: threading.Event | None = None) -> int | None:
        """Block until leadership is acquired (returns the fence) or
        ``stop`` fires (returns None). The poll sleeps through the
        shared full-jitter backoff — N standbys watching one lease
        must not stampede the store (or the CAS) in lockstep."""
        stop = stop or self._stopping
        attempt = 0
        while not stop.is_set():
            fence = self.lease.try_acquire()
            if fence is not None:
                self._won_at = self.clock()
                self._last_renew = self._won_at
                self.takeovers += 1
                self._gauge.set(1.0)
                return fence
            self._sleep(full_jitter_delay(
                attempt, ELECTION_POLL_BASE_S, ELECTION_POLL_MAX_S
            ))
            attempt += 1
        return None

    # -- heartbeat ---------------------------------------------------------
    def maybe_renew(self, now: float | None = None) -> bool:
        """Renew iff a renew interval has elapsed — the unit-testable
        heartbeat step (the CountingStore pin drives THIS with a fake
        clock: one CAS per elapsed interval, zero raw puts). Returns
        True when a renew happened. Raises :class:`LeadershipLost`
        through from the lease."""
        now = self.clock() if now is None else now
        if self._last_renew is not None and (
            now - self._last_renew < self.renew_interval_s
        ):
            return False
        self.lease.renew()
        self._last_renew = now
        return True

    def start_renewer(self) -> "LeaderElection":
        assert self.leading, "campaign() before start_renewer()"
        self._renewer = threading.Thread(
            target=self._renew_loop, name="leader-renewer", daemon=True
        )
        self._renewer.start()
        return self

    def _renew_loop(self) -> None:
        # wake a few times per interval so a stop() is honoured fast,
        # but WRITE only once per interval (maybe_renew gates the CAS)
        tick = max(0.01, self.renew_interval_s / 4.0)
        while not self._stopping.wait(tick):
            try:
                self.maybe_renew()
            except LeadershipLost as exc:
                log.error(f"dispatcher leadership lost: {exc}")
                self._gauge.set(0.0)
                self._won_at = None
                if self.on_lost is not None:
                    try:
                        self.on_lost()
                    except Exception as cb_exc:  # must not kill the thread
                        log.error(f"on_lost callback failed: {cb_exc!r}")
                return
            except Exception as exc:
                # a transient store error must not abdicate leadership:
                # the lease has ttl - renew_interval of slack, and the
                # next tick retries (classify/backoff is the store
                # stack's job, not the heartbeat's)
                log.warning(f"leader renew attempt failed: {exc!r}")

    # -- introspection / teardown ------------------------------------------
    def state(self) -> dict:
        """The dispatcher-side leadership block (mirrors the client-side
        one the front-ends serve on /healthz)."""
        now = self.clock()
        return {
            "role": "active" if self.leading else "standby",
            "fence": self.lease.fence,
            "lease_age_s": (
                round(now - self._won_at, 3)
                if self._won_at is not None else None
            ),
            "takeovers_observed": self.takeovers,
        }

    def stop(self) -> None:
        self._stopping.set()
        if self._renewer is not None and self._renewer.ident is not None:
            self._renewer.join(timeout=5)
        if self._won_at is not None:
            self.lease.release()
            self._won_at = None
        self._gauge.set(0.0)
