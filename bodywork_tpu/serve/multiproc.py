"""Multi-process serving replicas on one port (reference
``bodywork.yaml:40-42``: the scoring service is ``replicas: 2`` — two
independent OS processes behind a k8s Service).

The in-process :class:`~bodywork_tpu.serve.server.RoundRobinApp` is the
fast local stand-in for tests and the day loop, but it shares one
GIL/process: replica fault isolation is simulated, not real (VERDICT r4
missing-item 1). This module is the REAL local materialisation: N
spawned OS-process workers, each loading the latest checkpoint and
serving the frozen ``/score/v1`` contract, all ``listen()``-ing on the
SAME port via ``SO_REUSEPORT`` — the Linux kernel load-balances incoming
connections across the live listeners, exactly as a k8s Service spreads
connections across pod endpoints. Killing one worker leaves the
remaining listeners taking all new connections (the kernel removes the
dead socket from the distribution set), and the supervisor respawns the
replica — the local analogue of a Deployment restarting a failed pod.

Placement note: multi-process replicas are the HOST-serving shape (CPU,
or one process per accelerator). TPU chips are single-process: replicas
that need their own chip are separate pods in the emitted k8s manifests
(``pipeline/k8s.py``), not forks of one chip.
"""
from __future__ import annotations

import multiprocessing
import os
import shutil
import signal
import socket
import sys
import tempfile
import threading
import time

from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.multiproc")


#: supervisor respawn policy: an instantly-crashing worker (bad
#: checkpoint, broken env) must not respawn in a hot loop forever —
#: each consecutive quick death doubles the backoff, and past the
#: budget the slot is parked with an error instead of burning CPU (the
#: k8s analogue: CrashLoopBackOff). A worker that stays alive
#: ``RESTART_RESET_AFTER_S`` clears its slot's streak.
RESTART_BUDGET = 5
RESTART_BACKOFF_BASE_S = 0.5
RESTART_BACKOFF_MAX_S = 30.0
RESTART_RESET_AFTER_S = 60.0


def _count_worker_restart(registry=None) -> None:
    from bodywork_tpu.obs import get_registry

    (registry or get_registry()).counter(
        "bodywork_tpu_serve_worker_restarts_total",
        "Serving replica processes respawned by the supervisor",
    ).inc()


def _count_dispatcher_restart(registry=None) -> None:
    from bodywork_tpu.obs import get_registry

    (registry or get_registry()).counter(
        "bodywork_tpu_serve_dispatcher_restarts_total",
        "Device-owning dispatcher processes respawned by the supervisor "
        "(disaggregated serving)",
    ).inc()


class RespawnPolicy:
    """Pure respawn decisions for ONE worker slot (unit-testable
    without spawning processes): consecutive quick deaths back off
    exponentially; past ``budget`` consecutive deaths the slot is
    exhausted and stays down."""

    def __init__(
        self,
        budget: int = RESTART_BUDGET,
        base_s: float = RESTART_BACKOFF_BASE_S,
        max_s: float = RESTART_BACKOFF_MAX_S,
        reset_after_s: float = RESTART_RESET_AFTER_S,
    ):
        self.budget = budget
        self.base_s = base_s
        self.max_s = max_s
        self.reset_after_s = reset_after_s
        self.consecutive = 0
        self.exhausted = False

    def on_death(self, alive_s: float) -> float | None:
        """Called when the slot's worker is found dead after living
        ``alive_s`` seconds. Returns the backoff delay to wait before
        respawning, or None when the budget is exhausted (the slot
        stays down)."""
        if alive_s >= self.reset_after_s:
            self.consecutive = 0  # it was healthy: a fresh incident
        self.consecutive += 1
        if self.consecutive > self.budget:
            self.exhausted = True
            return None
        return min(self.base_s * 2 ** (self.consecutive - 1), self.max_s)


def _reuseport_socket(host: str, port: int) -> socket.socket:
    """A TCP socket bound with ``SO_REUSEPORT`` (not yet listening)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, port))
    return sock


def _worker_main(store_path: str, host: str, port: int, engine: str,
                 watch_interval_s: float | None, buckets, ready,
                 batch_window_ms: float | None = None,
                 batch_max_rows: int | None = None,
                 metrics_dir: str | None = None,
                 server_engine: str = "thread",
                 max_pending: int | None = None,
                 retry_after_max_s: float | None = None,
                 shared_budget=None,
                 slot_index: int = 0,
                 dtype: str = "float32",
                 tuned_config: str | None = None):
    """One serving replica: load latest checkpoint -> predictor -> listen
    on the shared port. Runs in a SPAWNED process (a fork would inherit
    the parent's initialized XLA runtime threads — undefined behavior)."""
    from werkzeug.serving import make_server

    from bodywork_tpu.models.checkpoint import load_model, resolve_serving_key
    from bodywork_tpu.serve.app import create_app
    from bodywork_tpu.serve.server import (
        _registry_bounds,
        build_admission,
        build_serving_predictor,
    )
    from bodywork_tpu.store import open_scoped_store

    store = open_scoped_store(store_path)
    # tuned-config resolution per worker (each loads the store anyway):
    # fitted values fill the knobs the supervisor left unset, explicit
    # values win, malformed degrades (tune/config.py) — every replica
    # resolves the same document, so the fleet serves one knob set
    tuned_digest = None
    if tuned_config:
        from bodywork_tpu.tune.config import resolve_serving_knobs

        resolved = resolve_serving_knobs(
            store, tuned_config,
            batch_window_ms=batch_window_ms,
            batch_max_rows=batch_max_rows,
            buckets=tuple(buckets) if buckets else None,
            max_pending=max_pending,
        )
        batch_window_ms = resolved.batch_window_ms
        batch_max_rows = resolved.batch_max_rows
        buckets = resolved.buckets
        max_pending = resolved.max_pending
        tuned_digest = resolved.tuned_digest
    # registry-aware resolution: the production alias when one exists,
    # else the newest date-keyed checkpoint (models/checkpoint.py)
    served_key, served_source = resolve_serving_key(store)
    model, model_date = load_model(store, served_key)
    # dtype composes here exactly as in single-process serving: a
    # quantized dtype runs the shadow quality gate per worker (same
    # store, same window — same verdict on every replica)
    predictor, _served_dtype = build_serving_predictor(
        store, model, None, engine, buckets=buckets, dtype=dtype,
    )
    # ONE admission budget for the whole fleet when the supervisor hands
    # every worker a slot in the shared cross-process budget array
    # (max_pending is then service-wide; the supervisor zeroes a dead
    # worker's slot so crashes can't leak budget); without it each
    # replica sheds against its own kernel-balanced connection share.
    # Either way the aggregated queue-depth gauge (sum of per-worker
    # contributions) plus the shed counter give the service-wide
    # saturation picture.
    shared_slot = None
    if shared_budget is not None:
        from bodywork_tpu.serve.admission import SharedBudgetSlot

        shared_slot = SharedBudgetSlot(shared_budget, slot_index)
    admission = build_admission(server_engine, max_pending,
                                retry_after_max_s,
                                shared_slot=shared_slot)
    # one coalescer PER WORKER PROCESS: replicas never share a dispatcher
    # (they never share a predictor either), so each worker amortises its
    # own connection share across its own padded device calls
    app = create_app(model, model_date, predictor=predictor,
                     buckets=buckets,
                     batch_window_ms=batch_window_ms,
                     batch_max_rows=batch_max_rows,
                     metrics_dir=metrics_dir,
                     model_key=served_key, model_source=served_source,
                     admission=admission,
                     model_bounds=_registry_bounds(store, served_key))
    app.tuned_config_digest = tuned_digest
    flusher = None
    if metrics_dir is not None:
        # each replica flushes its registry snapshot to the shared dir;
        # whichever replica answers a /metrics scrape merges all of them
        # (obs.multiproc) — one coherent service-wide view on one port
        from bodywork_tpu.obs import get_registry
        from bodywork_tpu.obs.multiproc import MetricsFlusher

        flusher = MetricsFlusher(get_registry(), metrics_dir).start()

    sock = _reuseport_socket(host, port)
    aio_handle = None
    server = None
    if server_engine == "aio":
        # the asyncio front-end listens on the same SO_REUSEPORT socket:
        # the kernel balances connections across replicas regardless of
        # which front-end each one runs (asyncio's start_server calls
        # listen() on the bound socket itself)
        from bodywork_tpu.serve.aio import AioServiceHandle

        aio_handle = AioServiceHandle(app, host, port, sock=sock)
    else:
        sock.listen(128)
        server = make_server(host, port, app, threaded=True,
                             fd=sock.fileno())

    # the supervisor stops workers with terminate() (SIGTERM); without a
    # handler the default disposition kills the process mid-stack and the
    # finally below (watcher/flusher/coalescer teardown, the flusher's
    # final snapshot) never runs — convert to a clean unwind instead
    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(0))
    watcher = None
    if watch_interval_s:
        from bodywork_tpu.ops.slo import SloWatchdog, policy_from_env
        from bodywork_tpu.serve.reload import CheckpointWatcher

        # each replica polls independently, like each k8s pod would —
        # including its own SLO watchdog over the shared canary slot:
        # the first breach CAS wins and the other replicas' watchdogs
        # find the slot already cleared (clean PromotionConflict), so an
        # abort can never double-apply
        watcher = CheckpointWatcher(
            app, store, poll_interval_s=watch_interval_s,
            engine=engine, served_key=served_key, buckets=buckets,
            slo_watchdog=SloWatchdog(store, [app],
                                     policy=policy_from_env()),
            dtype=dtype,
        ).start()
    try:
        if aio_handle is not None:
            # start() returns once the loop is listening — only then is
            # the replica ready to take its share of connections
            aio_handle.start()
            ready.put(os.getpid())
            aio_handle.wait()
        else:
            ready.put(os.getpid())
            server.serve_forever()
    finally:  # pragma: no cover - only on signal teardown
        if watcher is not None:
            watcher.stop()
        if flusher is not None:
            flusher.stop()  # final snapshot flush
        if aio_handle is not None:
            aio_handle.stop()
        app.close()  # flush + stop the worker's coalescer


def _frontend_main(queue, host: str, port: int, ready,
                   server_engine: str = "thread",
                   metrics_dir: str | None = None,
                   shared_budget=None,
                   slot_index: int = 0,
                   max_pending: int | None = None,
                   retry_after_max_s: float | None = None,
                   transport: str = "shm",
                   dispatcher_addr=None):
    """One parse/admission front-end of the disaggregated split: HTTP
    parse + admission + row-queue handoff, NO model. Deliberately
    JAX-free (pinned by a test) — front-end processes must stay cheap to
    spawn and must not touch the accelerator runtime; everything
    device-shaped lives in the single dispatcher
    (``serve.dispatch.dispatcher_main``).

    ``transport`` selects the queue the handoff rides: ``"shm"`` is the
    shared-memory ``queue`` (same host as the dispatcher); ``"tcp"`` /
    ``"unix"`` connect a :class:`~bodywork_tpu.serve.netqueue.
    NetQueueClient` to ``dispatcher_addr`` instead (``queue`` is then
    ``None`` — there is no arena to share across hosts)."""
    from bodywork_tpu.serve.admission import SharedBudgetSlot, build_admission
    from bodywork_tpu.serve.frontend import FrontendApp

    signal.signal(signal.SIGTERM, lambda signum, frame: sys.exit(0))
    if transport in ("tcp", "unix"):
        from bodywork_tpu.serve.netqueue import NetQueueClient

        client = NetQueueClient(dispatcher_addr, slot_index).start()
    else:
        from bodywork_tpu.serve.rowqueue import RowQueueClient

        client = RowQueueClient(queue, slot_index).start()
    # same service-wide admission budget shape as --workers: each
    # front-end holds a slot in the shared array, so max_pending bounds
    # the SERVICE's held work and the supervisor can zero a dead
    # front-end's contribution
    shared_slot = None
    if shared_budget is not None:
        shared_slot = SharedBudgetSlot(shared_budget, slot_index)
    admission = build_admission(server_engine, max_pending,
                                retry_after_max_s,
                                shared_slot=shared_slot)
    app = FrontendApp(client, admission=admission, metrics_dir=metrics_dir)
    flusher = None
    if metrics_dir is not None:
        # front-ends flush their registries into the same dir as the
        # dispatcher: any front-end's /metrics scrape merges the whole
        # fleet, dispatcher-side coalescer occupancy included
        from bodywork_tpu.obs import get_registry
        from bodywork_tpu.obs.multiproc import MetricsFlusher

        flusher = MetricsFlusher(get_registry(), metrics_dir).start()
    sock = _reuseport_socket(host, port)
    aio_handle = None
    server = None
    if server_engine == "aio":
        from bodywork_tpu.serve.aio import AioServiceHandle

        aio_handle = AioServiceHandle(app, host, port, sock=sock)
    else:
        from werkzeug.serving import make_server

        sock.listen(128)
        server = make_server(host, port, app, threaded=True,
                             fd=sock.fileno())
    try:
        if aio_handle is not None:
            aio_handle.start()
            ready.put(os.getpid())
            aio_handle.wait()
        else:
            ready.put(os.getpid())
            server.serve_forever()
    finally:  # pragma: no cover - only on signal teardown
        if flusher is not None:
            flusher.stop()
        if aio_handle is not None:
            aio_handle.stop()
        client.stop()


class MultiProcessService:
    """N OS-process serving replicas sharing one ``SO_REUSEPORT`` port.

    ``port=0`` reserves a free port: the parent binds (without
    listening) to pick the number and HOLDS that socket for the service
    lifetime so the port cannot be reused by another process between
    worker restarts; bound-but-not-listening sockets take no traffic,
    so the kernel distributes connections only across the live workers.

    ``restart=True`` supervises: a worker that dies (crash, OOM-kill) is
    respawned, preserving the declared replica count — the local
    analogue of the reference's Deployment keeping ``replicas: 2`` pods
    alive.

    ``frontends=N`` selects the DISAGGREGATED topology instead (mutually
    exclusive with ``--workers``, enforced at the CLI): N model-free
    parse/admission front-ends (``_frontend_main``) on the shared port
    feed exactly ONE device-owning dispatcher
    (``serve.dispatch.dispatcher_main``) over a shared-memory row-queue.
    The same supervisor keeps both roles alive; a dying dispatcher
    flips the queue down (front-ends answer 503 + Retry-After, never
    wedge) and is respawned under the same backoff budget.

    ``transport`` (frontends mode only) moves the handoff off shared
    memory: ``"tcp"`` / ``"unix"`` run the same split over the socket
    row-queue (``serve.netqueue``) — locally that buys nothing over shm
    (it IS the bench-16 overhead comparison), but it is the exact
    topology the split k8s Deployments run across pods, with
    ``dispatcher_addr`` naming the dispatcher's listener (auto-picked on
    loopback / a temp unix path when unset). ``external_dispatcher=True``
    runs ONLY the front-end half against a dispatcher some other
    supervisor owns (the k8s front-end Deployment): no local dispatcher
    is spawned or supervised, and dispatcher death shows up as the
    clients' connection loss (503 + Retry-After, reconnect backoff) —
    the remote supervisor owns the respawn.

    ``standby=True`` (socket transports only) runs an ACTIVE/STANDBY
    dispatcher pair under this supervisor instead of a singleton: both
    candidates warm fully (model, predictor, AOT buckets), one wins the
    CAS lease (``serve.leadership``) and binds the listener; the other
    parks campaigning. A dead candidate's lease is CAS-expired at the
    supervisor's FIRST death observation (local fast failover — the
    k8s pair relies on TTL expiry instead), the standby takes over by
    bumping the fence, and the dead process respawns as a fresh
    candidate. ``frontends=0`` with ``standby=True`` is the
    ``cli serve --role dispatcher --standby`` pair: no local HTTP, two
    supervised candidates serving remote front-ends.
    """

    def __init__(
        self,
        store_path: str,
        host: str = "127.0.0.1",
        port: int = 0,
        workers: int = 2,
        engine: str = "xla",
        watch_interval_s: float | None = None,
        buckets: tuple[int, ...] | None = None,
        restart: bool = True,
        startup_timeout_s: float = 120.0,
        batch_window_ms: float | None = None,
        batch_max_rows: int | None = None,
        metrics: bool = False,
        server_engine: str = "thread",
        max_pending: int | None = None,
        retry_after_max_s: float | None = None,
        dtype: str = "float32",
        tuned_config: str | None = None,
        frontends: int | None = None,
        transport: str = "shm",
        dispatcher_addr: str | None = None,
        external_dispatcher: bool = False,
        standby: bool = False,
        leader_ttl_s: float | None = None,
    ):
        from bodywork_tpu.serve.netqueue import (
            SERVE_TRANSPORTS,
            parse_dispatcher_addr,
        )

        if transport not in SERVE_TRANSPORTS:
            raise ValueError(
                f"unknown row-queue transport {transport!r}; "
                f"expected one of {SERVE_TRANSPORTS}"
            )
        if transport != "shm" and frontends is None:
            raise ValueError(
                "socket row-queue transports require the disaggregated "
                "topology (--frontends N); --workers replicas have no "
                "row-queue to carry"
            )
        if external_dispatcher and transport == "shm":
            raise ValueError(
                "an external dispatcher cannot be reached over shared "
                "memory; use --transport tcp or unix"
            )
        if standby and transport == "shm":
            raise ValueError(
                "standby leadership needs a socket transport (tcp/unix): "
                "the shm queue is single-host, where the supervisor "
                "respawn is already the takeover path"
            )
        if standby and external_dispatcher:
            raise ValueError(
                "an external dispatcher is supervised elsewhere; its "
                "standby (if any) belongs to that supervisor"
            )
        if frontends is not None:
            assert frontends >= 0, "front-end count cannot be negative"
            if frontends == 0 and not standby:
                raise ValueError(
                    "a dispatcher-only service (--frontends 0) is the "
                    "standby pair topology; it needs --standby"
                )
            # role split: `workers` now counts HTTP processes, which in
            # this topology are the front-ends (the dispatcher is extra).
            # 0 is the `serve --role dispatcher --standby` pair: one
            # supervisor, two dispatcher candidates, no local HTTP.
            workers = frontends
        else:
            assert workers >= 1, "need at least one replica"
        from bodywork_tpu.serve.predictor import SERVE_DTYPES
        from bodywork_tpu.serve.server import SERVER_ENGINES

        if server_engine not in SERVER_ENGINES:
            raise ValueError(
                f"unknown server engine {server_engine!r}; "
                f"expected one of {SERVER_ENGINES}"
            )
        if dtype not in SERVE_DTYPES:
            raise ValueError(
                f"unknown serving dtype {dtype!r}; "
                f"expected one of {SERVE_DTYPES}"
            )
        self.store_path = str(store_path)
        self.host = host
        self.workers = workers
        self.engine = engine
        self.watch_interval_s = watch_interval_s
        self.buckets = tuple(buckets) if buckets else None
        # opt-in per-worker request coalescing (serve.batcher); respawned
        # replicas inherit the same policy
        self.batch_window_ms = batch_window_ms
        self.batch_max_rows = batch_max_rows
        # HTTP front-end + per-worker admission budget (serve.admission);
        # respawned replicas inherit the same policy
        self.server_engine = server_engine
        self.max_pending = max_pending
        self.retry_after_max_s = retry_after_max_s
        #: quantized serving dtype, per worker (each runs the shadow
        #: quality gate itself at boot/swap — same store, same verdict)
        self.dtype = dtype
        #: tuned-config reference (tune/config.py). A "latest" ref is
        #: pinned to its CONCRETE key HERE, once, so a replica
        #: respawned after `cli tune` writes a newer document cannot
        #: resolve a different knob set than its still-running siblings
        #: — the fleet serves one knob set for its whole lifetime
        #: (workers still load + validate the pinned document
        #: themselves, with the malformed-degrades contract).
        if tuned_config == "latest":
            from bodywork_tpu.store import open_scoped_store
            from bodywork_tpu.tune.config import _resolve_ref

            pinned = _resolve_ref(
                open_scoped_store(self.store_path), tuned_config
            )
            # no tuning/ artefacts yet: keep the symbolic ref so the
            # workers log the standard degrade warning themselves
            tuned_config = pinned if pinned is not None else tuned_config
        self.tuned_config = tuned_config
        self.frontends = frontends
        if frontends is not None and tuned_config and max_pending is None:
            # max_pending is the ONE tuned knob that is front-end-scoped
            # in the split (admission must stay upstream of the queue),
            # but front-ends are store-free — so the supervisor resolves
            # it here, once, and hands the concrete value down. The
            # dispatcher resolves the dispatcher-scoped knobs
            # (tune.config.DISPATCHER_SCOPED_KNOBS) itself.
            from bodywork_tpu.store import open_scoped_store
            from bodywork_tpu.tune.config import resolve_serving_knobs

            resolved = resolve_serving_knobs(
                open_scoped_store(self.store_path), tuned_config,
                batch_window_ms=None, batch_max_rows=None,
                buckets=None, max_pending=None,
            )
            max_pending = resolved.max_pending
            self.max_pending = max_pending
        # opt-in aggregated /metrics: a shared snapshot dir every worker
        # flushes into, so any replica can answer for the whole service.
        # Created lazily in start() so a failed startup never leaks it.
        # Always on in frontends mode: the dispatcher is not scrapeable
        # directly (it serves no HTTP), so its metrics — coalescer
        # occupancy, handoff latency, queue depth — are only visible at
        # all through the shared snapshot dir.
        self._metrics_enabled = metrics or frontends is not None
        self.metrics_dir: str | None = None
        self.restart = restart
        self.startup_timeout_s = startup_timeout_s
        self._ctx = multiprocessing.get_context("spawn")
        self._queue = None
        #: live dispatcher processes: [] (workers / external mode), one
        #: (the PR 16 singleton), or an active/standby PAIR (standby
        #: mode — which one leads is the lease's call, not an index's)
        self._dispatchers: list = []
        self.standby = standby
        self.leader_ttl_s = leader_ttl_s
        self._lease_reader = None
        self.transport = transport
        self.external_dispatcher = external_dispatcher
        self.dispatcher_addr = None
        self._unix_dir = None
        if frontends is not None and transport == "shm":
            from bodywork_tpu.serve.rowqueue import RowQueue

            self._queue = RowQueue(self._ctx, frontends)
        elif frontends is not None:
            # socket transports carry no shared arena: the handoff state
            # lives in the dispatcher's listener, which needs an address
            # both halves agree on before either spawns
            if dispatcher_addr is None:
                if external_dispatcher:
                    raise ValueError(
                        "an external dispatcher needs an explicit "
                        "--dispatcher-addr"
                    )
                if transport == "unix":
                    self._unix_dir = tempfile.mkdtemp(
                        prefix="bodywork-tpu-netqueue-"
                    )
                    dispatcher_addr = os.path.join(
                        self._unix_dir, "rowqueue.sock"
                    )
                else:
                    # loopback free port, reserved the same racy-but-
                    # fine way every local test harness picks ports
                    probe = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
                    probe.bind(("127.0.0.1", 0))
                    dispatcher_addr = f"127.0.0.1:{probe.getsockname()[1]}"
                    probe.close()
            self.dispatcher_addr = parse_dispatcher_addr(
                transport, dispatcher_addr
            )
        # ONE service-wide admission budget across the fleet: every
        # worker's controller admits against the sum of this per-slot
        # array, so max_pending bounds the SERVICE's held work (the "N
        # replicas as one benchmarkable unit" contract bench config 11
        # measures). Per-worker slots so the supervisor can zero a dead
        # replica's contribution (a crash must not leak budget). Created
        # whenever admission would be armed in the workers (explicit
        # budget, or the aio engine's default).
        self._shared_budget = (
            self._ctx.Array("i", workers)
            if workers > 1
            and (max_pending is not None or server_engine == "aio")
            else None
        )
        self._reserved = _reuseport_socket(host, port)
        self.port = self._reserved.getsockname()[1]
        self._procs: list = []
        self._flusher = None
        self._sup_registry = None
        self._stopping = threading.Event()
        self._supervisor = threading.Thread(
            target=self._supervise, name="replica-supervisor", daemon=True
        )

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/score/v1"

    @property
    def metrics_url(self) -> str | None:
        """The aggregated Prometheus endpoint (None when metrics are off)."""
        if self.metrics_dir is None:
            return None
        return f"http://{self.host}:{self.port}/metrics"

    @property
    def worker_pids(self) -> list[int]:
        return [p.pid for p in self._procs if p.is_alive()]

    def _lease(self):
        """A read/expire handle on the dispatcher-leader lease (standby
        mode), lazily opened — the supervisor thread resolves the active
        leader and fast-expires the lease of a dispatcher it watched
        die."""
        if self._lease_reader is None:
            from bodywork_tpu.serve.leadership import DispatcherLease
            from bodywork_tpu.store import open_scoped_store

            self._lease_reader = DispatcherLease(
                open_scoped_store(self.store_path),
                ttl_s=self.leader_ttl_s,
            )
        return self._lease_reader

    @property
    def dispatcher_pid(self) -> int | None:
        """PID of the ACTIVE device-owning dispatcher (frontends mode
        only). In standby mode the lease document says which candidate
        leads; a local alive pid matching its owner wins, else the
        first live candidate (e.g. mid-election)."""
        alive = [p.pid for p in self._dispatchers if p.is_alive()]
        if not alive:
            return None
        if self.standby and len(alive) > 1:
            try:
                doc = self._lease().peek()
            except Exception:
                doc = None
            owner = (doc or {}).get("owner") or ""
            parts = owner.rsplit(":", 2)
            if len(parts) == 3 and parts[0] == socket.gethostname():
                try:
                    pid = int(parts[1])
                except ValueError:
                    pid = None
                if pid in alive:
                    return pid
        return alive[0]

    def _spawn_dispatcher(self):
        from bodywork_tpu.serve.dispatch import dispatcher_main

        ready = self._ctx.Queue()
        proc = self._ctx.Process(
            target=dispatcher_main,
            args=(self.store_path, self._queue, ready),
            kwargs=dict(
                engine=self.engine,
                watch_interval_s=self.watch_interval_s,
                buckets=self.buckets,
                batch_window_ms=self.batch_window_ms,
                batch_max_rows=self.batch_max_rows,
                metrics_dir=self.metrics_dir,
                dtype=self.dtype,
                tuned_config=self.tuned_config,
                transport=self.transport,
                dispatcher_addr=self.dispatcher_addr,
                standby=self.standby,
                leader_ttl_s=self.leader_ttl_s,
            ),
            daemon=True,
        )
        proc.start()
        return proc, ready

    def _spawn_one(self, slot_index: int = 0):
        if self.frontends is not None:
            ready = self._ctx.Queue()
            proc = self._ctx.Process(
                target=_frontend_main,
                args=(self._queue, self.host, self.port, ready,
                      self.server_engine, self.metrics_dir,
                      self._shared_budget, slot_index,
                      self.max_pending, self.retry_after_max_s,
                      self.transport, self.dispatcher_addr),
                daemon=True,
            )
            proc.start()
            return proc, ready
        ready = self._ctx.Queue()
        proc = self._ctx.Process(
            target=_worker_main,
            args=(self.store_path, self.host, self.port, self.engine,
                  self.watch_interval_s, self.buckets, ready,
                  self.batch_window_ms, self.batch_max_rows,
                  self.metrics_dir, self.server_engine,
                  self.max_pending, self.retry_after_max_s,
                  self._shared_budget, slot_index, self.dtype,
                  self.tuned_config),
            daemon=True,
        )
        proc.start()
        return proc, ready

    def _wait_ready(self, ready, proc) -> None:
        deadline = time.monotonic() + self.startup_timeout_s
        while True:
            try:
                ready.get(timeout=1.0)
                return
            except Exception:
                if not proc.is_alive():
                    raise RuntimeError(
                        f"serving replica died during startup "
                        f"(exitcode={proc.exitcode})"
                    )
                if time.monotonic() > deadline:
                    proc.terminate()
                    raise TimeoutError(
                        f"serving replica not ready within "
                        f"{self.startup_timeout_s:.0f}s"
                    )

    def start(self) -> "MultiProcessService":
        if self._metrics_enabled and self.metrics_dir is None:
            self.metrics_dir = tempfile.mkdtemp(prefix="bodywork-tpu-obs-")
        spawned: list = []
        try:
            if self.frontends is not None and not self.external_dispatcher:
                # dispatcher first: its readiness IS model readiness —
                # once it arms `queue.up`, the (fast-booting, model-free)
                # front-ends answer /healthz 200 from their first request.
                # In standby mode TWO candidates spawn; each signals
                # ready once WARM (model loaded), before the election —
                # the loser parks campaigning, so both waits return.
                for _ in range(2 if self.standby else 1):
                    proc, dready = self._spawn_dispatcher()
                    self._dispatchers.append(proc)
                    self._wait_ready(dready, proc)
            for i in range(self.workers):
                spawned.append(self._spawn_one(i))
            for proc, ready in spawned:
                self._wait_ready(ready, proc)
        except BaseException:
            # a replica that died/timed out during startup propagates
            # without stop() ever running — don't leak the snapshot dir
            # (or the already-spawned siblings). Join before rmtree so a
            # terminating worker's final flush cannot race the removal.
            spawned.extend((p, None) for p in self._dispatchers)
            self._dispatchers = []
            for proc, _ready in spawned:
                if proc.is_alive():
                    proc.terminate()
            for proc, _ready in spawned:
                proc.join(timeout=10)
            if self.metrics_dir is not None:
                shutil.rmtree(self.metrics_dir, ignore_errors=True)
                self.metrics_dir = None
            raise
        self._procs = [p for p, _ in spawned]
        # respawn counters are incremented where the respawn happens —
        # the supervisor — so they need their own flusher to reach the
        # merged /metrics view the workers serve. A DEDICATED registry,
        # not the process-global one: in library use the supervisor runs
        # in the caller's process, and flushing the caller's registry
        # would leak every unrelated metric it holds into this service's
        # view.
        if self.metrics_dir is not None:
            from bodywork_tpu.obs import Registry
            from bodywork_tpu.obs.multiproc import MetricsFlusher

            self._sup_registry = Registry()
            self._flusher = MetricsFlusher(
                self._sup_registry, self.metrics_dir
            ).start()
        self._supervisor.start()
        role = "front-end" if self.frontends is not None else "replica"
        log.info(
            f"{self.workers} {role} process(es) listening on "
            f"{self.url} (SO_REUSEPORT, pids {self.worker_pids})"
            + (
                f"; dispatcher pid(s) "
                f"{[p.pid for p in self._dispatchers]}"
                + (" (active/standby pair)" if self.standby else "")
                if self._dispatchers else ""
            )
        )
        return self

    def _supervise(self) -> None:
        #: per-slot supervision state: respawn policy (budget/backoff),
        #: spawn time (feeds the streak reset), and the scheduled
        #: respawn instant while backing off
        slots = [
            {"policy": RespawnPolicy(), "spawned_at": time.monotonic(),
             "respawn_at": None}
            for _ in self._procs
        ]
        dslots = [
            {"policy": RespawnPolicy(), "spawned_at": time.monotonic(),
             "respawn_at": None}
            for _ in self._dispatchers
        ]
        while not self._stopping.wait(0.5):
            now = time.monotonic()
            for d, dslot in enumerate(dslots):
                self._supervise_dispatcher(d, dslot, now)
            for i, proc in enumerate(self._procs):
                if self._stopping.is_set():
                    break
                slot = slots[i]
                if proc.is_alive():
                    continue
                if slot["policy"].exhausted:
                    continue  # parked: budget burned, already reported
                if slot["respawn_at"] is None:
                    # FIRST observation of this death: reclaim whatever
                    # admission budget the worker still held, whether or
                    # not it will ever respawn (a parked or
                    # restart=False slot must not shrink the service
                    # budget forever) — its in-flight requests died with
                    # it either way
                    if self._shared_budget is not None:
                        from bodywork_tpu.serve.admission import (
                            SharedBudgetSlot,
                        )

                        SharedBudgetSlot.clear(self._shared_budget, i)
                    # frontends mode: the dead front-end's _pending map
                    # died with it, so the slots it held in the shared
                    # row-queue pool are unreachable to its successor —
                    # reclaim them here or every crash permanently
                    # shrinks the pool toward total 429 shedding
                    if self._queue is not None:
                        freed = self._queue.reclaim_frontend(i)
                        if freed:
                            log.warning(
                                f"reclaimed {freed} row-queue slot(s) "
                                f"from dead front-end {i}"
                            )
                    alive_s = now - slot["spawned_at"]
                    delay = slot["policy"].on_death(alive_s)
                    if delay is None:
                        log.error(
                            f"replica slot {i} (pid {proc.pid}) died "
                            f"{slot['policy'].consecutive} consecutive "
                            f"time(s) within {RESTART_RESET_AFTER_S:.0f}s "
                            f"of spawn; restart budget "
                            f"({slot['policy'].budget}) exhausted — "
                            "leaving the slot down"
                        )
                        continue
                    log.warning(
                        f"replica pid {proc.pid} died "
                        f"(exitcode={proc.exitcode}, alive {alive_s:.1f}s)"
                        + (
                            f"; respawning in {delay:.1f}s "
                            f"(streak {slot['policy'].consecutive})"
                            if self.restart else ""
                        )
                    )
                    if not self.restart:
                        slot["policy"].exhausted = True  # report once
                        continue
                    slot["respawn_at"] = now + delay
                    continue
                if now < slot["respawn_at"]:
                    continue  # still backing off
                slot["respawn_at"] = None
                new_proc, ready = self._spawn_one(i)
                _count_worker_restart(self._sup_registry)
                try:
                    self._wait_ready(ready, new_proc)
                except Exception as exc:  # keep supervising the rest:
                    # the failed respawn counts against the slot's
                    # budget on the next tick. spawned_at must be NOW —
                    # after the (possibly long) readiness wait — or a
                    # worker that hangs at startup for longer than
                    # reset_after_s would launder its streak into a
                    # "healthy" reset and respawn forever
                    log.error(f"replica respawn failed: {exc!r}")
                    self._procs[i] = new_proc  # dead; next tick backs off
                    slot["spawned_at"] = time.monotonic()
                    continue
                self._procs[i] = new_proc
                slot["spawned_at"] = time.monotonic()
                log.info(f"replica respawned as pid {new_proc.pid}")

    def _supervise_dispatcher(self, d: int, slot, now: float) -> None:
        """One supervision tick for dispatcher slot ``d`` (frontends
        mode). Same budget/backoff as a replica slot, plus the liveness
        contract the front-ends depend on: the FIRST observation of a
        death downs the queue and bumps its epoch, failing every
        in-flight front-end wait into 503 + Retry-After immediately —
        waiters must not ride out the whole backoff window. In standby
        mode the first observation also CAS-expires the dead leader's
        lease, so the warm standby takes over on its next poll instead
        of waiting out the TTL."""
        proc = self._dispatchers[d]
        if proc.is_alive() or slot["policy"].exhausted:
            return
        if slot["respawn_at"] is None:
            if self._queue is not None:
                self._queue.up.value = 0
                self._queue.epoch.value += 1
            # (socket transports need no supervisor-side down-flip: the
            # dying dispatcher's connections break, and the clients HOLD
            # their in-flight waits for failover resubmission)
            if self.standby:
                # reclaim the dead candidate's leadership slot at the
                # first death observation: safe — this is evidence of a
                # dead process on THIS host, never a partition guess. A
                # dead STANDBY simply does not own the lease (no-op).
                try:
                    self._lease().expire_dead_owner(
                        socket.gethostname(), proc.pid
                    )
                except Exception as exc:
                    log.warning(
                        f"could not fast-expire the dead dispatcher's "
                        f"lease (TTL expiry will cover it): {exc!r}"
                    )
            alive_s = now - slot["spawned_at"]
            delay = slot["policy"].on_death(alive_s)
            if delay is None:
                log.error(
                    f"dispatcher (pid {proc.pid}) died "
                    f"{slot['policy'].consecutive} consecutive time(s); "
                    f"restart budget ({slot['policy'].budget}) exhausted "
                    "— front-ends will answer 503 until restarted"
                )
                return
            log.warning(
                f"dispatcher pid {proc.pid} died "
                f"(exitcode={proc.exitcode}, alive {alive_s:.1f}s)"
                + (
                    f"; respawning in {delay:.1f}s "
                    f"(streak {slot['policy'].consecutive})"
                    if self.restart else ""
                )
            )
            if not self.restart:
                slot["policy"].exhausted = True
                return
            slot["respawn_at"] = now + delay
            return
        if now < slot["respawn_at"]:
            return
        slot["respawn_at"] = None
        new_proc, ready = self._spawn_dispatcher()
        _count_dispatcher_restart(self._sup_registry)
        try:
            # the respawned dispatcher re-arms `queue.up` itself, only
            # after its model is loaded — serving resumes atomically.
            # (Standby mode: the respawn is a fresh WARM candidate; it
            # signals ready at warm and parks campaigning.)
            self._wait_ready(ready, new_proc)
        except Exception as exc:
            log.error(f"dispatcher respawn failed: {exc!r}")
            self._dispatchers[d] = new_proc  # dead; next tick backs off
            slot["spawned_at"] = time.monotonic()
            return
        self._dispatchers[d] = new_proc
        slot["spawned_at"] = time.monotonic()
        log.info(f"dispatcher respawned as pid {new_proc.pid}")

    def kill_worker(self, pid: int) -> None:
        """SIGKILL one replica (fault-injection hook for tests/drills)."""
        os.kill(pid, signal.SIGKILL)

    def kill_dispatcher(self) -> None:
        """SIGKILL the dispatcher (chaos hook: the disaggregated fleet's
        worst-case single fault)."""
        pid = self.dispatcher_pid
        if pid is None:
            raise RuntimeError("no live dispatcher to kill")
        os.kill(pid, signal.SIGKILL)

    def wait(self) -> None:
        """Block until :meth:`stop` is called from another thread or the
        process is signalled — the pod-entrypoint serve loop."""
        self._stopping.wait()

    def stop(self) -> None:
        self._stopping.set()
        procs = list(self._procs) + list(self._dispatchers)
        for proc in procs:
            if proc.is_alive():
                proc.terminate()
        for proc in procs:
            proc.join(timeout=10)
        if self._supervisor.ident is not None:
            self._supervisor.join(timeout=5)
        if self._flusher is not None:
            self._flusher.stop()
            self._flusher = None
        if self._queue is not None:
            self._queue.close()
        self._reserved.close()
        if self._unix_dir is not None:
            shutil.rmtree(self._unix_dir, ignore_errors=True)
        if self.metrics_dir is not None:
            shutil.rmtree(self.metrics_dir, ignore_errors=True)
        log.info("multi-process scoring service stopped")

    def __enter__(self) -> "MultiProcessService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
