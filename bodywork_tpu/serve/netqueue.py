"""Socket transport for the front-end -> dispatcher row queue.

The shared-memory row queue (``serve.rowqueue``) chains every front-end
to the dispatcher's host: slots, rings, and liveness words all live in
one ``multiprocessing`` arena. This module is the same producer/consumer
contract over a byte stream — TCP or a Unix domain socket — so the
jax-free front-ends can run on OTHER hosts/pods than the device-owning
dispatcher (ROADMAP item 1b; the k8s split in ``pipeline/k8s.py`` runs
each role as its own Deployment).

Wire protocol (all little-endian, one persistent connection per
front-end process):

- every frame is ``u32 length | u8 type | payload`` (length covers type
  + payload);
- ``HELLO`` (server -> client, once per connection) carries the
  ``serve.wire`` schema version, the per-connection credit window, and
  the binary content type string — a client from a different build
  refuses the connection instead of misparsing rows;
- ``SUBMIT`` (client -> server) is ``u64 request id | u8 kind |
  u16 trace-id length | trace id | rows`` where ``rows`` is EXACTLY the
  ``application/x-bodywork-rows`` framing (``wire.encode_binary_rows``:
  ``u32 n_rows, u32 n_features`` + f32 row data) — the request framing
  that already crosses HTTP is the one that crosses the queue;
- ``REPLY`` (server -> client) is ``u64 request id | u16 status |
  u32 n | n f32 predictions | u32 length | bundle-identity JSON`` (the
  same ``[model_key, model_info, model_date]`` triple the shm reply
  region carries, so the front-end splices byte-identical responses).

Frames pipeline: the client keeps submitting while replies are in
flight, and the reader thread demuxes replies by request id — one
connection, no per-request round-trip serialization.

**Credits are the slot budget.** The HELLO window mirrors the shm
transport's slot pool: a submit past the window raises
:class:`~bodywork_tpu.serve.rowqueue.SlotsExhausted` synchronously,
exactly as an empty slot free-list does, so admission/shed semantics
(shed-before-parse upstream, 429 + Retry-After here) are byte-identical
across transports. Credits also make "slow dispatcher" and "dead
network" distinguishable: a slow dispatcher consumes the window (credits
pinned at 0, connection healthy — scale the dispatcher); a partition or
death breaks the connection (credits irrelevant, ``connected`` false —
reconnect/respawn), see docs/RESILIENCE.md §14.

**Failure semantics extend the shm transport's** (PR 16) with safe
in-flight RESUBMISSION (ISSUE 19): a broken dispatcher connection no
longer fails in-flight waits immediately — the client HOLDS each
pending request's encoded SUBMIT frame, reconnects with the shared
full-jitter backoff (``utils.retry.full_jitter_delay``), and resends
the held frames verbatim over the new connection. Scoring is a pure
function of the rows, so duplicate dispatch is safe: if the old
dispatcher also replied, the late reply demuxes to an already-popped
request id and is inert; the response the waiter sees is byte-identical
either way. Only past ``failover_deadline_s`` of continuous disconnect
do the waits fail into
:class:`~bodywork_tpu.serve.rowqueue.DispatcherUnavailable` (503 +
Retry-After at the HTTP layer) — a dispatcher FAILOVER (warm standby
takes over within the lease TTL, ``serve.leadership``) heals under the
deadline and the client never sheds at all. NEW submissions while
disconnected still shed synchronously, as before.

**The leadership fence rides the HELLO** (``u64`` after the credit
window): clients track the highest fence ever seen and refuse — at the
handshake, before any row could be misparsed — a dispatcher offering a
LOWER fence: that is a zombie ex-leader that has not yet noticed its
lost lease. A fence of 0 means no election is running (the PR 16/18
topologies), and the check never fires.

A dropped front-end connection still reclaims its in-flight budget
server-side (the socket analogue of the dead-front-end slot reclaim):
queued submissions from the dead connection are skipped at poll, and
replies to it are dropped instead of erroring the dispatcher. Resubmits
stay within the credit window by construction (the client never held
more than the window), provided the standby serves the same window —
both sides default to ``DEFAULT_SLOTS``.

Dependency note: this module is deliberately jax-free (numpy + stdlib
sockets) — it rides the front-end processes, which must never pay the
accelerator import.
"""
from __future__ import annotations

import json
import os
import queue as queue_mod
import socket
import struct
import threading
import time

import numpy as np

from bodywork_tpu.serve.rowqueue import (
    DEFAULT_SLOTS,
    KIND_SINGLE,
    DispatcherUnavailable,
    SlotsExhausted,
    _Reply,
)
from bodywork_tpu.serve.wire import (
    BINARY_CONTENT_TYPE,
    WIRE_SCHEMA_VERSION,
    encode_binary_rows,
    parse_binary_rows,
)
from bodywork_tpu.utils.logging import get_logger
from bodywork_tpu.utils.retry import full_jitter_delay

log = get_logger("serve.netqueue")

__all__ = [
    "DEFAULT_DISPATCHER_PORT",
    "DEFAULT_FAILOVER_DEADLINE_S",
    "SERVE_ROLES",
    "SERVE_TRANSPORTS",
    "NetQueueClient",
    "NetQueueServer",
    "parse_dispatcher_addr",
]

#: the row-queue transports `cli serve --transport` selects. "shm" is
#: the PR 16 shared-memory queue (one host); "tcp"/"unix" are this
#: module. Pinned == the cli choices == the stages env-knob parser by a
#: guard test (tests/test_netqueue.py).
SERVE_TRANSPORTS = ("shm", "tcp", "unix")

#: the serve roles of the cross-host split: "auto" runs both halves
#: locally (the PR 16 topology, any transport), "frontend"/"dispatcher"
#: run ONE half against a remote peer — what the split k8s Deployments
#: set (pipeline/k8s.py). Pinned like SERVE_TRANSPORTS.
SERVE_ROLES = ("auto", "frontend", "dispatcher")

#: the dispatcher Service port the k8s split wires front-ends at
DEFAULT_DISPATCHER_PORT = 9091

#: reconnect backoff (client side): exponential with full jitter —
#: drawn through utils.retry.full_jitter_delay, the ONE backoff policy
#: every transport/store loop shares (guard: tests/test_chaos.py) — so
#: N front-ends orphaned by one dispatcher death do not reconnect in
#: lockstep (the reconnect-storm runbook, docs/RESILIENCE.md §14)
RECONNECT_BASE_S = 0.2
RECONNECT_MAX_S = 5.0

#: how long a disconnected client HOLDS in-flight requests for
#: resubmission before failing them into 503s: sized above the default
#: leadership TTL + one maximal reconnect backoff, so a warm-standby
#: failover completes under it, and WELL below the front-end's 60 s
#: rendezvous timeout, so nothing ever wedges
DEFAULT_FAILOVER_DEADLINE_S = 15.0

_FRAME_HEADER = struct.Struct("<IB")   # length, msg type
#: wire schema version, credits, leadership fence (0 = no election)
_HELLO_BODY = struct.Struct("<HIQ")
_SUBMIT_HEADER = struct.Struct("<QBH")  # req id, kind, trace length
_REPLY_HEADER = struct.Struct("<QHI")  # req id, status, n predictions

_MSG_HELLO = 1
_MSG_SUBMIT = 2
_MSG_REPLY = 3

#: a frame larger than this is a protocol violation, not a big request
#: (the slot-stride bound already caps legitimate rows far below it)
_MAX_FRAME = 64 * 1024 * 1024


def parse_dispatcher_addr(transport: str, addr: str | None):
    """Normalise a ``--dispatcher-addr`` value for ``transport``:
    ``("tcp", host, port)`` or ``("unix", path)``. tcp wants
    ``host:port`` (bare ``:port`` binds/targets localhost); unix wants a
    filesystem path. Raises ``ValueError`` on a malformed value — the
    CLI surfaces it; the stage env parser degrades instead."""
    if transport not in ("tcp", "unix"):
        raise ValueError(
            f"no dispatcher address for transport {transport!r}"
        )
    if not addr:
        raise ValueError(
            f"transport {transport!r} needs a dispatcher address"
        )
    if transport == "unix":
        return ("unix", addr)
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise ValueError(
            f"tcp dispatcher address must be host:port, got {addr!r}"
        )
    return ("tcp", host or "127.0.0.1", int(port))


def _connect(address, timeout_s: float):
    if address[0] == "unix":
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout_s)
        sock.connect(address[1])
    else:
        sock = socket.create_connection(
            (address[1], address[2]), timeout=timeout_s
        )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    return sock


def _recv_exact(sock, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError on EOF."""
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed the row-queue connection")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _recv_frame(sock) -> tuple[int, bytes]:
    length, msg_type = _FRAME_HEADER.unpack(
        _recv_exact(sock, _FRAME_HEADER.size)
    )
    if not 1 <= length <= _MAX_FRAME:
        raise ConnectionError(f"bad frame length {length}")
    return msg_type, _recv_exact(sock, length - 1)


def _frame(msg_type: int, payload: bytes) -> bytes:
    return _FRAME_HEADER.pack(len(payload) + 1, msg_type) + payload


def _shutdown_close(sock) -> None:
    """``shutdown()`` then ``close()``. Plain ``close()`` on a socket
    another thread is blocked ``recv()``-ing (or ``accept()``-ing) does
    NOT wake that thread on Linux — the kernel holds the socket open
    under the in-flight syscall, no FIN reaches the peer, and both ends
    hang forever. ``shutdown(SHUT_RDWR)`` tears the connection down
    immediately: the blocked reader returns EOF and the peer sees the
    close."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass  # already disconnected / never connected
    try:
        sock.close()
    except OSError:
        pass


class _PendingEntry:
    """One in-flight request: its completion callback, submit clock,
    and — for failover resubmission — the encoded SUBMIT frame (resent
    VERBATIM over a re-established connection, so the standby scores
    the exact bytes the dead leader held) and its row count."""

    __slots__ = ("on_done", "submitted_at", "frame", "n_rows")

    def __init__(self, on_done, submitted_at, frame, n_rows):
        self.on_done = on_done
        self.submitted_at = submitted_at
        self.frame = frame
        self.n_rows = n_rows


class NetQueueClient:
    """The front-end side of the socket row queue — the same surface as
    :class:`~bodywork_tpu.serve.rowqueue.RowQueueClient` (``submit`` /
    ``start`` / ``stop`` / ``stats`` / ``dispatcher_up``), so
    ``frontend.py`` and ``serve.aio`` run unchanged over either
    transport. One persistent connection, a reader thread demuxing
    replies by request id, a jittered-backoff reconnect loop, and
    failover resubmission of held in-flight frames (module docstring)."""

    def __init__(self, address, frontend_id: int = 0,
                 connect_timeout_s: float = 5.0,
                 reconnect_base_s: float = RECONNECT_BASE_S,
                 reconnect_max_s: float = RECONNECT_MAX_S,
                 failover_deadline_s: float = DEFAULT_FAILOVER_DEADLINE_S):
        self.address = address
        self.frontend_id = frontend_id
        self.connect_timeout_s = connect_timeout_s
        self.reconnect_base_s = reconnect_base_s
        self.reconnect_max_s = reconnect_max_s
        self.failover_deadline_s = failover_deadline_s
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._sock: socket.socket | None = None
        self._connected = False
        self._stopped = False
        self._next_id = 0
        #: req_id -> _PendingEntry (held across disconnects until the
        #: failover deadline — the resubmission set)
        self._pending: dict[int, _PendingEntry] = {}
        #: monotonic instant the connection carrying in-flight requests
        #: broke; None while connected (or nothing is held)
        self._disconnected_at: float | None = None
        #: per-connection credit window granted by the server's HELLO;
        #: 0 until connected (every submit then sheds as unavailable)
        self.credit_window = 0
        self.reconnects = 0
        #: highest leadership fence any HELLO carried; a dispatcher
        #: offering less is a zombie ex-leader, refused at handshake
        self.fence_seen = 0
        #: fence INCREASES observed (each one is a completed failover)
        self.takeovers_observed = 0
        self._leader_since: float | None = None
        # same accounting surface as RowQueueClient (healthz reads it)
        self.rows_submitted = 0
        self.requests_submitted = 0
        self.replies_received = 0
        self.failures = 0
        from bodywork_tpu.obs import get_registry

        reg = get_registry()
        self._m_rows = reg.counter(
            "bodywork_tpu_rowqueue_rows_total",
            "Feature rows handed to the dispatcher over the shared "
            "row-queue, by front-end role",
        )
        self._m_wait = reg.histogram(
            "bodywork_tpu_rowqueue_wait_seconds",
            "Front-end submit -> dispatcher reply, whole round trip",
        )
        self._m_reconnects = reg.counter(
            "bodywork_tpu_netqueue_reconnects_total",
            "Socket row-queue connections re-established after a "
            "dispatcher death or network failure",
        )
        self._m_rtt = reg.histogram(
            "bodywork_tpu_netqueue_rtt_seconds",
            "Submit -> reply round trip over the SOCKET row-queue "
            "transport (the cross-host analogue of the shm handoff "
            "histogram; includes dispatcher service time)",
            buckets=(0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1, 0.5, 2.0),
        )
        self._m_resubmitted = reg.counter(
            "bodywork_tpu_netqueue_resubmitted_rows_total",
            "In-flight feature rows resent verbatim over a "
            "re-established row-queue connection after a dispatcher "
            "failover (scoring is pure, so duplicate dispatch is safe "
            "and replies stay byte-identical)",
        )
        self._m_credits = reg.gauge(
            "bodywork_tpu_netqueue_credits_in_flight",
            "Transport credits consumed (submitted, not yet replied) on "
            "the socket row-queue connection; pinned at the window with "
            "a healthy connection = slow dispatcher, not a partition",
        )
        # the occupancy signal the HPA runbook keys on, exported from
        # the FRONT-END side here: in the cross-host split the
        # dispatcher's own gauge is scraped from another pod, and
        # credits-consumed / window IS this transport's slot occupancy
        self._m_occupancy = reg.gauge(
            "bodywork_tpu_rowqueue_occupancy_ratio",
            "Allocated row slots / slot pool size (1.0 = the queue, not "
            "admission, is the backpressure boundary)",
        )
        self._manager = threading.Thread(
            target=self._connection_loop,
            name=f"netqueue-client-{frontend_id}", daemon=True,
        )

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "NetQueueClient":
        self._manager.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        self._teardown_socket()
        self._fail_pending(DispatcherUnavailable("front-end shutting down"))
        if self._manager.ident is not None:
            self._manager.join(timeout=5)

    def dispatcher_up(self) -> bool:
        return self._connected

    # -- submit path ---------------------------------------------------------
    def submit(self, X, kind: int, on_done,
               trace_id: str | None = None) -> None:
        """Same contract as ``RowQueueClient.submit``: raises
        :class:`DispatcherUnavailable` / :class:`SlotsExhausted`
        synchronously when nothing was sent; otherwise ``on_done`` fires
        on the reader thread with a reply object or an exception."""
        if self._stopped or not self._connected:
            raise DispatcherUnavailable("scoring dispatcher is not available")
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 0:
            X = X[None]
        rows = encode_binary_rows(X)
        n_rows = int(X.shape[0])
        trace = (trace_id or "").encode("ascii", "replace")[:255]
        with self._lock:
            if len(self._pending) >= self.credit_window:
                # the socket analogue of an empty slot free-list: the
                # window mirrors the shm slot budget, so shedding kicks
                # in at the same boundary on either transport
                raise SlotsExhausted("no free row-queue transport credit")
            req_id = self._next_id
            self._next_id += 1
            payload = (
                _SUBMIT_HEADER.pack(req_id, kind, len(trace)) + trace + rows
            )
            frame = _frame(_MSG_SUBMIT, payload)
            self._pending[req_id] = _PendingEntry(
                on_done, time.monotonic(), frame, n_rows
            )
            self.requests_submitted += 1
            self.rows_submitted += n_rows
            self._m_credits.set(float(len(self._pending)))
            if self.credit_window:
                self._m_occupancy.set(
                    len(self._pending) / self.credit_window
                )
        try:
            with self._wlock:
                sock = self._sock
                if sock is None:
                    raise ConnectionError("not connected")
                sock.sendall(frame)
        except (OSError, ConnectionError) as exc:
            # nothing (whole) reached the dispatcher: unwind the credit
            # and raise synchronously, exactly as a failed enqueue would
            with self._lock:
                if self._pending.pop(req_id, None) is not None:
                    self.requests_submitted -= 1
                    self.rows_submitted -= n_rows
                self._m_credits.set(float(len(self._pending)))
            self._teardown_socket()
            raise DispatcherUnavailable(
                f"scoring dispatcher connection lost: {exc}"
            ) from exc
        self._m_rows.inc(n_rows)

    # -- connection manager / reader -----------------------------------------
    def _connection_loop(self) -> None:
        streak = 0
        first = True
        while not self._stopped:
            self._expire_held()
            try:
                sock = _connect(self.address, self.connect_timeout_s)
            except OSError:
                streak += 1
                self._backoff(streak)
                continue
            try:
                self._handshake(sock)
            except (OSError, ConnectionError, ValueError) as exc:
                log.warning(f"netqueue handshake failed: {exc}")
                sock.close()
                streak += 1
                self._backoff(streak)
                continue
            if not first:
                self.reconnects += 1
                self._m_reconnects.inc()
                log.info(
                    f"netqueue reconnected to the dispatcher "
                    f"(reconnect {self.reconnects})"
                )
            first = False
            streak = 0
            try:
                # resend the held in-flight frames BEFORE the submit
                # path can see the connection: the new dispatcher scores
                # the exact bytes the dead one held (pure function -> a
                # duplicate reply racing in is popped-empty and inert)
                self._resubmit_held(sock)
            except (OSError, ConnectionError) as exc:
                if not self._stopped:
                    log.warning(f"netqueue resubmission failed: {exc}")
                _shutdown_close(sock)
                streak += 1
                self._backoff(streak)
                continue
            self._sock = sock
            self._connected = True
            try:
                self._read_replies(sock)
            except (OSError, ConnectionError) as exc:
                if not self._stopped:
                    log.warning(f"netqueue connection lost: {exc}")
            finally:
                self._teardown_socket()
                # in-flight waits are NOT failed here (the pre-ISSUE-19
                # contract): they are HELD for resubmission — a standby
                # takeover heals them under the failover deadline, and
                # only _expire_held turns them into 503s
                with self._lock:
                    if self._pending and self._disconnected_at is None:
                        self._disconnected_at = time.monotonic()
            streak += 1
            self._backoff(streak)

    def _backoff(self, streak: int) -> None:
        if self._stopped:
            return
        # full jitter via the ONE shared policy (utils.retry): N
        # orphaned front-ends spread over [0, cap] rather than
        # stampeding the respawned/elected dispatcher in lockstep
        time.sleep(full_jitter_delay(
            max(0, streak - 1), self.reconnect_base_s, self.reconnect_max_s
        ))

    def _expire_held(self) -> None:
        """Fail the held in-flight requests once a disconnect has
        outlived the failover deadline — the ONLY place (besides stop)
        that turns a disconnect into DispatcherUnavailable waits."""
        with self._lock:
            expired = (
                self._disconnected_at is not None
                and time.monotonic() - self._disconnected_at
                >= self.failover_deadline_s
            )
            if expired:
                self._disconnected_at = None
        if expired:
            self._fail_pending(DispatcherUnavailable(
                f"scoring dispatcher did not fail over within "
                f"{self.failover_deadline_s:.1f}s"
            ))

    def _resubmit_held(self, sock) -> None:
        """Resend every held frame, in submit order, over the fresh
        connection. Raises the connection errors to the caller, which
        treats them exactly like a lost connection."""
        with self._lock:
            entries = [e for _id, e in sorted(self._pending.items())]
            self._disconnected_at = None
        if not entries:
            return
        rows = 0
        for entry in entries:
            sock.sendall(entry.frame)
            rows += entry.n_rows
        self._m_resubmitted.inc(rows)
        log.info(
            f"resubmitted {len(entries)} in-flight request(s) "
            f"({rows} rows) over the re-established connection"
        )

    def _handshake(self, sock) -> None:
        msg_type, body = _recv_frame(sock)
        if msg_type != _MSG_HELLO:
            raise ValueError(f"expected HELLO, got frame type {msg_type}")
        if len(body) < _HELLO_BODY.size:
            raise ValueError(f"short HELLO body ({len(body)} bytes)")
        version, credits, fence = _HELLO_BODY.unpack_from(body)
        content_type = body[_HELLO_BODY.size:].decode("ascii")
        if version != WIRE_SCHEMA_VERSION or (
            content_type != BINARY_CONTENT_TYPE
        ):
            # a peer from another build: refuse rather than misparse
            raise ValueError(
                f"wire schema mismatch: dispatcher speaks v{version} "
                f"({content_type!r}), this build v{WIRE_SCHEMA_VERSION} "
                f"({BINARY_CONTENT_TYPE!r})"
            )
        if fence < self.fence_seen:
            # a zombie ex-leader still listening after losing its
            # lease: refuse at the handshake, never misparse mid-stream
            raise ValueError(
                f"stale dispatcher fence {fence} < {self.fence_seen} "
                "already seen (zombie ex-leader refused)"
            )
        if fence > self.fence_seen:
            if self.fence_seen:
                # a fence INCREASE is a completed failover we lived
                # through (the first fence is just discovery)
                self.takeovers_observed += 1
            self.fence_seen = int(fence)
            self._leader_since = time.monotonic()
        elif self._leader_since is None:
            self._leader_since = time.monotonic()
        self.credit_window = int(credits)

    def _read_replies(self, sock) -> None:
        while not self._stopped:
            msg_type, body = _recv_frame(sock)
            if msg_type != _MSG_REPLY:
                raise ConnectionError(f"unexpected frame type {msg_type}")
            req_id, status, n = _REPLY_HEADER.unpack_from(body)
            offset = _REPLY_HEADER.size
            predictions = np.frombuffer(
                body, dtype="<f4", count=n, offset=offset
            ).astype(np.float32, copy=True)
            offset += n * 4
            (blob_len,) = struct.unpack_from("<I", body, offset)
            blob = body[offset + 4:offset + 4 + blob_len]
            try:
                model_key, model_info, model_date = json.loads(
                    blob or b"[null, null, null]"
                )
            except (ValueError, TypeError):
                model_key = model_info = model_date = None
            with self._lock:
                entry = self._pending.pop(req_id, None)
                self.replies_received += 1 if entry is not None else 0
                self._m_credits.set(float(len(self._pending)))
                if self.credit_window:
                    self._m_occupancy.set(
                        len(self._pending) / self.credit_window
                    )
            if entry is None:
                continue  # duplicate/late reply after a failover: inert
            rtt = time.monotonic() - entry.submitted_at
            self._m_wait.observe(rtt)
            self._m_rtt.observe(rtt)
            self._complete(
                entry.on_done,
                _Reply(status, predictions, model_key, model_info,
                       model_date),
            )

    def _teardown_socket(self) -> None:
        self._connected = False
        with self._wlock:
            sock, self._sock = self._sock, None
        if sock is not None:
            _shutdown_close(sock)

    def _fail_pending(self, exc: Exception) -> None:
        with self._lock:
            failed = list(self._pending.values())
            self._pending.clear()
            self.failures += len(failed)
            self._m_credits.set(0.0)
            self._m_occupancy.set(0.0)
        for entry in failed:
            self._complete(entry.on_done, exc)

    @staticmethod
    def _complete(on_done, outcome) -> None:
        try:
            on_done(outcome)
        except Exception as exc:  # a broken callback must not kill the reader
            log.error(f"netqueue on_done callback failed: {exc!r}")

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "dispatcher_up": self.dispatcher_up(),
                "requests_submitted": self.requests_submitted,
                "rows_submitted": self.rows_submitted,
                "replies_received": self.replies_received,
                "failures": self.failures,
                "in_flight": len(self._pending),
                "slots": self.credit_window,
                "slots_free": max(
                    0, self.credit_window - len(self._pending)
                ),
            }

    def transport_state(self) -> dict:
        """The /healthz transport block (frontend.healthz_payload)."""
        with self._lock:
            in_flight = len(self._pending)
        return {
            "kind": self.address[0],
            "connected": self._connected,
            "reconnects": self.reconnects,
            "credit_window": self.credit_window,
            "credits_in_flight": in_flight,
            "address": (
                self.address[1] if self.address[0] == "unix"
                else f"{self.address[1]}:{self.address[2]}"
            ),
            # the ISSUE 19 /healthz leadership section, from the
            # CLIENT's vantage point: what fence it is pinned to and
            # how many completed failovers it has lived through
            "leadership": {
                "role": "active" if self._connected else "unknown",
                "fence": self.fence_seen,
                "lease_age_s": (
                    round(time.monotonic() - self._leader_since, 3)
                    if self._leader_since is not None else None
                ),
                "takeovers_observed": self.takeovers_observed,
            },
        }


class _NetSubmission:
    """One dequeued request, dispatcher-side — duck-typed to
    ``rowqueue._Submission`` (``kind`` / ``X`` / ``frontend_id`` /
    ``trace_id``), plus the owning connection the reply routes back
    over."""

    __slots__ = ("conn", "req_id", "kind", "X", "trace_id", "frontend_id",
                 "received_at")

    def __init__(self, conn, req_id, kind, X, trace_id, received_at):
        self.conn = conn
        self.req_id = req_id
        self.kind = kind
        self.X = X
        self.trace_id = trace_id
        self.frontend_id = conn.conn_id
        self.received_at = received_at


class _Conn:
    """One accepted front-end connection: its socket, a write lock (the
    serve loop and the coalescer's dispatcher thread both reply), and
    in-flight accounting for the disconnect reclaim."""

    __slots__ = ("sock", "conn_id", "alive", "wlock", "in_flight")

    def __init__(self, sock, conn_id: int):
        self.sock = sock
        self.conn_id = conn_id
        self.alive = True
        self.wlock = threading.Lock()
        self.in_flight = 0


class NetQueueServer:
    """The dispatcher side of the socket row queue — the same
    ``poll``/``reply`` surface as
    :class:`~bodywork_tpu.serve.rowqueue.RowQueueServer`, so
    ``DispatchServer`` pumps either transport unchanged. Listens on TCP
    or a Unix domain socket, accepts any number of front-end
    connections, and feeds their SUBMIT frames through one internal
    queue — the coalescer downstream still batches from the union of
    every front-end's rows.

    A dropped connection reclaims its in-flight budget (the socket
    analogue of ``RowQueue.reclaim_frontend``): queued submissions from
    the dead connection are skipped at ``poll`` and replies to it are
    dropped, never raised."""

    def __init__(self, address, credit_window: int = DEFAULT_SLOTS,
                 backlog: int = 64, fence: int = 0):
        self.credit_window = int(credit_window)
        #: leadership fence announced in every HELLO; 0 = no election
        #: (clients then never refuse on fence). An elected dispatcher
        #: passes its lease fence so zombie ex-leaders are refused.
        self.fence = int(fence)
        self._unix_path = None
        if address[0] == "unix":
            self._unix_path = address[1]
            if os.path.exists(self._unix_path):
                os.unlink(self._unix_path)  # stale socket from a crash
            self._listener = socket.socket(
                socket.AF_UNIX, socket.SOCK_STREAM
            )
            self._listener.bind(self._unix_path)
        else:
            self._listener = socket.socket(
                socket.AF_INET, socket.SOCK_STREAM
            )
            self._listener.setsockopt(
                socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
            )
            self._listener.bind((address[1], address[2]))
        self._listener.listen(backlog)
        self.address = (
            ("unix", self._unix_path) if self._unix_path is not None
            else ("tcp",) + self._listener.getsockname()[:2]
        )
        self._subs: queue_mod.Queue = queue_mod.Queue()
        self._conns: dict[int, _Conn] = {}
        self._lock = threading.Lock()
        self._next_conn_id = 0
        self._stopped = False
        self._in_flight = 0
        from bodywork_tpu.obs import get_registry

        reg = get_registry()
        # same dispatcher-side families as the shm server, so dashboards
        # and the depth-based runbooks see one queue either way. The
        # handoff histogram here covers socket receive -> dispatch poll
        # (one clock); the full cross-host hop is the CLIENT's
        # netqueue_rtt_seconds — two hosts share no monotonic clock.
        self._m_handoff = reg.histogram(
            "bodywork_tpu_rowqueue_handoff_seconds",
            "Front-end enqueue -> dispatcher dequeue across the shared "
            "row-queue (the cost of the disaggregation hop)",
            buckets=(0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1, 0.5),
        )
        self._m_depth = reg.gauge(
            "bodywork_tpu_rowqueue_depth",
            "Row-queue requests dequeued by the dispatcher and not yet "
            "replied to",
            aggregate="sum",
        )
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="netqueue-accept", daemon=True
        )
        self._accept_thread.start()

    # -- accept / per-connection readers -------------------------------------
    def _accept_loop(self) -> None:
        while not self._stopped:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            if sock.family == socket.AF_INET:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                conn = _Conn(sock, self._next_conn_id)
                self._next_conn_id += 1
                self._conns[conn.conn_id] = conn
            try:
                hello = _HELLO_BODY.pack(
                    WIRE_SCHEMA_VERSION, self.credit_window, self.fence
                ) + BINARY_CONTENT_TYPE.encode("ascii")
                sock.sendall(_frame(_MSG_HELLO, hello))
            except OSError:
                self._drop_conn(conn)
                continue
            threading.Thread(
                target=self._conn_reader, args=(conn,),
                name=f"netqueue-conn-{conn.conn_id}", daemon=True,
            ).start()
            log.info(
                f"netqueue front-end connection {conn.conn_id} accepted "
                f"(window {self.credit_window})"
            )

    def _conn_reader(self, conn: _Conn) -> None:
        try:
            while not self._stopped:
                msg_type, body = _recv_frame(conn.sock)
                if msg_type != _MSG_SUBMIT:
                    raise ConnectionError(
                        f"unexpected frame type {msg_type}"
                    )
                req_id, kind, trace_len = _SUBMIT_HEADER.unpack_from(body)
                offset = _SUBMIT_HEADER.size
                trace_id = body[offset:offset + trace_len].decode(
                    "ascii", "replace"
                ) or None
                X, err = parse_binary_rows(body[offset + trace_len:])
                if err is not None:
                    raise ConnectionError(f"bad row framing: {err}")
                with conn.wlock:
                    conn.in_flight += 1
                if conn.in_flight > self.credit_window:
                    # the client enforces the window; exceeding it here
                    # is a protocol violation, not backpressure
                    raise ConnectionError("credit window exceeded")
                self._subs.put(_NetSubmission(
                    conn, req_id, int(kind), X, trace_id, time.monotonic()
                ))
        except (OSError, ConnectionError) as exc:
            if not self._stopped:
                log.warning(
                    f"netqueue front-end connection {conn.conn_id} "
                    f"dropped: {exc}"
                )
        finally:
            self._drop_conn(conn)

    def _drop_conn(self, conn: _Conn) -> None:
        with self._lock:
            if not conn.alive:
                return
            conn.alive = False
            self._conns.pop(conn.conn_id, None)
        reclaimed = conn.in_flight
        if reclaimed:
            # the socket analogue of the dead-front-end slot reclaim:
            # its queued submissions are skipped at poll and its
            # in-flight budget evaporates with the connection
            log.warning(
                f"reclaimed {reclaimed} in-flight submission(s) from "
                f"dead front-end connection {conn.conn_id}"
            )
        _shutdown_close(conn.sock)

    # -- the RowQueueServer surface ------------------------------------------
    def poll(self, timeout_s: float = 0.2):
        """Next live submission, or None on timeout. Submissions whose
        connection died while they queued are skipped (their front-end
        can no longer receive the reply)."""
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                sub = self._subs.get(timeout=remaining)
            except queue_mod.Empty:
                return None
            if not sub.conn.alive:
                continue  # dead front-end: reply would go nowhere
            self._m_handoff.observe(
                max(0.0, time.monotonic() - sub.received_at),
                exemplar=sub.trace_id,
            )
            with self._lock:
                self._in_flight += 1
                self._m_depth.set(float(self._in_flight))
            return sub

    def reply(self, sub, status: int, predictions=None,
              bundle=None) -> None:
        """Write one REPLY frame back over the owning connection. A dead
        connection drops the reply silently — the front-end's waits
        already failed when its connection broke."""
        n = 0
        pred_bytes = b""
        if predictions is not None:
            arr = np.asarray(predictions, dtype="<f4").ravel()
            n = int(arr.shape[0])
            pred_bytes = np.ascontiguousarray(arr).tobytes()
        blob = b"[null, null, null]"
        if bundle is not None:
            blob = json.dumps([
                bundle.model_key, bundle.model_info, bundle.model_date,
            ]).encode()
        payload = (
            _REPLY_HEADER.pack(sub.req_id, status, n)
            + pred_bytes
            + struct.pack("<I", len(blob))
            + blob
        )
        with self._lock:
            self._in_flight = max(0, self._in_flight - 1)
            self._m_depth.set(float(self._in_flight))
        conn = sub.conn
        try:
            with conn.wlock:
                if not conn.alive:
                    return
                conn.in_flight = max(0, conn.in_flight - 1)
                conn.sock.sendall(_frame(_MSG_REPLY, payload))
        except OSError as exc:
            log.warning(
                f"netqueue reply to dead front-end connection "
                f"{conn.conn_id} dropped: {exc}"
            )
            self._drop_conn(conn)

    def close(self) -> None:
        self._stopped = True
        _shutdown_close(self._listener)  # wakes the blocked accept()
        with self._lock:
            conns = list(self._conns.values())
        for conn in conns:
            self._drop_conn(conn)
        if self._unix_path and os.path.exists(self._unix_path):
            try:
                os.unlink(self._unix_path)
            except OSError:
                pass
        if self._accept_thread.ident is not None:
            self._accept_thread.join(timeout=5)


# re-exported for callers that only deal in transports
KIND_SINGLE = KIND_SINGLE
