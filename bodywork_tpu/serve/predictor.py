"""Shape-bucketed prediction wrapper for serving.

SURVEY.md "hard part (1)": keep host<->device transfers and *recompilation*
out of the per-request path. Under jit, every distinct input shape is a new
XLA compilation; a scoring service seeing arbitrary request sizes would
compile on the request path. This wrapper pads each request's row count up to
a fixed bucket (powers of two), so the set of compiled executables is small,
pre-warmable at startup, and shared across requests. Oversized requests are
chunked through the largest bucket.

The reference has no analogue (sklearn predict is shape-agnostic); this is
pure TPU-serving design.
"""
from __future__ import annotations

import itertools

import numpy as np

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.predictor")

DEFAULT_BUCKETS = (1, 8, 64, 512, 4096)

#: (predictor class, model class, n_features, bucket, extra) shapes already
#: dispatched this process — the jit cache holds their executables, so
#: re-warming them (e.g. the day-loop re-serving daily) would only pay a
#: pointless host->device transfer per bucket
_WARMED_SHAPES: set[tuple] = set()


class PaddedPredictor:
    """Bucket-padding predictor over ``model.predict``.

    Subclasses may override :meth:`_predict_padded` to change the execution
    backend (e.g. sharded over a mesh) while reusing the bucket/pad/chunk
    logic here.
    """

    def __init__(self, model: Regressor, buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        assert model.params is not None, "cannot serve an unfitted model"
        self.model = model
        self.buckets = tuple(sorted(buckets))

    def _predict_padded(self, Xp: np.ndarray) -> np.ndarray:
        """Run the model on an exactly-bucket-sized batch."""
        return np.asarray(self._dispatch_padded(Xp))

    def _dispatch_padded(self, Xp: np.ndarray):
        """Dispatch the padded batch without materialising on the host
        (compile + enqueue only — no device->host transfer)."""
        return self.model.predict_device(Xp)

    def warmup(self, n_features: int | None = None, sync: bool = True) -> None:
        """Compile every bucket shape before taking traffic (startup cost,
        analogous to the reference's load-model-at-boot — ``stage_2:113``).

        The feature dimension defaults to the fitted model's own, so the
        shapes compiled here are exactly the request-path shapes. All
        buckets are dispatched first (XLA compiles synchronously at
        dispatch; execution drains asynchronously), then with ``sync`` a
        ``fence`` (``utils.sync``) surfaces any device-side execution error
        (e.g. HBM OOM on the largest bucket) HERE — before the health gate
        reports ready — at the cost of one tiny fetch per bucket
        (``block_until_ready`` would be transfer-free but does not actually
        wait over the axon relay). ``sync=False`` is for callers that
        already executed these exact shapes in this process (the local
        day-loop re-serving each day).
        """
        import jax

        if n_features is None:
            n_features = self.model.n_features or 1
        # the compiled program depends on every param leaf's shape (two
        # same-class models with different widths compile differently), so
        # fingerprint them into the dedup key
        shapes = tuple(
            tuple(leaf.shape)
            for leaf in jax.tree_util.tree_leaves(self.model.params)
        )
        extra = self._warm_key_extra()
        results, added = [], []
        try:
            for b in self.buckets:
                key = (type(self), type(self.model), shapes, n_features, b, extra)
                if key in _WARMED_SHAPES:
                    continue
                results.append(
                    self._dispatch_padded(
                        np.zeros((b, n_features), dtype=np.float32)
                    )
                )
                # only a successful dispatch counts as warmed
                _WARMED_SHAPES.add(key)
                added.append(key)
            if sync and results:
                from bodywork_tpu.utils.sync import fence

                fence(results)
        except BaseException:
            # a failed warm must be retryable, not silently skipped forever
            _WARMED_SHAPES.difference_update(added)
            raise
        log.info(
            f"warmed up predict buckets {self.buckets} (n_features={n_features},"
            f" {len(results)} new)"
        )

    def _warm_key_extra(self) -> tuple:
        """Extra warm-cache key material beyond (model class, shape): the
        params' device placement. Two same-shape models pinned to different
        devices (an A/B run) compile distinct per-device executables — a
        shared key would skip the second variant's warmup and push its
        compile (and any device fault) onto the first scoring request.
        Subclasses add what else their program depends on (e.g. the mesh).
        """
        import jax

        ids = set()
        for leaf in jax.tree_util.tree_leaves(self.model.params):
            if isinstance(leaf, jax.Array):
                ids.update(d.id for d in leaf.devices())
        return tuple(sorted(ids))

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def predict(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        n = X.shape[0]
        max_bucket = self.buckets[-1]
        if n > max_bucket:
            # chunk through the largest compiled bucket
            parts = [
                self.predict(X[i : i + max_bucket]) for i in range(0, n, max_bucket)
            ]
            return np.concatenate(parts)
        b = self._bucket_for(n)
        if b != n:
            Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
            Xp[:n] = X
        else:
            Xp = X
        return self._predict_padded(Xp)[:n]


#: process-wide jitted bf16 apply, shared by every BF16MLPPredictor
#: instance (mirroring the per-class ``_APPLY_FNS`` cache in models/base):
#: a hot-reload swap builds a fresh predictor for the new checkpoint, and
#: only a SHARED jit wrapper lets the ``_WARMED_SHAPES`` dedup skip its
#: warmup correctly — a per-instance wrapper would have an empty compile
#: cache and push the compile onto the first scoring request
_BF16_APPLY = None


def bf16_mlp_apply():
    """The shared jitted ``mlp_apply(..., compute_dtype='bfloat16')`` —
    also what the benchmark times, so the measured engine IS the served
    one."""
    global _BF16_APPLY
    if _BF16_APPLY is None:
        from functools import partial

        import jax

        from bodywork_tpu.models.mlp import mlp_apply

        _BF16_APPLY = jax.jit(partial(mlp_apply, compute_dtype="bfloat16"))
    return _BF16_APPLY


class BF16MLPPredictor(PaddedPredictor):
    """Serves an MLP with the dense stack's matmuls in bfloat16 (the
    opt-in ``xla-bf16`` engine): single-pass MXU at wide widths, ~half the
    HBM traffic of f32 weights. Predictions carry bf16's ~3 significant
    digits — callers choose this engine explicitly for throughput; the
    default engine stays f32 so the frozen contract's recorded exchanges
    reproduce bit-for-bit.
    """

    def __init__(self, model, buckets: tuple[int, ...] | None = None):
        from bodywork_tpu.models.mlp import MLPRegressor

        if not isinstance(model, MLPRegressor):
            raise ValueError(
                f"engine='xla-bf16' serves MLP models; got {model.info}"
            )
        super().__init__(model, buckets if buckets else DEFAULT_BUCKETS)
        self._apply = bf16_mlp_apply()

    def _dispatch_padded(self, Xp: np.ndarray):
        return self._apply(self.model.params, Xp)

    def _warm_key_extra(self) -> tuple:
        # a distinct executable per engine: never share warm state with
        # the f32 predictor for the same model/shape
        return ("xla-bf16", *super()._warm_key_extra())


class PallasMLPPredictor(PaddedPredictor):
    """Serves an MLP through the fused Pallas kernel
    (:mod:`bodywork_tpu.ops.mlp_kernel`): scaler folded into the weights,
    the whole forward as one VMEM-resident kernel per padded batch.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    tests); on TPU leave it False.
    """

    #: monotonic instance ids — id(self) could be recycled by the allocator
    #: and alias a dead predictor's warm-cache entries
    _instance_counter = itertools.count()

    def __init__(self, model, buckets: tuple[int, ...] | None = None,
                 interpret: bool = False,
                 compute_dtype: str | None = None):
        from bodywork_tpu.ops import ROW_TILE, make_pallas_mlp_apply

        if buckets is None:
            # the kernel pads every batch to a ROW_TILE multiple anyway;
            # sub-tile buckets would just compile duplicate programs
            buckets = (ROW_TILE, 2 * ROW_TILE, 16 * ROW_TILE)
        super().__init__(model, buckets)
        self._apply = make_pallas_mlp_apply(
            model.params, interpret=interpret, compute_dtype=compute_dtype
        )
        self._instance_id = next(self._instance_counter)

    def _dispatch_padded(self, Xp: np.ndarray):
        return self._apply(Xp)

    def _warm_key_extra(self) -> tuple:
        # params are baked into the kernel closure: never share warm state
        # with other predictors (or other instances) of this model class
        return ("pallas", self._instance_id)
