"""Shape-bucketed prediction wrapper for serving, over an AOT-compiled
executable cache.

SURVEY.md "hard part (1)": keep host<->device transfers and *recompilation*
out of the per-request path. Under jit, every distinct input shape is a new
XLA compilation; a scoring service seeing arbitrary request sizes would
compile on the request path. This wrapper pads each request's row count up to
a fixed bucket (powers of two), so the set of compiled executables is small,
pre-warmable at startup, and shared across requests. Oversized requests are
chunked through the largest bucket.

Compilation itself goes through a PROCESS-WIDE executable cache
(:data:`EXECUTABLE_CACHE`): every bucket's program is lowered and compiled
ahead of time (``jax.jit(...).lower(...).compile()``) and keyed by
``(engine tag, param-shape digest, bucket shape, device placement)`` —
*not* by the parameter values. A hot swap to a same-architecture
checkpoint therefore re-binds the new params to the already-compiled
executable: the swap pays zero compiles, on or off the request path, which
is what keeps the canary watchdog's p99-ratio verdict from eating a
compile stall every time a canary starts or production rolls. Input
buffers are donated on the dispatch path where the backend supports it
(TPU/GPU; the padded batch is a fresh scratch buffer, so the executable
may reuse its memory for the output).

The reference has no analogue (sklearn predict is shape-agnostic); this is
pure TPU-serving design.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.obs.tracing import annotate_active
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.predictor")

DEFAULT_BUCKETS = (1, 8, 64, 512, 4096)

#: the serving dtypes `cli serve --dtype` exposes — ONE source of truth,
#: pinned == the cli choices == bench config 11's sweep by a guard test
#: (tests/test_compiled.py). "float32" is the default engine exactly as
#: before; "bfloat16"/"int8" are the quantized variants, which only ever
#: serve after the shadow quality gate admits them (serve.server).
SERVE_DTYPES = ("float32", "bfloat16", "int8")

#: set to "0" to disable CROSS-INSTANCE executable reuse (each predictor
#: then compiles its own buckets — the pre-AOT behaviour whose swap
#: stall bench config 11 measures as the baseline). Per-instance caching
#: and the hit/miss accounting stay on either way.
AOT_CACHE_ENV = "BODYWORK_TPU_AOT_CACHE"

#: (predictor class, model class, n_features, bucket, extra) shapes already
#: dispatched this process — their executables are compiled and their
#: first execution has run, so re-warming them (e.g. the day-loop
#: re-serving daily) would only pay a pointless host->device transfer
#: per bucket
_WARMED_SHAPES: set[tuple] = set()


def params_shape_digest(params) -> tuple:
    """A hashable fingerprint of a params pytree's ARCHITECTURE — every
    leaf's shape, dtype, and device placement/sharding, in tree order —
    deliberately blind to the values: two same-architecture checkpoints
    digest identically, which is exactly what lets a hot swap re-bind
    new params to an already-compiled executable. Sharding is part of
    the program identity (mesh-sharded params lower a different
    computation than single-device ones), so it is part of the key."""
    import jax

    return tuple(
        (
            tuple(np.shape(leaf)),
            str(np.result_type(leaf)),
            str(getattr(leaf, "sharding", None)),
        )
        for leaf in jax.tree_util.tree_leaves(params)
    )


def _leaf_struct(leaf):
    """The ShapeDtypeStruct an AOT lowering sees for one params leaf —
    sharding-preserving: a compiled executable must accept the ACTUAL
    arrays it will be called with (a mesh-sharded checkpoint's leaves
    carry NamedShardings; lowering them as single-device would make
    every call a sharding-mismatch error)."""
    import jax

    sharding = getattr(leaf, "sharding", None)
    if sharding is not None:
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sharding)
    return jax.ShapeDtypeStruct(np.shape(leaf), np.result_type(leaf))


class ExecutableCache:
    """Process-wide cache of AOT-compiled serving executables.

    Keyed by ``(engine tag, params digest, batch shape, devices)`` — the
    full identity of an XLA program minus the parameter VALUES. Entries
    survive hot swaps (the whole point) and are never evicted: the key
    space is bounded by (architectures seen) x (buckets), both small by
    design. Hit/miss counters and the compile-seconds histogram are the
    observability contract bench config 11 and the swap regression test
    read (``bodywork_tpu_serve_executable_cache_{hits,misses}_total``,
    ``bodywork_tpu_serve_compile_seconds``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: dict[tuple, object] = {}
        # plain-int mirrors of the obs counters, for cheap assertions
        # (the counting-jit seam the swap regression test reads)
        self.hits = 0
        self.misses = 0
        self._metrics = None

    def _obs(self):
        if self._metrics is None:
            from bodywork_tpu.obs import get_registry

            reg = get_registry()
            self._metrics = (
                reg.counter(
                    "bodywork_tpu_serve_executable_cache_hits_total",
                    "Serving-bucket executable requests answered from the "
                    "process-wide AOT cache (no compile)",
                ),
                reg.counter(
                    "bodywork_tpu_serve_executable_cache_misses_total",
                    "Serving-bucket executables compiled (cache miss); a "
                    "nonzero rate on the request path is a warmup bug",
                ),
                reg.histogram(
                    "bodywork_tpu_serve_compile_seconds",
                    "Wall time of one serving-bucket AOT lower+compile "
                    "(executable-cache miss)",
                ),
            )
        return self._metrics

    @staticmethod
    def enabled() -> bool:
        return os.environ.get(AOT_CACHE_ENV, "1") != "0"

    def get(self, key: tuple, build):
        """The compiled executable for ``key``, compiling via ``build()``
        on a miss. With the cache disabled (:data:`AOT_CACHE_ENV`) every
        call compiles — the measured-stall baseline — but still counts."""
        hits, misses, compile_s = self._obs()
        if self.enabled():
            with self._lock:
                compiled = self._cache.get(key)
            if compiled is not None:
                with self._lock:
                    self.hits += 1
                hits.inc()
                return compiled
        t0 = time.perf_counter()
        compiled = build()
        compile_seconds = time.perf_counter() - t0
        with self._lock:
            self.misses += 1
            if self.enabled():
                self._cache[key] = compiled
        misses.inc()
        compile_s.observe(compile_seconds)
        return compiled

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._cache),
                "hits": self.hits,
                "misses": self.misses,
            }


#: THE process-wide executable cache (one per serving process, exactly as
#: one k8s pod holds one XLA compile cache)
EXECUTABLE_CACHE = ExecutableCache()


def _donate_inputs() -> bool:
    """Donate the padded batch buffer to the executable where the
    backend implements donation (TPU/GPU). Safe by construction: inputs
    arrive as HOST numpy arrays, so what the executable consumes (and
    may reuse for its output) is the device-side transfer buffer — the
    caller's array is never aliased, and the uncoalesced
    sanity-firewall fallback re-predict (serve.app) that re-submits the
    SAME host array is unaffected (pinned by test). On CPU XLA ignores
    donation (and warns at compile), so skip it there."""
    import jax

    return jax.devices()[0].platform in ("tpu", "gpu")


class PaddedPredictor:
    """Bucket-padding predictor over ``model.predict``.

    Subclasses may override :meth:`_predict_padded` to change the execution
    backend (e.g. sharded over a mesh) while reusing the bucket/pad/chunk
    logic here.
    """

    #: the serving dtype tag this predictor class answers for (one of
    #: :data:`SERVE_DTYPES`) — part of the executable-cache key and the
    #: /healthz identity the quantization gate reports
    dtype = "float32"

    def __init__(self, model: Regressor, buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        assert model.params is not None, "cannot serve an unfitted model"
        self.model = model
        self.buckets = tuple(sorted(buckets))
        #: per-instance executable handles: (bucket, n_features) ->
        #: compiled. A plain dict read on the hot path; the process-wide
        #: EXECUTABLE_CACHE behind it is what survives this instance
        self._compiled: dict[tuple, object] = {}
        self._aot_eligible: bool | None = None

    # -- AOT executable plumbing -------------------------------------------
    def _aot_fn(self):
        """The pure ``(params, X) -> y`` apply this predictor's
        executables are lowered from, or None when the engine cannot be
        AOT-cached across instances (params baked into a kernel closure,
        mesh-placed dispatch) — those subclasses fall back to their own
        jit path in :meth:`_dispatch_padded`."""
        return type(self.model).apply

    def _exec_params(self):
        """The params pytree the compiled executable is CALLED with
        (quantized predictors substitute their quantized tree)."""
        return self.model.params

    def _x_struct(self, bucket: int, n_features: int):
        """The ShapeDtypeStruct the padded input batch is lowered as.
        Mesh-sharded predictors attach a NamedSharding here so the
        compiled program shards rows over the mesh's ``data`` axis."""
        import jax

        return jax.ShapeDtypeStruct((bucket, n_features), np.float32)

    def _out_shardings(self):
        """Output sharding for the AOT lowering (None = let jit decide —
        the single-device default). Mesh predictors pin the row-sharded
        output so nothing forces a gather inside the program."""
        return None

    def _aot_ok(self) -> bool:
        """Whether this predictor's params can be AOT-lowered: a pytree
        mixing multi-device-sharded leaves (a mesh-trained checkpoint)
        with uncommitted host leaves has no single lowering the compiled
        call signature can pin — jit reconciles such mixes at trace
        time, so those params keep the per-class jit path (mesh serving
        proper goes through DataParallelPredictor)."""
        if self._aot_eligible is None:
            import jax

            eligible = self._aot_fn() is not None
            if eligible:
                for leaf in jax.tree_util.tree_leaves(self._exec_params()):
                    sharding = getattr(leaf, "sharding", None)
                    if sharding is not None and len(leaf.devices()) > 1:
                        eligible = False
                        break
            self._aot_eligible = eligible
        return self._aot_eligible

    def _compiled_for(self, bucket: int, n_features: int):
        """The AOT executable for one padded batch shape — resolved from
        the process-wide cache, compiling on first sight of this
        (architecture, shape) anywhere in the process. Request-side
        calls normally hit the per-instance dict; a lazy compile here is
        an executable-cache miss, which the swap regression test pins
        at zero across a warmed hot swap."""
        import jax

        handle = self._compiled.get((bucket, n_features))
        if handle is not None:
            # the normal warmed case: this instance's own handle. The
            # annotation is the tracing seam (obs.tracing) — a no-op
            # contextvar read unless a sampled request's dispatch span
            # is active.
            annotate_active(aot_cache="warm", bucket=bucket)
            return handle
        # first sight of this shape on THIS instance: the annotation
        # below records whether the process-wide cache answered ("hit")
        # or a lazy compile landed on the request path ("miss" — the
        # warmup-bug signal the cache-miss counter also carries)
        misses_before = EXECUTABLE_CACHE.misses
        fn = self._aot_fn()
        params = self._exec_params()
        key = (
            # BOTH classes: the predictor picks the program variant, the
            # MODEL class owns the apply being lowered — two model
            # classes with identical params architectures must never
            # share an executable (the warmup dedup key makes the same
            # distinction)
            type(self).__name__, type(self.model).__qualname__, self.dtype,
            params_shape_digest(params), (bucket, n_features),
            self._warm_key_extra(),
        )

        def build():
            structs = jax.tree_util.tree_map(_leaf_struct, params)
            x_struct = self._x_struct(bucket, n_features)
            donate = (1,) if _donate_inputs() else ()
            jit_kwargs: dict = {"donate_argnums": donate}
            out_shardings = self._out_shardings()
            if out_shardings is not None:
                jit_kwargs["out_shardings"] = out_shardings
            return (
                jax.jit(fn, **jit_kwargs)
                .lower(structs, x_struct)
                .compile()
            )

        handle = EXECUTABLE_CACHE.get(key, build)
        self._compiled[(bucket, n_features)] = handle
        annotate_active(
            aot_cache=(
                "miss" if EXECUTABLE_CACHE.misses > misses_before else "hit"
            ),
            bucket=bucket,
        )
        return handle

    def _predict_padded(self, Xp: np.ndarray) -> np.ndarray:
        """Run the model on an exactly-bucket-sized batch."""
        return np.asarray(self._dispatch_padded(Xp))

    def _fallback_dispatch(self, Xp: np.ndarray):
        """The non-AOT dispatch (``_aot_ok`` False — e.g. mesh-sharded
        params): MUST serve the same engine/dtype as the AOT path, so
        quantized subclasses override it with their own jitted quantized
        apply — falling back to the f32 per-class apply there would
        silently serve a different precision than /healthz reports."""
        return self.model.predict_device(Xp)

    def _dispatch_padded(self, Xp: np.ndarray):
        """Dispatch the padded batch without materialising on the host
        (enqueue only — no device->host transfer). Routes through the
        bucket's AOT executable, so the request path never compiles
        (a shape nobody warmed still works — it compiles here, counted
        as a cache miss). Engines/params that cannot AOT-cache
        (``_aot_ok`` False) fall back to the per-class jit path.

        A sampled request's active device-dispatch span (obs.tracing)
        is annotated by ``_compiled_for`` with how the executable
        resolved: ``warm`` (this instance's own handle — the normal
        warmed case), ``hit`` (process-wide cache, first sight on this
        instance), ``miss`` (lazily compiled ON the request path — the
        warmup-bug signal the cache-miss counter also carries)."""
        if not self._aot_ok():
            return self._fallback_dispatch(Xp)
        return self._compiled_for(Xp.shape[0], Xp.shape[1])(
            self._exec_params(), Xp
        )

    def warmup(self, n_features: int | None = None, sync: bool = True) -> None:
        """Compile every bucket's executable AND run each once before
        taking traffic (startup cost, analogous to the reference's
        load-model-at-boot — ``stage_2:113``).

        The feature dimension defaults to the fitted model's own, so the
        shapes compiled here are exactly the request-path shapes.
        Compilation is the AOT lower+compile through the process-wide
        executable cache — a same-architecture swap finds every bucket
        already compiled and pays nothing. Each bucket is then executed
        once (XLA compiles nothing at dispatch; execution drains
        asynchronously), and with ``sync`` a ``fence`` (``utils.sync``)
        surfaces any device-side execution error (e.g. HBM OOM on the
        largest bucket) HERE — before the health gate reports ready — at
        the cost of one tiny fetch per bucket (``block_until_ready``
        would be transfer-free but does not actually wait over the axon
        relay). ``sync=False`` is for callers that already executed
        these exact shapes in this process (the local day-loop
        re-serving each day)."""
        import jax

        if n_features is None:
            n_features = self.model.n_features or 1
        # the compiled program depends on every param leaf's shape (two
        # same-class models with different widths compile differently), so
        # fingerprint them into the dedup key
        shapes = tuple(
            tuple(leaf.shape)
            for leaf in jax.tree_util.tree_leaves(self.model.params)
        )
        extra = self._warm_key_extra()
        results, added = [], []
        try:
            for b in self.buckets:
                key = (type(self), type(self.model), shapes, n_features, b, extra)
                if key in _WARMED_SHAPES:
                    # executables compiled + executed earlier in this
                    # process; re-warming would only pay a transfer. The
                    # per-instance handle dict still needs filling so
                    # the first request doesn't pay a (cheap, cache-hit)
                    # process-cache lookup under its latency budget.
                    if self._aot_ok():
                        self._compiled_for(b, n_features)
                    continue
                results.append(
                    self._dispatch_padded(
                        np.zeros((b, n_features), dtype=np.float32)
                    )
                )
                # only a successful dispatch counts as warmed
                _WARMED_SHAPES.add(key)
                added.append(key)
            if sync and results:
                from bodywork_tpu.utils.sync import fence

                fence(results)
        except BaseException:
            # a failed warm must be retryable, not silently skipped forever
            _WARMED_SHAPES.difference_update(added)
            raise
        log.info(
            f"warmed up predict buckets {self.buckets} (n_features={n_features},"
            f" {len(results)} new)"
        )


    def _warm_key_extra(self) -> tuple:
        """Extra warm-cache key material beyond (model class, shape): the
        params' device placement. Two same-shape models pinned to different
        devices (an A/B run) compile distinct per-device executables — a
        shared key would skip the second variant's warmup and push its
        compile (and any device fault) onto the first scoring request.
        Subclasses add what else their program depends on (e.g. the mesh).
        """
        import jax

        ids = set()
        for leaf in jax.tree_util.tree_leaves(self.model.params):
            if isinstance(leaf, jax.Array):
                ids.update(d.id for d in leaf.devices())
        return tuple(sorted(ids))

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def predict(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        n = X.shape[0]
        max_bucket = self.buckets[-1]
        if n > max_bucket:
            # chunk through the largest compiled bucket
            parts = [
                self.predict(X[i : i + max_bucket]) for i in range(0, n, max_bucket)
            ]
            return np.concatenate(parts)
        b = self._bucket_for(n)
        if b != n:
            Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
            Xp[:n] = X
        else:
            Xp = X
        return self._predict_padded(Xp)[:n]


#: process-wide jitted bf16 apply — kept for the benchmark's device-side
#: (HTTP-free) timing path, so the measured program is the same one the
#: BF16MLPPredictor's AOT executables are lowered from
_BF16_APPLY = None


def bf16_mlp_apply():
    """The shared jitted ``mlp_apply(..., compute_dtype='bfloat16')``."""
    global _BF16_APPLY
    if _BF16_APPLY is None:
        import jax

        _BF16_APPLY = jax.jit(_bf16_apply_fn())
    return _BF16_APPLY


def _bf16_apply_fn():
    from functools import partial

    from bodywork_tpu.models.mlp import mlp_apply

    return partial(mlp_apply, compute_dtype="bfloat16")


class BF16MLPPredictor(PaddedPredictor):
    """Serves an MLP with the dense stack's matmuls in bfloat16 (the
    opt-in ``xla-bf16`` engine, also ``--dtype bfloat16``): single-pass
    MXU at wide widths, ~half the HBM traffic of f32 weights. Predictions
    carry bf16's ~3 significant digits — callers choose this engine
    explicitly for throughput (and ``--dtype`` routes it through the
    shadow quality gate first); the default engine stays f32 so the
    frozen contract's recorded exchanges reproduce bit-for-bit.
    """

    dtype = "bfloat16"

    def __init__(self, model, buckets: tuple[int, ...] | None = None):
        from bodywork_tpu.models.mlp import MLPRegressor

        if not isinstance(model, MLPRegressor):
            raise ValueError(
                f"engine='xla-bf16' serves MLP models; got {model.info}"
            )
        super().__init__(model, buckets if buckets else DEFAULT_BUCKETS)

    def _aot_fn(self):
        return _bf16_apply_fn()

    def _fallback_dispatch(self, Xp: np.ndarray):
        # same bf16 program, jit-cached — never the f32 apply
        return bf16_mlp_apply()(self.model.params, Xp)

    def _warm_key_extra(self) -> tuple:
        # a distinct executable per engine: never share warm state with
        # the f32 predictor for the same model/shape
        return ("xla-bf16", *super()._warm_key_extra())


class Int8MLPPredictor(PaddedPredictor):
    """Serves an MLP from int8 weights (``--dtype int8``): every dense
    weight matrix is quantized once at construction to symmetric
    per-output-channel int8 (``models.fused.quantize_mlp_params_int8``)
    and dequantized inside the compiled program — a quarter of f32's
    weight HBM traffic per forward, the dominant serving cost for
    memory-bound widths. Biases, the scaler, and accumulation stay f32.
    Quantization error is a per-matmul relative error of order 1/127 on
    the weight operand; ``--dtype`` routes the realised quality delta
    through the shadow gate before this predictor may serve."""

    dtype = "int8"

    def __init__(self, model, buckets: tuple[int, ...] | None = None):
        import jax

        from bodywork_tpu.models.fused import quantize_mlp_params_int8
        from bodywork_tpu.models.mlp import MLPRegressor

        if not isinstance(model, MLPRegressor):
            raise ValueError(
                f"dtype='int8' serves MLP models; got {model.info}"
            )
        super().__init__(model, buckets if buckets else DEFAULT_BUCKETS)
        # quantize once, then pin the quantized tree in device memory:
        # a host-resident pytree would re-upload the whole weight stack
        # on EVERY dispatch — exactly the per-request transfer this
        # module exists to eliminate
        self._qparams = jax.device_put(
            quantize_mlp_params_int8(model.host_params())
        )

    def _aot_fn(self):
        from bodywork_tpu.models.fused import int8_mlp_apply

        return int8_mlp_apply

    def _exec_params(self):
        return self._qparams

    def _fallback_dispatch(self, Xp: np.ndarray):
        # same int8 program, jit-cached — never the f32 apply
        return _int8_jit_apply()(self._qparams, Xp)

    def _warm_key_extra(self) -> tuple:
        return ("xla-int8", *super()._warm_key_extra())


#: process-wide jitted int8 apply — the Int8 predictor's non-AOT
#: fallback path (mesh-mixed params), same program as its executables
_INT8_APPLY = None


def _int8_jit_apply():
    global _INT8_APPLY
    if _INT8_APPLY is None:
        import jax

        from bodywork_tpu.models.fused import int8_mlp_apply

        _INT8_APPLY = jax.jit(int8_mlp_apply)
    return _INT8_APPLY


class PallasMLPPredictor(PaddedPredictor):
    """Serves an MLP through the fused Pallas kernel
    (:mod:`bodywork_tpu.ops.mlp_kernel`): scaler folded into the weights,
    the whole forward as one VMEM-resident kernel per padded batch.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU
    tests); on TPU leave it False.
    """

    #: monotonic instance ids — id(self) could be recycled by the allocator
    #: and alias a dead predictor's warm-cache entries
    _instance_counter = itertools.count()

    def __init__(self, model, buckets: tuple[int, ...] | None = None,
                 interpret: bool = False,
                 compute_dtype: str | None = None,
                 row_tile: int | None = None):
        from bodywork_tpu.ops import ROW_TILE, make_pallas_mlp_apply

        if compute_dtype in ("bfloat16", "int8"):
            self.dtype = compute_dtype
        tile = row_tile or ROW_TILE
        if buckets is None:
            # the kernel pads every batch to a row-tile multiple anyway;
            # sub-tile buckets would just compile duplicate programs.
            # A caller serving the coalescer's small flushes passes a
            # smaller row_tile (the kernel grids over it) so a handful
            # of coalesced rows stops padding to the full 256-row tile.
            buckets = (tile, 2 * tile, 16 * tile)
        super().__init__(model, buckets)
        self._apply = make_pallas_mlp_apply(
            model.params, interpret=interpret, compute_dtype=compute_dtype,
            row_tile=tile,
        )
        self._instance_id = next(self._instance_counter)

    def _aot_fn(self):
        # params live inside the kernel closure: nothing to re-bind
        # across a swap, so the process-wide executable cache does not
        # apply — the per-instance jit apply below is the compile cache
        return None

    def _dispatch_padded(self, Xp: np.ndarray):
        return self._apply(Xp)

    def _warm_key_extra(self) -> tuple:
        # params are baked into the kernel closure: never share warm state
        # with other predictors (or other instances) of this model class
        return ("pallas", self._instance_id)
