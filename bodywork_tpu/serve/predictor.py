"""Shape-bucketed prediction wrapper for serving.

SURVEY.md "hard part (1)": keep host<->device transfers and *recompilation*
out of the per-request path. Under jit, every distinct input shape is a new
XLA compilation; a scoring service seeing arbitrary request sizes would
compile on the request path. This wrapper pads each request's row count up to
a fixed bucket (powers of two), so the set of compiled executables is small,
pre-warmable at startup, and shared across requests. Oversized requests are
chunked through the largest bucket.

The reference has no analogue (sklearn predict is shape-agnostic); this is
pure TPU-serving design.
"""
from __future__ import annotations

import numpy as np

from bodywork_tpu.models.base import Regressor
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.predictor")

DEFAULT_BUCKETS = (1, 8, 64, 512, 4096)


class PaddedPredictor:
    """Bucket-padding predictor over ``model.predict``.

    Subclasses may override :meth:`_predict_padded` to change the execution
    backend (e.g. sharded over a mesh) while reusing the bucket/pad/chunk
    logic here.
    """

    def __init__(self, model: Regressor, buckets: tuple[int, ...] = DEFAULT_BUCKETS):
        assert model.params is not None, "cannot serve an unfitted model"
        self.model = model
        self.buckets = tuple(sorted(buckets))

    def _predict_padded(self, Xp: np.ndarray) -> np.ndarray:
        """Run the model on an exactly-bucket-sized batch."""
        return np.asarray(self.model.predict(Xp))

    def warmup(self, n_features: int | None = None) -> None:
        """Compile every bucket shape before taking traffic (startup cost,
        analogous to the reference's load-model-at-boot — ``stage_2:113``).

        The feature dimension defaults to the fitted model's own, so the
        shapes compiled here are exactly the request-path shapes.
        """
        if n_features is None:
            n_features = self.model.n_features or 1
        for b in self.buckets:
            self._predict_padded(np.zeros((b, n_features), dtype=np.float32))
        log.info(
            f"warmed up predict buckets {self.buckets} (n_features={n_features})"
        )

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def predict(self, X: np.ndarray) -> np.ndarray:
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 1:
            X = X[:, None]
        n = X.shape[0]
        max_bucket = self.buckets[-1]
        if n > max_bucket:
            # chunk through the largest compiled bucket
            parts = [
                self.predict(X[i : i + max_bucket]) for i in range(0, n, max_bucket)
            ]
            return np.concatenate(parts)
        b = self._bucket_for(n)
        if b != n:
            Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
            Xp[:n] = X
        else:
            Xp = X
        return self._predict_padded(Xp)[:n]
