"""Model hot-reload for the scoring service (beyond-parity; SURVEY §3.2).

The reference loads its model once at boot (``stage_2_serve_model.py:57-65,
113``): serving a new day's model requires the orchestrator to re-deploy
the whole service. Here a :class:`CheckpointWatcher` polls the store for
the checkpoint serving SHOULD run — the registry's ``production`` alias
when one exists (``bodywork_tpu.registry``: only gate-promoted models
ever take traffic, and a one-op rollback flips the alias so the next
poll swaps the previous production back in), falling back to the newest
date-keyed artefact under ``models/`` on a registry-less store (the
original behavior, byte-identical). The target key plus the backend's
version token are compared, so an in-place overwrite of the same key is
also seen — the watcher loads and warms the replacement OFF the request
path, then swaps it into the running
:class:`~bodywork_tpu.serve.app.ScoringApp` atomically. A k8s serve
Deployment therefore lives across days instead of being re-rolled per
retrain.
"""
from __future__ import annotations

import threading

from bodywork_tpu.models.checkpoint import (
    load_model,
    resolve_serving_key,
    resolve_serving_state,
)
from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore
from bodywork_tpu.store.schema import MODELS_PREFIX
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.reload")

#: ``served_key`` sentinel for "the caller is serving NO model" (degraded
#: boot on an empty store): the watcher must treat whatever checkpoint it
#: first finds as NEW. Passing None instead would make the constructor
#: snapshot ``latest()`` as already-served — and a checkpoint published
#: between the caller's failed lookup and construction would never load.
NOTHING_SERVED = object()


class CheckpointWatcher:
    """Polls ``store`` for a newer model checkpoint and hot-swaps it into
    ``app``. Load + predictor build + bucket warmup all happen on the
    watcher thread; the request path only ever sees the finished swap.
    """

    def __init__(
        self,
        app,
        store: ArtefactStore,
        poll_interval_s: float = 30.0,
        mesh_data: int | None = None,
        engine: str = "xla",
        served_key: str | None = None,
        buckets: tuple[int, ...] | None = None,
        slo_watchdog=None,
        dtype: str = "float32",
        mesh_model: int = 1,
        tune_controller=None,
    ):
        # one watcher drives every replica app: replicas share read-only
        # model state by design, so one load+warm serves them all
        self.apps = list(app) if isinstance(app, (list, tuple)) else [app]
        self.store = store
        self.poll_interval_s = poll_interval_s
        self.mesh_data = mesh_data
        #: tensor-parallel mesh axis for swapped-in predictors: a swap
        #: re-places the new checkpoint's params over the SAME mesh shape
        #: the boot predictor used, so the AOT executable cache re-binds
        #: instead of recompiling (same-mesh swaps are compile-free)
        self.mesh_model = mesh_model
        self.engine = engine
        #: the serving dtype (serve.predictor.SERVE_DTYPES): a swapped-in
        #: checkpoint re-runs the quantization shadow gate for it, so a
        #: retrain whose quantized variant regresses falls back to f32
        #: on THAT swap without touching the dtype choice for later ones
        self.dtype = dtype
        # the caller's EXPLICIT bucket narrowing (pipeline spec), if any.
        # Distinct from the booted predictor's buckets, which may just be
        # an engine's default policy that should not survive an
        # engine-changing swap (see check_once).
        self.buckets = tuple(buckets) if buckets else None
        # what the app serves now: (key, version token). ``served_key``
        # should be the key the caller actually LOADED — snapshotting
        # latest() here instead would mark a checkpoint published during
        # the caller's (slow, compile-heavy) warmup as already served and
        # skip it until the next one lands. A caller serving NOTHING
        # passes the NOTHING_SERVED sentinel for the same reason.
        self._current: tuple | None = None
        if served_key is NOTHING_SERVED:
            served_key = None
        elif served_key is None:
            try:
                served_key, _source = resolve_serving_key(store)
            except ArtefactNotFound:
                served_key = None
            except Exception as exc:  # e.g. a corrupt alias document:
                # snapshot nothing-served; polls retry resolution
                log.error(
                    f"serving-key resolution failed at watcher init "
                    f"(polls will retry): {exc!r}"
                )
                served_key = None
        if served_key is not None:
            self._current = (served_key, store.version_token(served_key))
        # whether THIS watcher flagged the apps degraded for a serving-key
        # resolution failure — a healed resolution that needs no swap must
        # clear exactly that flag (a swap clears it via swap_model anyway)
        self._resolve_degraded = False
        #: the canary the apps currently serve: (key, token, fraction,
        #: seed) — compared against the alias document's slot each poll
        self._current_canary: tuple | None = None
        #: optional SLO watchdog (ops/slo.py), driven once per poll —
        #: the loop that makes canary abort/promote automatic
        self.slo_watchdog = slo_watchdog
        #: optional online tune controller (tune/online.py), driven once
        #: per poll right after the watchdog — model releases and config
        #: releases share one cadence. Wiring here (not in the
        #: controller) gives it the ladder-apply path below.
        self.tune_controller = tune_controller
        if tune_controller is not None and tune_controller.apply_buckets is None:
            tune_controller.apply_buckets = self.apply_bucket_ladder
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="checkpoint-watcher", daemon=True
        )

    def check_once(self) -> bool:
        """One poll: swap if the store resolves a DIFFERENT checkpoint to
        serve — the registry's ``production`` alias when one exists
        (a candidate that fails the promotion gate never moves the alias
        and therefore never goes live; a rollback moves it back and the
        next poll swaps accordingly), else the newest date-keyed
        checkpoint. Returns whether a swap happened. Load/warm errors —
        and a corrupt alias document — are logged and swallowed: the
        service keeps answering with the current model (flagged DEGRADED
        in /healthz and the state gauge, so a stuck reload is visible)
        and retries on the next poll (a half-written checkpoint must
        never take the service down)."""
        try:
            key, source, canary_state, canary_dangling = (
                resolve_serving_state(self.store)
            )
        except ArtefactNotFound:
            self._poll_watchdog()
            self._poll_tuner()
            return False
        except Exception as exc:
            # e.g. registry.records.RegistryCorrupt: falling back to
            # latest here could put an UNGATED checkpoint live — keep
            # serving what we serve and let the next poll retry. SAY so:
            # while resolution fails, promotions/rollbacks cannot take
            # effect, and that must show in /healthz + the state gauge
            log.error(f"serving-key resolution failed (will retry): {exc!r}")
            if not self._resolve_degraded:
                self._resolve_degraded = True
                for app in self.apps:
                    app.set_degraded(
                        "serving-key resolution failing; promotions and "
                        "rollbacks are not taking effect"
                    )
            return False
        if self._resolve_degraded:
            # resolution healed; if a swap is also due, swap_model clears
            self._resolve_degraded = False
            for app in self.apps:
                app.clear_degraded()
        swapped = False
        candidate = (key, self.store.version_token(key))
        if candidate != self._current:
            try:
                model, model_date = load_model(self.store, key)
                predictor = self._build_swap_predictor(model)
            except Exception as exc:
                log.error(f"hot reload of {key} failed (will retry): {exc!r}")
                # keep serving the last-good model, but SAY so: the
                # degraded flag rides /healthz +
                # bodywork_tpu_serve_degraded_state until a later poll
                # swaps successfully (swap_model clears it)
                for app in self.apps:
                    app.set_degraded(
                        f"hot reload of {key} failed; serving last-good model"
                    )
                self._sync_canary(canary_state, canary_dangling)
                self._poll_watchdog()
                self._poll_tuner()
                return False
            # swap_model is an atomic bundle swap; for apps with a request
            # coalescer it ALSO drains the batch queue before returning.
            # Mid-flight batched traffic stays consistent either way:
            # every coalesced submission carries the served bundle it was
            # enqueued against, and a batch only ever groups one bundle's
            # submissions (serve.batcher._take_batch_locked) — a swap
            # landing mid-queue splits old-model and new-model rows into
            # separate device calls, never one mixed batch.
            bounds = self._record_bounds(key)
            for app in self.apps:
                app.swap_model(model, model_date, predictor,
                               model_key=key, model_source=source,
                               model_bounds=bounds)
            self._current = candidate
            swapped = True
        self._sync_canary(canary_state, canary_dangling)
        self._poll_watchdog()
        self._poll_tuner()
        return swapped

    def _build_swap_predictor(self, model):
        """Build + warm a predictor for a model being swapped in (the
        production reload and the canary load share this, so a canary
        serves through exactly the engine selection — and, for a
        quantized dtype, the shadow quality gate — production does).
        Every bucket is compiled AND executed here, on the watcher
        thread, BEFORE the swap pointer publishes: with the process-wide
        executable cache a same-architecture swap finds its executables
        already compiled (zero compile work), and a new architecture
        pays its compiles here, never on a scoring request."""
        from bodywork_tpu.serve.server import (
            build_serving_predictor,
            resolve_engine,
        )

        # Bucket policy for the swapped-in predictor, in priority order:
        # 1. the caller's explicit list (a reload must not widen the
        #    compiled-shape set the spec narrowed);
        # 2. same resolved engine as currently served -> keep the
        #    current bucket set (shape-set stability across swaps);
        # 3. engine CHANGED across the swap (engine='auto' resolving
        #    differently for the new checkpoint, e.g. narrow->wide MLP
        #    flipping xla->pallas) -> let the new engine apply its own
        #    default policy. Inheriting the old engine's buckets here
        #    would e.g. hand the Pallas kernel sub-ROW_TILE buckets
        #    that all pad to the same program — several duplicate
        #    compiles per warmup for nothing.
        current = self.apps[0].predictor  # None on a degraded boot
        old_resolved = (
            resolve_engine(self.engine, current.model, self.mesh_data,
                           mesh_model=self.mesh_model)
            if current is not None
            else None  # nothing served yet: nothing to inherit
        )
        new_resolved = resolve_engine(self.engine, model, self.mesh_data,
                                      mesh_model=self.mesh_model)
        if self.buckets is not None:
            swap_buckets = self.buckets
        elif current is not None and new_resolved == old_resolved:
            swap_buckets = current.buckets
        else:
            swap_buckets = None
        # ONE composition point for every dtype (build_serving_predictor
        # collapses to plain build_predictor for float32): a swapped-in
        # checkpoint goes through exactly the selection — and, for a
        # quantized dtype, the shadow quality gate — boot did
        predictor, _served_dtype = build_serving_predictor(
            self.store, model, self.mesh_data, new_resolved,
            buckets=swap_buckets, dtype=self.dtype,
            mesh_model=self.mesh_model,
        )
        if predictor is None:
            # plain xla engine with no bucket narrowing: the app-level
            # default predictor (its own default bucket policy)
            from bodywork_tpu.serve.predictor import PaddedPredictor

            predictor = PaddedPredictor(model)
        # warm every bucket BEFORE the swap: the first request after
        # reload must not pay the new model's compiles
        predictor.warmup()
        return predictor

    def _record_bounds(self, key: str):
        """The registry record's prediction-sanity band for a checkpoint
        (None when absent/registry-less) — one record GET per swap, off
        the request path. Delegates to the one shared lookup so boot and
        reload resolve bounds under identical rules."""
        from bodywork_tpu.serve.server import _registry_bounds

        return _registry_bounds(self.store, key)

    def _sync_canary(self, state: dict | None, dangling_reason: str | None) -> None:
        """Reconcile the apps' canary bundle with the alias document's
        slot: load+warm a newly-configured canary OFF the request path,
        clear a retired one, and REPAIR a dangling slot (stale canary
        pointing at a deleted/rejected checkpoint — a crashed watchdog's
        debris) with one CAS + a repair lineage event so boot and every
        later poll stop tripping over it."""
        if dangling_reason is not None:
            log.warning(
                f"dangling canary slot ignored ({dangling_reason}); "
                "serving production only"
            )
            try:
                from bodywork_tpu.registry import ModelRegistry

                ModelRegistry(self.store).canary_repair(reason=dangling_reason)
            except Exception as exc:
                log.error(f"canary slot repair failed (will retry): {exc!r}")
            state = None
        if state is None:
            if (
                self._current_canary is not None
                or self.apps[0].canary_key is not None
            ):
                for app in self.apps:
                    app.clear_canary()
                self._current_canary = None
            return
        desired = (
            state["key"], self.store.version_token(state["key"]),
            state["fraction"], state["seed"],
        )
        if desired == self._current_canary:
            return
        try:
            model, model_date = load_model(self.store, state["key"])
            predictor = self._build_swap_predictor(model)
        except Exception as exc:
            # a half-written canary checkpoint must not take the service
            # down OR the production stream with it: keep serving, retry
            # next poll
            log.error(
                f"canary load of {state['key']} failed (will retry): {exc!r}"
            )
            return
        for app in self.apps:
            app.set_canary(
                model, model_date, predictor, model_key=state["key"],
                fraction=state["fraction"], seed=state["seed"],
                bounds=state.get("bounds"),
            )
        self._current_canary = desired

    def apply_bucket_ladder(self, buckets: tuple) -> None:
        """Swap the SERVED predictor onto a new bucket ladder without
        changing the model — the online tune controller's ladder-apply
        path. The current checkpoint is re-loaded and a predictor over
        ``buckets`` is built + warmed on the calling (watcher) thread
        before the atomic swap, exactly like a model reload: with the
        process-wide AOT executable cache, a ladder whose rungs were
        ever compiled for this architecture swaps in with ZERO compile
        work, and a genuinely new rung pays its compile here, never on
        a scoring request. The explicit ladder is pinned as this
        watcher's bucket policy so later model swaps keep it."""
        key = self.apps[0].model_key
        if key is None:
            raise RuntimeError("no model is served; cannot apply a ladder")
        model, model_date = load_model(self.store, key)
        self.buckets = tuple(buckets)
        predictor = self._build_swap_predictor(model)
        bounds = self._record_bounds(key)
        source = self.apps[0].model_source
        # identity-preserving swap: same model, same key/date/source ->
        # same response templates, so bodies stay byte-identical across
        # the ladder change (the mid-flight apply test pins this)
        for app in self.apps:
            app.swap_model(model, model_date, predictor,
                           model_key=key, model_source=source,
                           model_bounds=bounds)
        self._current = (key, self.store.version_token(key))
        log.info(f"bucket ladder applied live: {tuple(buckets)}")

    def _poll_tuner(self) -> None:
        """Drive the online tune controller once per poll. Sibling of
        :meth:`_poll_watchdog`; a controller error must never kill
        model reloads."""
        if self.tune_controller is None:
            return
        try:
            self.tune_controller.poll()
        except Exception as exc:
            log.error(f"online tune poll failed: {exc!r}")

    def _poll_watchdog(self) -> None:
        """Drive the SLO watchdog once per poll. A promote re-anchors
        the watcher's current-production marker so the next poll does
        not redundantly reload the checkpoint the apps already serve
        warm."""
        if self.slo_watchdog is None:
            return
        try:
            action = self.slo_watchdog.poll()
        except Exception as exc:  # the watchdog must never kill reloads
            log.error(f"SLO watchdog poll failed: {exc!r}")
            return
        if action == "promote":
            key = self.apps[0].model_key
            if key is not None:
                self._current = (key, self.store.version_token(key))
            self._current_canary = None
        elif action == "abort":
            self._current_canary = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.check_once()
            except Exception as exc:  # a poll error must not kill the loop
                log.error(f"checkpoint watch poll failed: {exc!r}")

    def start(self) -> "CheckpointWatcher":
        self._thread.start()
        log.info(
            f"watching the serving target every "
            f"{self.poll_interval_s:.0f}s (registry production alias "
            f"when one exists, else newest under {MODELS_PREFIX})"
        )
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:
            self._thread.join(timeout=10)
