"""Shared-memory row queue between front-end and dispatcher processes.

The disaggregated serving split (``serve.frontend`` / ``serve.dispatch``)
puts HTTP parsing and admission in N cheap front-end processes and the
device in exactly ONE dispatcher — so the dispatcher's coalescer forms
batches from the union of every front-end's rows instead of each
SO_REUSEPORT worker fragmenting its own. This module is the channel
between them: a fixed pool of fixed-stride shared-memory row slots plus
small control queues.

Data plane (shared ``multiprocessing`` memory, allocated once by the
fleet supervisor and inherited by every process):

- ``data``   — request rows: ``slots x slot_floats`` little-endian f32.
  A front-end writes a request's rows into its slot ONCE; the dispatcher
  reads them **zero-copy** as a numpy view straight into the predictor.
- ``reply``  — predictions, written by the dispatcher, read by the
  owning front-end.
- ``meta``   — per-slot int64 header: generation, kind, row/feature
  counts, reply status.
- ``text``   — per-slot strings: the request's trace id (the trace ctx
  that rides the queue) and the reply's answering-bundle identity
  (model key / info / date) — what the front-end needs to render a
  byte-identical response without ever holding a model.
- ``stamps`` — per-slot ``time.monotonic()`` enqueue timestamps
  (CLOCK_MONOTONIC is machine-wide on Linux, so the dispatcher can
  subtract them) behind the ``bodywork_tpu_rowqueue_handoff_seconds``
  histogram.

Control plane (lock-free by design — see :class:`_SpscRing` for why a
``multiprocessing.Queue`` CANNOT carry it):

- ``sub_rings[i]`` — per-front-end single-producer/single-consumer
  descriptor ring (front-end *i* pushes ``gen<<20 | slot``, the
  dispatcher pops; only 8 bytes of descriptor cross, never rows).
- ``rep_rings[i]`` — the completion ring back (dispatcher pushes, the
  front-end's reader thread pops).
- ``up`` / ``epoch`` — the dispatcher-liveness channel the supervisor
  owns: ``up`` gates new submissions (a front-end answers 503 +
  Retry-After instead of enqueueing into a dead dispatcher), and an
  ``epoch`` bump fails every in-flight wait immediately so a dispatcher
  crash degrades front-ends instead of wedging them. Both are
  ``RawValue`` — a lock-guarded ``Value`` read on every request would
  put a shared lock on the hot path AND hand a SIGKILLed holder a way
  to wedge the fleet.

Crash safety is generation-based: a slot's ``gen`` is bumped at every
allocation, every descriptor carries the gen it was enqueued under, and
both sides drop mismatches. A respawned dispatcher can therefore drain
stale descriptors harmlessly, and a late reply to a slot the front-end
already failed (epoch bump) is ignored — torn responses are impossible
by construction.

Slot allocation is front-end-only (the free list is guarded by one
shared lock); the dispatcher never allocates, so a dispatcher crash can
never leak slots it didn't own.
"""
from __future__ import annotations

import json
import threading
import time

import numpy as np

from bodywork_tpu.obs import get_registry
from bodywork_tpu.utils.logging import get_logger

log = get_logger("serve.rowqueue")

__all__ = [
    "DispatcherUnavailable",
    "RowQueue",
    "RowQueueClient",
    "RowQueueServer",
    "SlotsExhausted",
]

#: default slot pool: bounds the service-wide in-flight row-queue work.
#: Sized above the default admission budget (512) so admission — not the
#: queue — is the normal backpressure boundary.
DEFAULT_SLOTS = 1024
#: f32 capacity per slot: matches the largest predictor bucket (4096
#: rows x 1 feature), so any request the bench offers fits one slot
DEFAULT_SLOT_FLOATS = 4096

#: request kinds (meta K_KIND)
KIND_SINGLE = 1
KIND_BATCH = 2

#: reply statuses beyond plain HTTP codes: the dispatcher answers with
#: the HTTP status the in-process path would have used (200/500/503),
#: and the front-end renders the matching byte-identical body
STATUS_PENDING = 0

#: per-slot int64 meta fields
_M_GEN = 0
_M_KIND = 1
_M_ROWS = 2
_M_FEATURES = 3
_M_STATUS = 4
_M_REPLY_ROWS = 5
#: 1 + owning frontend_id while allocated, 0 while free — written only
#: under the free-list lock, so the supervisor can reclaim a SIGKILLed
#: front-end's slots (reclaim_frontend) without racing live allocators
_M_OWNER = 6
META_INTS = 8

#: per-slot text region: trace id (request) + answering-bundle identity
#: (reply), JSON-encoded so None survives the trip
REQ_TEXT_BYTES = 64
REP_TEXT_BYTES = 448
TEXT_BYTES = REQ_TEXT_BYTES + REP_TEXT_BYTES


#: descriptor encoding: ``gen << _SLOT_BITS | slot``. 20 bits of slot
#: index (1M slots — far above any sane pool) leaves 43 bits of
#: generation counter in the int64 ring payload: centuries of churn.
_SLOT_BITS = 20
_SLOT_MASK = (1 << _SLOT_BITS) - 1


class _SpscRing:
    """Single-producer/single-consumer int64 ring in shared memory.

    The control plane deliberately refuses ``multiprocessing.Queue`` (or
    ``Pipe``): a Queue reader holds the queue's shared rlock for the
    WHOLE blocking ``get`` — the dispatcher polls constantly, so a
    SIGKILL lands inside the critical section with near certainty,
    orphans the lock, and every respawned dispatcher inherits a channel
    it can never read. (A Pipe has no lock but a kill mid-``recv`` tears
    the byte stream for every successor.) Here the only shared state is
    a data array and two monotonic cursors: a push stores the payload
    FIRST and publishes by advancing ``tail`` LAST, so a kill at any
    instruction leaves the ring consistent — an entry is either fully
    visible or not there at all. A respawned process just keeps
    consuming from ``head``.

    Two caveats the callers own:

    - **Single producer means ONE THREAD.** The payload-then-tail
      publish protocol is safe against a concurrent consumer, not
      against a second producer: two threads that read the same tail
      overwrite each other's payload and advance it once, silently
      dropping an entry. The client serializes its HTTP handler
      threads through ``RowQueueClient._lock`` and the server
      serializes its serve-loop/coalescer threads through
      ``RowQueueServer._lock`` — any new producer call site must take
      the owning side's lock.
    - **Cross-process ordering assumes x86-TSO.** ctypes RawArray
      writes are plain stores with no fence; total store order is what
      makes the consumer see the payload before the advanced tail. On
      weakly-ordered architectures (aarch64) a consumer in another
      process could observe the new tail first and read a stale
      descriptor. The descriptor's generation guard downgrades that
      from a torn response to a dropped request (both sides discard
      gen mismatches), but a port to ARM should publish ``tail``
      through a fencing primitive instead.
    """

    __slots__ = ("data", "pos", "cap")

    def __init__(self, ctx, capacity: int):
        self.data = ctx.RawArray("q", capacity)
        # pos[0] = head (consumer cursor), pos[1] = tail (producer
        # cursor); both monotonic, entry i lives at data[i % cap]
        self.pos = ctx.RawArray("q", 2)
        self.cap = capacity

    def push(self, value: int) -> bool:
        tail = self.pos[1]
        if tail - self.pos[0] >= self.cap:
            return False  # full (unreachable when cap > slot pool size)
        self.data[tail % self.cap] = value
        self.pos[1] = tail + 1  # publish AFTER the payload store
        return True

    def pop(self) -> int | None:
        head = self.pos[0]
        if self.pos[1] <= head:
            return None
        value = self.data[head % self.cap]
        self.pos[0] = head + 1
        return int(value)


class DispatcherUnavailable(RuntimeError):
    """The dispatcher is down (or died mid-request): the front-end
    answers 503 + Retry-After; the supervisor's respawn heals it."""


class SlotsExhausted(RuntimeError):
    """No free row slot (or the request outgrows one slot): backpressure
    — the front-end sheds exactly as an admission-budget refusal."""


class RowQueue:
    """The shared handles, created ONCE by the fleet supervisor and
    passed to every front-end/dispatcher process at spawn (all members
    are picklable multiprocessing primitives)."""

    def __init__(
        self,
        ctx,
        frontends: int,
        slots: int = DEFAULT_SLOTS,
        slot_floats: int = DEFAULT_SLOT_FLOATS,
    ):
        if frontends < 1:
            raise ValueError(f"need >= 1 front-end, got {frontends}")
        if slots < 1 or slot_floats < 1:
            raise ValueError("slots and slot_floats must be >= 1")
        if slots > _SLOT_MASK:
            raise ValueError(
                f"slots must fit the {_SLOT_BITS}-bit descriptor field "
                f"(<= {_SLOT_MASK}), got {slots}"
            )
        self.frontends = frontends
        self.slots = slots
        self.slot_floats = slot_floats
        self.data = ctx.RawArray("f", slots * slot_floats)
        self.reply = ctx.RawArray("f", slots * slot_floats)
        self.meta = ctx.RawArray("q", slots * META_INTS)
        self.text = ctx.RawArray("c", slots * TEXT_BYTES)
        self.stamps = ctx.RawArray("d", slots)
        # free list: [0] = count, [1..] = LIFO stack of free slot indices
        self.free = ctx.Array("i", slots + 1)
        with self.free.get_lock():
            self.free[0] = slots
            for i in range(slots):
                self.free[1 + i] = i
        # a front-end can never have more than `slots` submissions in
        # flight, so slots + 1 ring entries can never fill
        self.sub_rings = [_SpscRing(ctx, slots + 1) for _ in range(frontends)]
        self.rep_rings = [_SpscRing(ctx, slots + 1) for _ in range(frontends)]
        #: 1 while a dispatcher is live with a loaded model (the
        #: dispatcher sets it; the supervisor clears it at death)
        self.up = ctx.RawValue("i", 0)
        #: bumped by the supervisor at every dispatcher death: clients
        #: fail their in-flight waits the moment they observe a change
        self.epoch = ctx.RawValue("i", 0)

    def close(self) -> None:
        """Supervisor teardown hook. Everything here is plain shared
        memory — reclaimed with the last process holding it — so there
        is nothing to release eagerly; kept for symmetry with resource
        owners the supervisor tears down."""

    def reclaim_frontend(self, frontend_id: int) -> int:
        """Free every slot a dead front-end still owned (supervisor
        hook, called at the FIRST observation of a front-end death).

        A SIGKILLed front-end takes its ``_pending`` map with it, so
        the respawned client has no record of the slots the old process
        held — without this, every front-end crash permanently shrinks
        the shared pool until the service sheds everything. Ownership
        is recorded per-slot under the free-list lock (``_M_OWNER``),
        so the scan here cannot race a live allocator. Each reclaimed
        slot's generation is bumped first: a dispatcher still scoring
        it drops the reply on its gen guard, and stale descriptors in
        either ring become inert. Returns the number of slots freed."""
        views = _Views(self)
        freed = 0
        with self.free.get_lock():
            for slot in range(self.slots):
                if int(views.meta[slot, _M_OWNER]) != frontend_id + 1:
                    continue
                views.meta[slot, _M_GEN] += 1
                views.meta[slot, _M_OWNER] = 0
                self.free[0] += 1
                self.free[self.free[0]] = slot
                freed += 1
        return freed


class _Reply:
    """One completed submission, as the front-end renders it."""

    __slots__ = (
        "status", "predictions", "model_key", "model_info", "model_date",
    )

    def __init__(self, status, predictions, model_key, model_info,
                 model_date):
        self.status = status
        self.predictions = predictions
        self.model_key = model_key
        self.model_info = model_info
        self.model_date = model_date


class _Views:
    """Per-process numpy views over the shared regions (views cannot
    cross a spawn; each process rebuilds them once)."""

    def __init__(self, queue: RowQueue):
        self.data = np.frombuffer(queue.data, dtype=np.float32).reshape(
            queue.slots, queue.slot_floats
        )
        self.reply = np.frombuffer(queue.reply, dtype=np.float32).reshape(
            queue.slots, queue.slot_floats
        )
        self.meta = np.frombuffer(queue.meta, dtype=np.int64).reshape(
            queue.slots, META_INTS
        )
        self.text = np.frombuffer(queue.text, dtype=np.uint8).reshape(
            queue.slots, TEXT_BYTES
        )
        self.stamps = np.frombuffer(queue.stamps, dtype=np.float64)


def _write_text(view_row, offset: int, limit: int, blob: bytes) -> None:
    blob = blob[:limit]
    region = view_row[offset:offset + limit]
    region[: len(blob)] = np.frombuffer(blob, dtype=np.uint8)
    region[len(blob):] = 0


def _read_text(view_row, offset: int, limit: int) -> bytes:
    return bytes(view_row[offset:offset + limit]).rstrip(b"\x00")


class RowQueueClient:
    """The front-end side: allocate a slot, write rows once, enqueue the
    descriptor, and complete via a push callback when the dispatcher's
    reply lands (one reader thread per front-end process bridges the
    reply queue to callbacks — the same push shape as the coalescer's
    ``on_done``, so both HTTP engines wrap it the way they already wrap
    coalesced submissions)."""

    def __init__(self, queue: RowQueue, frontend_id: int):
        if not 0 <= frontend_id < queue.frontends:
            raise ValueError(
                f"frontend_id {frontend_id} out of range 0..{queue.frontends - 1}"
            )
        self.queue = queue
        self.frontend_id = frontend_id
        self._views = _Views(queue)
        self._lock = threading.Lock()
        #: slot -> (gen, on_done) for submissions awaiting a reply
        self._pending: dict[int, tuple[int, object]] = {}
        self._stopped = False
        self._epoch_seen = queue.epoch.value
        # accounting (the shed-before-parse proof reads rows_submitted)
        self.rows_submitted = 0
        self.requests_submitted = 0
        self.replies_received = 0
        self.failures = 0
        reg = get_registry()
        self._m_rows = reg.counter(
            "bodywork_tpu_rowqueue_rows_total",
            "Feature rows handed to the dispatcher over the shared "
            "row-queue, by front-end role",
        )
        self._m_wait = reg.histogram(
            "bodywork_tpu_rowqueue_wait_seconds",
            "Front-end submit -> dispatcher reply, whole round trip",
        )
        self._reader = threading.Thread(
            target=self._reader_loop, name=f"rowqueue-replies-{frontend_id}",
            daemon=True,
        )

    def start(self) -> "RowQueueClient":
        self._reader.start()
        return self

    def stop(self) -> None:
        self._stopped = True
        self._fail_pending(DispatcherUnavailable("front-end shutting down"))
        if self._reader.ident is not None:
            self._reader.join(timeout=5)

    # -- submit path ---------------------------------------------------------
    def dispatcher_up(self) -> bool:
        return self.queue.up.value == 1

    def _alloc_slot(self) -> int:
        free = self.queue.free
        with free.get_lock():
            count = free[0]
            if count <= 0:
                raise SlotsExhausted("no free row-queue slot")
            slot = free[count]  # stack top is free[count], count preceding
            free[0] = count - 1
            # ownership stamp, inside the lock: the supervisor's
            # dead-front-end reclaim scans owners under the same lock
            self._views.meta[slot, _M_OWNER] = self.frontend_id + 1
        return slot

    def _free_slot(self, slot: int) -> None:
        free = self.queue.free
        with free.get_lock():
            self._views.meta[slot, _M_OWNER] = 0
            free[0] += 1
            free[free[0]] = slot

    def submit(self, X, kind: int, on_done, trace_id: str | None = None) -> None:
        """Write one request's rows and enqueue it. ``on_done`` fires on
        the reader thread with a reply object (``status``,
        ``predictions``, answering-bundle identity) or an exception
        (:class:`DispatcherUnavailable` on a dispatcher death). Raises
        :class:`DispatcherUnavailable` / :class:`SlotsExhausted`
        synchronously when nothing was enqueued."""
        if self._stopped or self.queue.up.value != 1:
            raise DispatcherUnavailable("scoring dispatcher is not available")
        X = np.asarray(X, dtype=np.float32)
        if X.ndim == 0:
            X = X[None]
        n_rows = int(X.shape[0])
        n_features = int(X.shape[1]) if X.ndim == 2 else 1
        floats = n_rows * n_features
        if floats > self.queue.slot_floats:
            raise SlotsExhausted(
                f"request of {floats} values exceeds the "
                f"{self.queue.slot_floats}-value slot stride"
            )
        slot = self._alloc_slot()
        views = self._views
        meta = views.meta[slot]
        gen = int(meta[_M_GEN]) + 1
        meta[_M_GEN] = gen
        meta[_M_KIND] = kind
        meta[_M_ROWS] = n_rows
        meta[_M_FEATURES] = n_features
        meta[_M_STATUS] = STATUS_PENDING
        meta[_M_REPLY_ROWS] = 0
        views.data[slot, :floats] = X.ravel()
        _write_text(
            views.text[slot], 0, REQ_TEXT_BYTES,
            (trace_id or "").encode("ascii", "replace"),
        )
        views.stamps[slot] = time.monotonic()
        with self._lock:
            # the descriptor push stays inside the lock: werkzeug's
            # threaded engine calls submit from concurrent request
            # threads, and the sub ring is single-PRODUCER — two
            # unserialized pushes can read the same tail and silently
            # drop one descriptor (its handler would hang into the
            # rendezvous timeout and leak the slot)
            pushed = self.queue.sub_rings[self.frontend_id].push(
                (gen << _SLOT_BITS) | slot
            )
            if pushed:
                self._pending[slot] = (gen, on_done)
                self.requests_submitted += 1
                self.rows_submitted += n_rows
        if not pushed:  # pragma: no cover - ring cap exceeds the slot pool
            self._free_slot(slot)
            raise SlotsExhausted("row-queue descriptor ring full")
        self._m_rows.inc(n_rows)

    # -- reply path ----------------------------------------------------------
    def _reader_loop(self) -> None:
        ring = self.queue.rep_rings[self.frontend_id]
        idle_sleep = 0.0002
        while not self._stopped:
            epoch = self.queue.epoch.value
            if epoch != self._epoch_seen:
                # the supervisor observed a dispatcher death: every
                # in-flight wait fails NOW (503 + Retry-After at the
                # HTTP layer) instead of hanging into a client timeout
                self._epoch_seen = epoch
                self._fail_pending(
                    DispatcherUnavailable("scoring dispatcher died")
                )
            descriptor = ring.pop()
            if descriptor is None:
                # adaptive poll: sub-ms while traffic flows (replies
                # arrive well inside the coalescer window), backing off
                # toward 20ms when idle so an idle front-end costs ~none
                time.sleep(idle_sleep)
                idle_sleep = min(idle_sleep * 2, 0.02)
                continue
            idle_sleep = 0.0002
            slot = descriptor & _SLOT_MASK
            gen = descriptor >> _SLOT_BITS
            entry = None
            with self._lock:
                pending = self._pending.get(slot)
                if pending is not None and pending[0] == gen:
                    entry = self._pending.pop(slot)
            if entry is None:
                # a stale descriptor (the wait already failed on an
                # epoch bump, and the slot was freed then): drop it —
                # the gen guard makes late replies inert
                continue
            views = self._views
            meta = views.meta[slot]
            status = int(meta[_M_STATUS])
            n = int(meta[_M_REPLY_ROWS])
            predictions = np.array(views.reply[slot, :n])  # copy, then free
            blob = _read_text(views.text[slot], REQ_TEXT_BYTES, REP_TEXT_BYTES)
            try:
                model_key, model_info, model_date = json.loads(blob or b"[null, null, null]")
            except (ValueError, TypeError):
                model_key = model_info = model_date = None
            enqueued_at = float(views.stamps[slot])
            self._free_slot(slot)
            with self._lock:
                self.replies_received += 1
            self._m_wait.observe(time.monotonic() - enqueued_at)
            self._complete(
                entry[1],
                _Reply(status, predictions, model_key, model_info, model_date),
            )

    def _fail_pending(self, exc: Exception) -> None:
        with self._lock:
            failed = list(self._pending.items())
            self._pending.clear()
            self.failures += len(failed)
        for slot, (_gen, on_done) in failed:
            self._free_slot(slot)
            self._complete(on_done, exc)

    @staticmethod
    def _complete(on_done, outcome) -> None:
        try:
            on_done(outcome)
        except Exception as exc:  # a broken callback must not kill the reader
            log.error(f"rowqueue on_done callback failed: {exc!r}")

    def stats(self) -> dict:
        with self._lock:
            return {
                "dispatcher_up": self.dispatcher_up(),
                "requests_submitted": self.requests_submitted,
                "rows_submitted": self.rows_submitted,
                "replies_received": self.replies_received,
                "failures": self.failures,
                "in_flight": len(self._pending),
                "slots": self.queue.slots,
                "slots_free": int(self.queue.free[0]),
            }

    def transport_state(self) -> dict:
        """The /healthz transport block — same shape as
        ``netqueue.NetQueueClient.transport_state`` so operators read one
        schema whichever transport a front-end rides. The shm transport
        has no connection to lose (liveness is the supervisor-maintained
        ``up`` word) and never reconnects; its credit window is the
        shared slot pool."""
        with self._lock:
            in_flight = len(self._pending)
        return {
            "kind": "shm",
            "connected": self.dispatcher_up(),
            "reconnects": 0,
            "credit_window": self.queue.slots,
            "credits_in_flight": in_flight,
            "address": None,
            # the /healthz leadership section, shm analogue: no CAS
            # election runs on one host — the supervisor's respawn IS
            # the takeover, and the queue epoch (bumped once per
            # dispatcher death) plays the fence's monotonic role
            "leadership": {
                "role": "active" if self.dispatcher_up() else "down",
                "fence": int(self.queue.epoch.value),
                "lease_age_s": None,
                "takeovers_observed": int(self.queue.epoch.value),
            },
        }


class _Submission:
    """One dequeued request, dispatcher-side. ``X`` is a ZERO-COPY numpy
    view straight into the shared slot — valid until the reply is
    written (the owning front-end frees the slot only after that)."""

    __slots__ = ("slot", "gen", "frontend_id", "kind", "X", "trace_id")

    def __init__(self, slot, gen, frontend_id, kind, X, trace_id):
        self.slot = slot
        self.gen = gen
        self.frontend_id = frontend_id
        self.kind = kind
        self.X = X
        self.trace_id = trace_id


class RowQueueServer:
    """The dispatcher side: poll descriptors, hand out zero-copy row
    views, write replies. One instance per dispatcher process."""

    def __init__(self, queue: RowQueue):
        self.queue = queue
        self._views = _Views(queue)
        reg = get_registry()
        self._m_handoff = reg.histogram(
            "bodywork_tpu_rowqueue_handoff_seconds",
            "Front-end enqueue -> dispatcher dequeue across the shared "
            "row-queue (the cost of the disaggregation hop)",
            buckets=(0.00005, 0.0001, 0.0005, 0.001, 0.005, 0.025, 0.1, 0.5),
        )
        self._m_occupancy = reg.gauge(
            "bodywork_tpu_rowqueue_occupancy_ratio",
            "Allocated row slots / slot pool size (1.0 = the queue, not "
            "admission, is the backpressure boundary)",
        )
        self._m_depth = reg.gauge(
            "bodywork_tpu_rowqueue_depth",
            "Row-queue requests dequeued by the dispatcher and not yet "
            "replied to",
            aggregate="sum",
        )
        self._in_flight = 0
        self._next_ring = 0
        # reply() runs on TWO threads — the serve_forever loop (batch /
        # 503 / error / coalescer-saturated paths) and the coalescer's
        # dispatcher thread — and the rep rings are single-producer:
        # every reply (and the _in_flight accounting poll shares)
        # serializes through this lock
        self._lock = threading.Lock()

    def _pop_submission(self) -> tuple[int, int] | None:
        """One round-robin sweep over the front-ends' descriptor rings
        (rotating the start index so a chatty front-end cannot starve
        its siblings); ``(descriptor, frontend_id)`` or None."""
        n = self.queue.frontends
        for offset in range(n):
            i = (self._next_ring + offset) % n
            descriptor = self.queue.sub_rings[i].pop()
            if descriptor is not None:
                self._next_ring = (i + 1) % n
                return descriptor, i
        return None

    def poll(self, timeout_s: float = 0.2) -> _Submission | None:
        """Next live submission, or None (timeout / stale descriptor).
        Also refreshes the occupancy gauge — the scale-front-ends signal
        the runbook keys off."""
        used = self.queue.slots - int(self.queue.free[0])
        self._m_occupancy.set(used / self.queue.slots)
        deadline = time.monotonic() + timeout_s
        idle_sleep = 0.0002
        while True:
            popped = self._pop_submission()
            if popped is not None:
                break
            if time.monotonic() >= deadline:
                return None
            # same adaptive poll as the client reader: sub-ms under
            # load, ~2ms when idle (bounded by the poll timeout)
            time.sleep(idle_sleep)
            idle_sleep = min(idle_sleep * 2, 0.002)
        descriptor, frontend_id = popped
        slot = descriptor & _SLOT_MASK
        gen = descriptor >> _SLOT_BITS
        views = self._views
        meta = views.meta[slot]
        if int(meta[_M_GEN]) != gen:
            # a stale descriptor from before a front-end failure/respawn
            # cycle: the slot has moved on — never touch it
            return None
        self._m_handoff.observe(
            max(0.0, time.monotonic() - views.stamps[slot]),
            exemplar=(
                _read_text(views.text[slot], 0, REQ_TEXT_BYTES).decode(
                    "ascii", "replace"
                ) or None
            ),
        )
        n_rows = int(meta[_M_ROWS])
        n_features = int(meta[_M_FEATURES])
        flat = views.data[slot, : n_rows * n_features]
        X = flat if n_features == 1 else flat.reshape(n_rows, n_features)
        trace_id = _read_text(views.text[slot], 0, REQ_TEXT_BYTES).decode(
            "ascii", "replace"
        ) or None
        with self._lock:
            self._in_flight += 1
            self._m_depth.set(float(self._in_flight))
        return _Submission(slot, gen, frontend_id, int(meta[_M_KIND]), X,
                           trace_id)

    def reply(self, sub: _Submission, status: int, predictions=None,
              bundle=None) -> None:
        """Write one reply and signal the owning front-end. ``bundle``
        is the ANSWERING served bundle (post-firewall) — its identity is
        what the front-end splices into the response, keeping
        disaggregated bytes identical to in-process bytes.

        Thread-safe: the serve loop and the coalescer's dispatcher
        thread both land here, and the rep rings are single-producer —
        an unserialized pair of pushes to the same ring can drop a
        reply descriptor (the waiting front-end would hang into its
        rendezvous timeout), so the whole reply serializes through
        ``self._lock``."""
        views = self._views
        with self._lock:
            meta = views.meta[sub.slot]
            if int(meta[_M_GEN]) != sub.gen:
                return  # the front-end moved on; never write a stale slot
            n = 0
            if predictions is not None:
                arr = np.asarray(predictions, dtype=np.float32).ravel()
                n = int(arr.shape[0])
                views.reply[sub.slot, :n] = arr
            blob = b"[null, null, null]"
            if bundle is not None:
                encoded = json.dumps([
                    bundle.model_key, bundle.model_info, bundle.model_date,
                ]).encode()
                if len(encoded) <= REP_TEXT_BYTES:
                    blob = encoded
                else:  # never tear the region; degrade identity-less
                    log.error("reply bundle identity exceeds the text region")
            _write_text(views.text[sub.slot], REQ_TEXT_BYTES, REP_TEXT_BYTES,
                        blob)
            meta[_M_REPLY_ROWS] = n
            meta[_M_STATUS] = status
            self._in_flight = max(0, self._in_flight - 1)
            self._m_depth.set(float(self._in_flight))
            # cannot fill (ring cap exceeds the slot pool); a dead
            # front-end simply never consumes — shared memory doesn't error
            self.queue.rep_rings[sub.frontend_id].push(
                (sub.gen << _SLOT_BITS) | sub.slot
            )
