"""Scoring-service lifecycle.

Two run modes replace the reference's bare ``app.run`` (``stage_2:108-116``):

- :func:`serve_latest_model` — blocking production entrypoint: load the
  latest checkpoint from the store into TPU HBM, warm up the compiled
  buckets, serve.
- :class:`ServiceHandle` — in-process threaded server (werkzeug
  ``make_server``) with clean startup/shutdown, used by the local pipeline
  runner and the live-service tester so the whole daily loop can run in one
  process (the reference needs a k8s cluster for this).
"""
from __future__ import annotations

import itertools
import threading

from werkzeug.serving import make_server

from bodywork_tpu.models.checkpoint import load_model
from bodywork_tpu.serve.app import create_app
from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.utils.logging import get_logger
from bodywork_tpu.utils.shutdown import ShutdownRequested

log = get_logger("serve.server")

#: the serving front-end (HTTP server) registry: ``thread`` is the
#: werkzeug thread-per-request server (default — the closed-loop-proven
#: path), ``aio`` the asyncio event-loop front-end (``serve.aio``) built
#: for open-loop arrival-rate load. Kept in sync with ``cli serve
#: --server-engine`` choices and bench config 9 by a guard test
#: (tests/test_aio.py) — a front-end that exists in only some of the
#: three tables would either be unreachable or unmeasured.
SERVER_ENGINES = ("thread", "aio")


class RoundRobinApp:
    """WSGI front alternating requests across N replica apps.

    The local stand-in for the k8s Service load-balancing across the
    reference's 2 Deployment replicas (``bodywork.yaml:40-42``): replicas
    are stateless with read-only model state, so a request is served
    identically by any of them; this front just guarantees every replica
    actually takes traffic in local runs and tests.
    """

    def __init__(self, apps):
        assert apps, "need at least one replica app"
        self.apps = list(apps)
        self._counter = itertools.count()

    def __call__(self, environ, start_response):
        app = self.apps[next(self._counter) % len(self.apps)]
        return app(environ, start_response)

    def test_client(self):
        """Werkzeug test client over the front (same shape as
        ``Flask.test_client`` — what ``InProcessScoringClient`` needs)."""
        from werkzeug.test import Client

        return Client(self)


class ServiceHandle:
    """A scoring service running on a background thread."""

    def __init__(self, app, host: str = "127.0.0.1", port: int = 5000):
        # port=0 lets the OS pick a free port (tests / concurrent pipelines)
        self._server = make_server(host, port, app, threaded=True)
        self.app = app  # the served WSGI app (round-robin front or single)
        self.host = host
        self.port = self._server.server_port
        self._cleanups: list = []
        # poll_interval bounds how long shutdown() blocks (socketserver's
        # serve_forever only notices the shutdown flag between polls)
        self._thread = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.005),
            name="scoring-service",
            daemon=True,
        )

    def add_cleanup(self, fn) -> None:
        """Run ``fn`` on :meth:`stop` (e.g. a checkpoint watcher's stop)."""
        self._cleanups.append(fn)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/score/v1"

    def start(self) -> "ServiceHandle":
        self._thread.start()
        log.info(f"scoring service listening on {self.url}")
        return self

    def serve_forever(self) -> None:
        """Serve in the calling thread (pod-entrypoint mode): an unhandled
        error in the serve loop propagates, so a crashed service exits
        non-zero instead of reporting success to its supervisor."""
        log.info(f"scoring service listening on {self.url}")
        self._server.serve_forever()

    def wait(self) -> None:
        """Block until the server thread exits."""
        self._thread.join()

    def stop(self) -> None:
        for fn in self._cleanups:
            fn()
        self._server.shutdown()
        # in serve_forever mode the background thread was never started
        if self._thread.ident is not None:
            self._thread.join(timeout=10)
        log.info("scoring service stopped")

    def __enter__(self) -> "ServiceHandle":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


#: minimum hidden width at which ``engine="auto"`` picks the Pallas kernel.
#: Measured regime split (BENCH_DEV_r03 config 4 vs 6): at width 64 the
#: XLA apply beat the kernel (2.47 vs 2.77 ms/1k-row batch) — sub-lane
#: widths pad to 128 and the kernel's fixed overhead dominates; at width
#: 1024 the kernel's VMEM-resident weights win. The crossover sits between;
#: 256 (two lane-widths) is the conservative cut until a finer sweep moves it.
PALLAS_AUTO_MIN_WIDTH = 256


def resolve_engine(
    engine: str,
    model,
    mesh_data: int | None = None,
    platform: str | None = None,
    mesh_model: int = 1,
) -> str:
    """Resolve ``engine="auto"`` to the faster engine for the regime:
    the fused Pallas kernel only ever wins for wide MLPs on a real TPU
    (see :data:`PALLAS_AUTO_MIN_WIDTH`); everything else serves through
    the XLA apply. Explicit engine choices pass through untouched."""
    if engine != "auto":
        return engine
    from bodywork_tpu.models.mlp import MLPRegressor

    if (mesh_data and mesh_data > 1) or mesh_model > 1:
        return "xla"  # the kernel is single-device; the mesh path is XLA
    if not isinstance(model, MLPRegressor):
        return "xla"
    if platform is None:
        import jax

        platform = jax.devices()[0].platform
    if platform != "tpu":
        return "xla"  # off-TPU the kernel runs in the interpreter
    widths = [
        layer["w"].shape[1] for layer in model.params["net"]["layers"][:-1]
    ]
    if widths and min(widths) >= PALLAS_AUTO_MIN_WIDTH:
        return "pallas"
    return "xla"


def quantized_engine_for(engine: str, dtype: str) -> str:
    """Map a (resolved base engine, serving dtype) pair onto the engine
    variant that implements it: ``xla``+bf16 -> ``xla-bf16``, ``xla``+
    int8 -> ``xla-int8``, ``pallas``+bf16/int8 -> the kernel variants.
    Explicit quantized engine choices (``--engine xla-bf16``) may not be
    combined with a CONTRADICTING ``--dtype``."""
    from bodywork_tpu.serve.predictor import SERVE_DTYPES

    if dtype not in SERVE_DTYPES:
        raise ValueError(
            f"unknown serving dtype {dtype!r}; expected one of {SERVE_DTYPES}"
        )
    if dtype == "float32":
        return engine
    variants = {
        ("xla", "bfloat16"): "xla-bf16",
        ("xla", "int8"): "xla-int8",
        ("pallas", "bfloat16"): "pallas-bf16",
        ("pallas", "int8"): "pallas-int8",
    }
    if engine in variants.values():
        # an explicit quantized engine: --dtype must agree with it
        implied = "bfloat16" if engine.endswith("bf16") else "int8"
        if implied != dtype:
            raise ValueError(
                f"--engine {engine} contradicts --dtype {dtype}"
            )
        return engine
    variant = variants.get((engine, dtype))
    if variant is None:
        raise ValueError(
            f"engine {engine!r} has no {dtype} variant; use engine "
            "'xla' or 'pallas' with --dtype"
        )
    return variant


def build_predictor(model, mesh_data: int | None = None, engine: str = "xla",
                    buckets: tuple[int, ...] | None = None,
                    mesh_model: int = 1):
    """The predictor for a (resolved) engine choice, or ``None`` for the
    app's single-device bucketed default. Shared by boot-time serving and
    the hot-reload watcher so a swapped-in model goes through exactly the
    engine selection the booted one did.

    ``buckets`` narrows the compiled shape set for the bucketed engines —
    the same knob the app's default predictor honours, threaded here so a
    pipeline spec's explicit bucket list is never silently ignored when a
    non-default engine is selected (each engine keeps its own default
    bucket policy when unset).

    ``mesh_data``/``mesh_model`` > 1 serve through a ``data x model``
    device mesh: MLP checkpoints get the AOT-cached
    :class:`~bodywork_tpu.parallel.ShardedMLPPredictor` (Megatron
    weight sharding on ``model``, rows split on ``data``); other model
    classes serve data-parallel (their params are too small to split —
    a requested ``mesh_model`` > 1 degrades to the data axis with a
    warning rather than crash-looping a pod whose fleet-wide env knob
    outlives any one checkpoint)."""
    engine = resolve_engine(engine, model, mesh_data, mesh_model=mesh_model)
    use_mesh = bool(mesh_data and mesh_data > 1) or mesh_model > 1
    predictor = None
    if engine in ("pallas", "pallas-bf16", "pallas-int8"):
        import jax

        from bodywork_tpu.models.mlp import MLPRegressor
        from bodywork_tpu.serve.predictor import PallasMLPPredictor

        if use_mesh:
            raise ValueError(
                f"engine={engine!r} is single-device; drop --mesh-data/"
                "--mesh-model"
            )
        if not isinstance(model, MLPRegressor):
            raise ValueError(
                f"engine={engine!r} serves MLP models; latest is {model.info}"
            )
        interpret = jax.devices()[0].platform != "tpu"
        if interpret:
            log.warning(
                f"engine={engine!r} on a non-TPU backend runs the kernel "
                "in the (slow) Pallas interpreter — use engine='xla' "
                "unless you are testing the kernel itself"
            )
        kernel_dtype = {
            "pallas-bf16": "bfloat16", "pallas-int8": "int8",
        }.get(engine)
        predictor = PallasMLPPredictor(
            model, buckets=buckets, interpret=interpret,
            compute_dtype=kernel_dtype,
        )
    elif engine in ("xla-bf16", "xla-int8"):
        from bodywork_tpu.serve.predictor import (
            BF16MLPPredictor,
            Int8MLPPredictor,
        )

        if use_mesh:
            raise ValueError(
                f"engine={engine!r} is single-device; drop --mesh-data/"
                "--mesh-model"
            )
        # never chosen by "auto": trading prediction precision for
        # throughput is an explicit caller decision (and --dtype routes
        # it through the shadow quality gate first)
        cls = BF16MLPPredictor if engine == "xla-bf16" else Int8MLPPredictor
        predictor = cls(model, buckets=buckets)
    elif engine == "xla":
        if buckets and not use_mesh:
            # an explicit bucket list must never be silently ignored, so
            # the plain engine materialises the bucketed default here
            # rather than returning None and hoping the caller re-applies
            from bodywork_tpu.serve.predictor import PaddedPredictor

            predictor = PaddedPredictor(model, buckets)
    else:
        raise ValueError(f"unknown serving engine {engine!r}")
    if use_mesh:
        import jax

        from bodywork_tpu.models.mlp import MLPRegressor
        from bodywork_tpu.parallel import (
            DataParallelPredictor,
            ShardedMLPPredictor,
            make_mesh,
        )

        data = mesh_data if mesh_data and mesh_data > 1 else 1
        model_axis = mesh_model
        if model_axis > 1 and not isinstance(model, MLPRegressor):
            # the mesh knobs are fleet-wide env settings while the served
            # model changes per swap: a linear checkpoint under
            # --mesh-model 2 keeps serving (data-parallel) instead of
            # crash-looping the pod (same contract as --dtype int8 over
            # a linear checkpoint)
            log.warning(
                f"mesh_model={model_axis} requires an MLP checkpoint; "
                f"serving {model.info} data-parallel over "
                f"{data} device(s) instead"
            )
            model_axis = 1
        devices = jax.devices()
        if data * model_axis > len(devices):
            # the mesh knobs are fleet-wide env settings while device
            # counts vary per pod (and per box): an oversized request
            # serves the largest mesh that FITS, with a warning —
            # crash-looping the pod would turn a sizing typo into an
            # outage (same contract as the model-class degrade above)
            requested = f"{data}x{model_axis}"
            if model_axis > len(devices):
                model_axis = 1
            data = max(len(devices) // model_axis, 1)
            log.warning(
                f"mesh {requested} needs more than the {len(devices)} "
                f"available device(s); serving {data}x{model_axis} instead"
            )
        mesh = make_mesh(
            data=data, model=model_axis, devices=devices[:data * model_axis]
        )
        if isinstance(model, MLPRegressor):
            predictor = ShardedMLPPredictor(model, mesh, buckets=buckets)
        else:
            # non-MLP params are two scalars — nothing to tensor-shard;
            # the data-parallel predictor is the right program
            predictor = DataParallelPredictor(model, mesh, buckets=buckets)
    return predictor


def _count_quantization_gate(dtype: str, outcome: str) -> None:
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "bodywork_tpu_serve_quantization_gate_total",
        "Quantized-serving shadow-gate verdicts at boot/swap, by dtype "
        "and outcome (served|rejected_quality|no_shadow_data|"
        "unsupported_model|unsupported_mesh)",
    ).inc(dtype=dtype, outcome=outcome)
    reg.gauge(
        "bodywork_tpu_serve_quantized_state",
        "Quantized serving: 0=f32 default, 1=quantized dtype serving, "
        "2=quantized requested but f32 kept (gate/unsupported)",
        aggregate="max",
    ).set(1.0 if outcome == "served" else 2.0)


def build_serving_predictor(
    store: ArtefactStore,
    model,
    mesh_data: int | None = None,
    engine: str = "xla",
    buckets: tuple[int, ...] | None = None,
    dtype: str = "float32",
    policy=None,
    mesh_model: int = 1,
):
    """The predictor serving should run for a (engine, dtype) choice —
    the ONE composition point boot (``serve_latest_model``), the
    hot-reload watcher, and the multiproc workers share, so a swapped-in
    checkpoint goes through exactly the selection (and the quality gate)
    the booted one did.

    ``dtype="float32"`` is :func:`build_predictor` unchanged. A
    quantized dtype builds BOTH variants and runs the f32-vs-quantized
    shadow comparison over the last ``policy.quantized_shadow_days``
    dataset days (``registry.shadow.shadow_compare`` + the gate's
    ceilings, ``registry.gates.evaluate_quantization``): a quality
    regression past the policy ceiling KEEPS F32 SERVING — quantization
    is a performance upgrade that must never cost quality silently. A
    store with no dataset history to shadow over also keeps f32 (there
    is no evidence either way; refusing is the safe default).

    Returns ``(predictor_or_None, served_dtype)`` — ``served_dtype`` is
    what actually serves ("float32" after a rejection), surfaced on
    /healthz and the ``bodywork_tpu_serve_quantized_state`` gauge."""
    use_mesh = bool(mesh_data and mesh_data > 1) or mesh_model > 1
    if dtype in (None, "float32"):
        return build_predictor(model, mesh_data, engine, buckets=buckets,
                               mesh_model=mesh_model), "float32"
    if use_mesh:
        # both knobs are fleet-wide env settings; the quantized engines
        # are single-device. Crash-looping the pod on the combination
        # would turn a config contradiction into an outage — keep f32
        # MESH serving (the mesh is the capacity knob; precision is the
        # optional one) and say so, same contract as an unsupported model
        log.warning(
            f"dtype={dtype} is single-device; keeping f32 serving over "
            f"the {mesh_data or 1}x{mesh_model} mesh"
        )
        _count_quantization_gate(dtype, "unsupported_mesh")
        return build_predictor(model, mesh_data, engine, buckets=buckets,
                               mesh_model=mesh_model), "float32"
    from bodywork_tpu.registry.gates import GatePolicy, evaluate_quantization
    from bodywork_tpu.registry.shadow import shadow_compare
    policy = policy or GatePolicy()
    base_engine = resolve_engine(engine, model, mesh_data)
    quant_engine = quantized_engine_for(base_engine, dtype)
    # the f32 baseline predictor: the gate's reference, and the fallback
    # that serves when the quantized variant fails it
    f32_engine = "pallas" if base_engine.startswith("pallas") else "xla"
    f32_predictor = build_predictor(
        model, mesh_data, f32_engine, buckets=buckets
    )
    f32_predict = (
        f32_predictor.predict if f32_predictor is not None
        else model.predict_padded
    )
    try:
        quant_predictor = build_predictor(
            model, mesh_data, quant_engine, buckets=buckets
        )
    except ValueError as exc:
        # e.g. a linear checkpoint under --dtype int8 (the quantized
        # engines are MLP-only): the dtype knob is a fleet-wide env
        # setting while the serving model changes per swap — crashing
        # the pod would turn a valid-but-inapplicable knob into an
        # outage, so keep f32 serving and say so (same contract as a
        # quality rejection)
        log.warning(
            f"dtype={dtype} unavailable for this checkpoint ({exc}); "
            "keeping f32 serving"
        )
        _count_quantization_gate(dtype, "unsupported_model")
        return f32_predictor, "float32"
    try:
        report = shadow_compare(
            store, quant_predictor.predict, f32_predict,
            days=policy.quantized_shadow_days,
        )
    except ValueError as exc:
        if "no dataset history" not in str(exc):
            raise
        log.warning(
            f"dtype={dtype}: no dataset history to shadow the quantized "
            "variant over; keeping f32 serving"
        )
        _count_quantization_gate(dtype, "no_shadow_data")
        return f32_predictor, "float32"
    ok, detail = evaluate_quantization(report, policy)
    if not ok:
        log.warning(
            f"dtype={dtype} REJECTED by the shadow quality gate "
            f"({detail}); keeping f32 serving"
        )
        _count_quantization_gate(dtype, "rejected_quality")
        return f32_predictor, "float32"
    log.info(f"dtype={dtype} admitted by the shadow quality gate ({detail})")
    _count_quantization_gate(dtype, "served")
    return quant_predictor, dtype


# build_admission moved to serve.admission (its JAX-free home, so the
# disaggregated front-ends can arm the shared budget without importing
# the model-loading stack); re-exported here for its historical callers
from bodywork_tpu.serve.admission import build_admission  # noqa: E402,F401


def _registry_bounds(store: ArtefactStore, key: str | None):
    """The registry record's prediction-sanity band for a checkpoint —
    the serving firewall's out-of-range reference. None when the store
    is registry-less or the record is absent (the firewall then only
    checks finiteness)."""
    if key is None:
        return None
    try:
        from bodywork_tpu.registry.records import load_record

        record = load_record(store, key)
        return (record or {}).get("prediction_bounds")
    except Exception:  # bounds are an enhancement, never a boot blocker
        return None


def serve_latest_model(
    store: ArtefactStore,
    host: str = "0.0.0.0",
    port: int = 5000,
    block: bool = True,
    mesh_data: int | None = None,
    engine: str = "xla",
    watch_interval_s: float | None = None,
    buckets: tuple[int, ...] | None = None,
    batch_window_ms: float | None = None,
    batch_max_rows: int | None = None,
    server_engine: str = "thread",
    max_pending: int | None = None,
    retry_after_max_s: float | None = None,
    dtype: str = "float32",
    mesh_model: int = 1,
    tuned_config: str | None = None,
    online_tune: bool = False,
    tune_request_logs: tuple = (),
    tune_results_logs: tuple = (),
    cost_budget_s: float | None = None,
):
    """Load latest model -> HBM, warm up, serve (reference ``stage_2`` main).

    ``online_tune`` (env ``BODYWORK_TPU_TUNE_ONLINE`` via ``cli serve
    --online-tune``) arms the online re-tune controller
    (``tune/online.py``) on the reload-watcher loop — it requires
    ``watch_interval_s`` (the controller IS a watcher passenger) and
    watches ``tune_request_logs`` / ``tune_results_logs`` (growing
    ``traffic run`` JSONL files) incrementally for traffic-shape drift,
    refitting and applying knobs mid-flight under the config-canary
    guard. ``cost_budget_s`` additionally arms the admission layer's
    cost-priced shed from the latest learned cost model, bounding the
    estimated dispatch-seconds of admitted work.

    ``tuned_config`` names a tuned serving-config document (a
    ``tuning/`` store key, or ``"latest"`` — ``cli tune``'s output,
    env ``BODYWORK_TPU_TUNED_CONFIG``): its fitted knob values fill
    every knob the caller left unset (coalescer window/max-rows,
    predictor buckets, admission ``max_pending``), explicit caller
    values always win, and a missing/malformed document degrades to
    the built-in defaults with a warning — never a failed boot
    (``tune/config.py resolve_serving_knobs``). The applied document's
    digest rides /healthz ``effective_config.tuned_config``. Note: a
    tuned ``max_pending`` arms admission on either engine (tuning is
    an explicit opt-in).

    ``dtype`` picks the serving precision (``serve.predictor.
    SERVE_DTYPES``): ``bfloat16``/``int8`` serve the quantized variant
    of the checkpoint — but ONLY after the shadow quality gate admits it
    (:func:`build_serving_predictor`); a regression past the policy
    ceiling keeps f32 serving and says so on /healthz and the
    ``bodywork_tpu_serve_quantized_state`` gauge.

    ``mesh_data``/``mesh_model`` > 1 serve through a sharded predictor
    over a ``(mesh_data, mesh_model)`` device mesh — params placed with
    NamedSharding (MLP weights Megatron-split on the ``model`` axis),
    request rows split on ``data``, programs AOT-cached per mesh
    (:func:`build_predictor`; BASELINE.json config 4, bench config 12).
    ``engine="pallas"`` serves an MLP through the fused Pallas kernel
    (``ops.mlp_kernel``; single-device, TPU only); ``engine="auto"`` picks
    the engine by regime (:func:`resolve_engine`). ``watch_interval_s``
    starts a checkpoint watcher that hot-swaps newer models from the store
    without a restart (``serve.reload``; the reference re-deploys the
    service for every new day's model — ``stage_2:113``). With
    ``block=False`` returns a started :class:`ServiceHandle`.

    ``server_engine`` picks the HTTP front-end (:data:`SERVER_ENGINES`):
    ``thread`` (werkzeug, default) or ``aio`` (asyncio event loop,
    ``serve.aio`` — built for open-loop arrival-rate load). ``max_pending``
    arms admission control (``serve.admission``: scoring requests beyond
    the budget answer 429 + ``Retry-After`` before any work happens); the
    aio engine arms it by default (its whole point is staying responsive
    past saturation), the threaded engine only on request.
    ``retry_after_max_s`` caps the EWMA-derived ``Retry-After`` hint.

    Degraded boot: with the watcher enabled, a store holding NO model
    checkpoint yet starts the service anyway — scoring answers 503 +
    ``Retry-After`` until the watcher swaps in the first checkpoint —
    instead of the process dying and flapping its supervisor. Without a
    watcher there is no path to ever serve, so the error still raises.
    """
    from bodywork_tpu.models.checkpoint import resolve_serving_key
    from bodywork_tpu.registry.records import RegistryCorrupt
    from bodywork_tpu.store.base import ArtefactNotFound

    if server_engine not in SERVER_ENGINES:
        raise ValueError(
            f"unknown server engine {server_engine!r}; "
            f"expected one of {SERVER_ENGINES}"
        )
    # tuned-config resolution BEFORE any predictor/app construction:
    # the tuned values must flow into the same bucket/batcher/admission
    # plumbing explicit values do (lazy import keeps the no-tuning boot
    # path's import closure unchanged)
    tuned_digest = None
    if tuned_config:
        from bodywork_tpu.tune.config import resolve_serving_knobs

        resolved = resolve_serving_knobs(
            store, tuned_config,
            batch_window_ms=batch_window_ms,
            batch_max_rows=batch_max_rows,
            buckets=buckets,
            max_pending=max_pending,
        )
        batch_window_ms = resolved.batch_window_ms
        batch_max_rows = resolved.batch_max_rows
        buckets = resolved.buckets
        max_pending = resolved.max_pending
        tuned_digest = resolved.tuned_digest
    try:
        # registry-aware: the production alias when one exists, else the
        # newest date-keyed checkpoint (models/checkpoint.py)
        served_key, served_source = resolve_serving_key(store)
        model, model_date = load_model(store, served_key)
    except (ArtefactNotFound, RegistryCorrupt) as exc:
        # no serviceable checkpoint YET (empty store, all candidates
        # gate-rejected), an unreadable alias document, or an alias
        # pointing at a checkpoint that no longer exists (load_model is
        # inside the try for exactly that dangling case): with a watcher
        # the service boots degraded (503 + Retry-After) and the
        # watcher's polls pick up the first resolvable checkpoint —
        # dying here would just flap the pod supervisor against a
        # condition only time or an operator can clear
        if not watch_interval_s:
            raise
        log.warning(
            f"no serviceable checkpoint at boot ({exc!r}); serving 503s "
            "until the checkpoint watcher resolves one"
        )
        served_key = served_source = None
        model = model_date = predictor = None
        model_bounds = None
    else:
        # with buckets set, build_predictor always returns a predictor
        # (every engine honours the list), so create_app never needs the
        # knob here
        predictor, _served_dtype = build_serving_predictor(
            store, model, mesh_data, engine, buckets=buckets, dtype=dtype,
            mesh_model=mesh_model,
        )
        model_bounds = _registry_bounds(store, served_key)
    admission = build_admission(server_engine, max_pending, retry_after_max_s)
    app = create_app(
        model, model_date, predictor=predictor,
        batch_window_ms=batch_window_ms, batch_max_rows=batch_max_rows,
        model_key=served_key, model_source=served_source,
        admission=admission, model_bounds=model_bounds,
    )
    app.tuned_config_digest = tuned_digest
    if cost_budget_s and admission is not None and model is not None:
        # cost-priced shed: price each request's estimated dispatch
        # cost from the learned cost model BEFORE parse-side queueing.
        # Degrades armlessly when no model document exists yet.
        from bodywork_tpu.tune.costmodel import cost_pricer, load_cost_model

        cm_doc, cm_digest = load_cost_model(store, "latest")
        if cm_doc is not None:
            admission.configure_cost_shed(
                cost_pricer(
                    cm_doc, model.n_features or 1, buckets=buckets,
                ),
                cost_budget_s,
            )
            log.info(
                f"cost-priced shed armed (model {cm_digest[:23]}..., "
                f"budget {cost_budget_s}s)"
            )
        else:
            log.warning(
                "cost-priced shed requested but no cost model is "
                "readable under tuning/; admission stays count-only"
            )
    if server_engine == "aio":
        from bodywork_tpu.serve.aio import AioServiceHandle

        handle = AioServiceHandle(app, host, port)
    else:
        handle = ServiceHandle(app, host, port)
    # the coalescer's dispatcher stops (after flushing) with the service
    handle.add_cleanup(app.close)
    if watch_interval_s:
        from bodywork_tpu.ops.slo import SloWatchdog, policy_from_env
        from bodywork_tpu.serve.reload import NOTHING_SERVED, CheckpointWatcher

        # the SLO watchdog rides the reload-watcher loop: canary
        # routing, breach detection, and the one-CAS auto-abort/promote
        # all poll on the same cadence as checkpoint swaps. Idle cost
        # with no canary live: one attribute read per poll.
        watchdog = SloWatchdog(store, [app], policy=policy_from_env())
        tune_controller = None
        if online_tune:
            from bodywork_tpu.tune.online import (
                OnlineTuneController,
                policy_from_env as tune_policy_from_env,
            )

            tune_controller = OnlineTuneController(
                store, app, policy=tune_policy_from_env(),
                request_logs=tune_request_logs,
                results_logs=tune_results_logs,
            )
            # reachable from handle.app for operational drills (the
            # sabotage path injects through apply_tuned, not a fork)
            app.tune_controller = tune_controller
        watcher = CheckpointWatcher(
            app, store, poll_interval_s=watch_interval_s,
            mesh_data=mesh_data, mesh_model=mesh_model, engine=engine,
            # degraded boot serves nothing: the sentinel (NOT None, which
            # would re-snapshot latest() as already-served and skip a
            # checkpoint published in the lookup->construction window)
            served_key=served_key if served_key is not None else NOTHING_SERVED,
            buckets=buckets,
            slo_watchdog=watchdog,
            dtype=dtype,
            tune_controller=tune_controller,
        )
        watcher.start()
        handle.add_cleanup(watcher.stop)
    elif online_tune:
        log.warning(
            "--online-tune requested without a watch interval; the "
            "controller rides the reload-watcher loop — set "
            "watch_interval_s to arm it"
        )
    if block:
        try:
            handle.serve_forever()
        except ShutdownRequested:
            # graceful SIGTERM (utils.shutdown, installed by `cli
            # serve`): stop ADMITTING first — new scoring requests shed
            # with Retry-After instead of landing on a dying process —
            # then stop() drains the rest: watcher down, coalescer
            # flushed (app.close is a registered cleanup), listener
            # closed. The shutdown watchdog bounds all of this to the
            # grace deadline, inside k8s terminationGracePeriodSeconds.
            log.warning(
                "SIGTERM: draining scoring service "
                "(admission closed, in-flight work finishing)"
            )
            if admission is not None:
                admission.begin_drain()
            handle.stop()
        return None
    return handle.start()
