"""The scoring service's wire formats — a dependency-leaf module.

Everything here is pure ``numpy + json``: request validation, response
payload construction, the binary row-batch framing, and the
pre-serialized single-row response template. It exists as its own module
(rather than living in ``serve.app``, which re-exports it) because the
disaggregated front-end processes (``serve.frontend``) import it on
their hot path and must stay **accelerator-free**: ``serve.app`` pulls
``models.base`` which imports JAX, and N parse/admission front-ends each
paying the JAX import (time and RSS) would defeat the point of keeping
the device in exactly one dispatcher process. A guard test pins that
importing this module (and the front-end stack over it) never imports
``jax``.

Byte-identity is this module's real contract: the WSGI engine, the
asyncio engine, and the disaggregated front-end all build scoring
responses through these helpers with ``json.dumps`` default separators,
which is what lets the bench assert that in-process, disaggregated, and
binary-framed requests produce identical response bytes.
"""
from __future__ import annotations

import json
import struct

import numpy as np

__all__ = [
    "BINARY_CONTENT_TYPE",
    "MODEL_KEY_HEADER",
    "WIRE_SCHEMA_VERSION",
    "BatchResponseTemplate",
    "SingleResponseTemplate",
    "batch_score_payload",
    "encode_binary_rows",
    "parse_binary_rows",
    "parse_features",
    "single_score_payload",
]

#: which model bundle ANSWERED a scoring request (canary releases may
#: route a request to a different model than its neighbour's) — the
#: response header the traffic harness and the byte-identity comparator
#: read. Headers are invisible to the frozen JSON body contract.
MODEL_KEY_HEADER = "X-Bodywork-Model-Key"

#: request content type for the binary row-batch framing (the JSON
#: ``{"X": [...]}`` body stays the default): a little-endian
#: ``u32 n_rows, u32 n_features`` header followed by ``n_rows *
#: n_features`` little-endian f32s. Responses stay JSON either way — the
#: framing removes the client-side float formatting and server-side JSON
#: parse from the request path, nothing else.
BINARY_CONTENT_TYPE = "application/x-bodywork-rows"

#: version of the row framing above, negotiated by every transport that
#: carries it (HTTP via the content type; the socket row-queue transport
#: — ``serve.netqueue`` — via its HELLO frame). Bump on ANY change to
#: the header layout or the f32 row encoding: a front-end and a
#: dispatcher from different builds must refuse to talk rather than
#: misparse each other's rows. Pinned identical across the shm and
#: socket paths by a guard test.
WIRE_SCHEMA_VERSION = 1

#: the binary header: little-endian (n_rows, n_features)
_BINARY_HEADER = struct.Struct("<II")


def parse_features(payload):
    """Validate a decoded request body into a float32 feature array.

    Returns ``(X, None)`` or ``(None, error_message)``. Factored out of
    the WSGI handler so BOTH front-ends (threaded werkzeug and the
    asyncio event loop, ``serve.aio``) validate with the same code and
    answer malformed input with byte-identical 400 bodies."""
    if not isinstance(payload, dict) or "X" not in payload:
        return None, "request body must be a JSON object with an 'X' field"
    try:
        X = np.asarray(payload["X"], dtype=np.float32)
    except (TypeError, ValueError):
        return None, "'X' must be numeric"
    if X.size == 0:
        return None, "'X' must be non-empty"
    if not np.all(np.isfinite(X)):
        return None, "'X' must be finite"
    return X, None


def encode_binary_rows(X) -> bytes:
    """Frame a feature array as a binary row-batch request body.

    1-D input is framed as ``(n_rows, 1)`` — the shape the JSON path's
    ``{"X": [a, b, c]}`` produces — so a JSON request and its binary
    twin parse to byte-identical arrays (same canary routing hash, same
    predictions, same response bytes)."""
    arr = np.asarray(X, dtype="<f4")
    if arr.ndim == 0:
        arr = arr[None]
    if arr.ndim == 1:
        n_rows, n_features = arr.shape[0], 1
    elif arr.ndim == 2:
        n_rows, n_features = arr.shape
    else:
        raise ValueError(f"need 1-D or 2-D features, got shape {arr.shape}")
    return _BINARY_HEADER.pack(n_rows, n_features) + np.ascontiguousarray(
        arr
    ).tobytes()


def parse_binary_rows(body: bytes):
    """Decode a binary row-batch request body into a float32 feature
    array. Same ``(X, None) | (None, error_message)`` contract — and the
    same *semantic* validations (non-empty, finite) with the same
    messages — as :func:`parse_features`, so a client switching framings
    sees one validation behaviour. ``n_features == 1`` decodes to a 1-D
    array, exactly what the JSON path's flat ``"X"`` list produces."""
    if len(body) < _BINARY_HEADER.size:
        return None, "binary body too short for the row header"
    n_rows, n_features = _BINARY_HEADER.unpack_from(body)
    expected = _BINARY_HEADER.size + n_rows * n_features * 4
    if n_features < 1 or n_rows < 1:
        return None, "'X' must be non-empty"
    if len(body) != expected:
        return None, (
            f"binary body length mismatch: header says {n_rows}x"
            f"{n_features} rows ({expected} bytes), got {len(body)}"
        )
    X = np.frombuffer(body, dtype="<f4", offset=_BINARY_HEADER.size).astype(
        np.float32, copy=False
    )
    if n_features > 1:
        X = X.reshape(n_rows, n_features)
    if not np.all(np.isfinite(X)):
        return None, "'X' must be finite"
    return X, None


def single_score_payload(served, prediction0: float) -> dict:
    """The ``/score/v1`` response body. One constructor for both
    front-ends: key order and value formatting are what make coalesced
    responses byte-identical across engines."""
    return {
        "prediction": prediction0,
        "model_info": served.model_info,
        "model_date": served.model_date,
    }


def batch_score_payload(served, predictions) -> dict:
    """The ``/score/v1/batch`` response body (see
    :func:`single_score_payload` for why this is factored)."""
    return {
        "predictions": [float(p) for p in predictions],
        "n": int(len(predictions)),
        "model_info": served.model_info,
        "model_date": served.model_date,
    }


class SingleResponseTemplate:
    """Pre-serialized framing for the single-row 200 response.

    Everything in the body except the prediction is invariant per served
    bundle (``model_info``/``model_date`` change only on a swap, which
    builds a new bundle and therefore a new template), so the hot path
    splices the prediction's own JSON bytes between two cached byte
    strings instead of building and serializing a fresh dict per
    response. ``render`` is pinned byte-identical to
    ``json.dumps(single_score_payload(served, p))`` by construction —
    the framing below IS ``json.dumps``'s default-separator output for
    that dict — and by a regression test sweeping awkward floats.
    """

    __slots__ = ("prefix", "suffix")

    def __init__(self, model_info, model_date):
        # json.dumps default separators: '", "' between items and
        # '": "' after keys; insertion order "prediction", "model_info",
        # "model_date" — exactly single_score_payload's dict
        self.prefix = b'{"prediction": '
        self.suffix = (
            ", \"model_info\": " + json.dumps(model_info)
            + ", \"model_date\": " + json.dumps(model_date) + "}"
        ).encode()

    def render(self, prediction0: float) -> bytes:
        # the prediction still goes through json.dumps (a scalar dump is
        # ~free): float repr, NaN/Infinity spelling, and int-vs-float
        # formatting stay exactly the full-dump path's
        return self.prefix + json.dumps(prediction0).encode() + self.suffix


class BatchResponseTemplate:
    """Pre-serialized framing for the ``/score/v1/batch`` 200 response —
    :class:`SingleResponseTemplate`'s shape, applied to the batch body.

    Per response only the predictions list and its count vary; the
    ``model_info``/``model_date`` tail is invariant per served bundle
    and serializing it per batch is pure rework (it is the largest part
    of the body for small batches). The predictions themselves still go
    through ONE ``json.dumps`` C call on a plain float list, so float
    repr stays exactly the full-dump path's. ``render`` is pinned
    byte-identical to ``json.dumps(batch_score_payload(served, p))`` by
    construction and by a regression test sweeping awkward floats and
    batch sizes.
    """

    __slots__ = ("prefix", "suffix")

    def __init__(self, model_info, model_date):
        # json.dumps default separators; insertion order "predictions",
        # "n", "model_info", "model_date" — exactly batch_score_payload
        self.prefix = b'{"predictions": '
        self.suffix = (
            ", \"model_info\": " + json.dumps(model_info)
            + ", \"model_date\": " + json.dumps(model_date) + "}"
        ).encode()

    def render(self, predictions) -> bytes:
        floats = [float(p) for p in predictions]
        return (
            self.prefix
            + json.dumps(floats).encode()
            + b', "n": ' + str(len(floats)).encode()
            + self.suffix
        )
