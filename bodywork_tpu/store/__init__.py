from bodywork_tpu.store.base import (
    ArtefactStore,
    ArtefactNotFound,
    CasConflict,
    DelegatingStore,
)
from bodywork_tpu.store.filesystem import FilesystemStore
from bodywork_tpu.store.resilient import ResilientStore
from bodywork_tpu.store import schema
from bodywork_tpu.store.schema import (
    DATASETS_PREFIX,
    MODELS_PREFIX,
    MODEL_METRICS_PREFIX,
    REGISTRY_ALIAS_KEY,
    REGISTRY_PREFIX,
    REGISTRY_RECORDS_PREFIX,
    SNAPSHOTS_PREFIX,
    TEST_METRICS_PREFIX,
    dataset_key,
    model_key,
    model_metrics_key,
    registry_record_key,
    snapshot_key,
    test_metrics_key,
)

__all__ = [
    "ArtefactStore",
    "ArtefactNotFound",
    "CasConflict",
    "DelegatingStore",
    "FilesystemStore",
    "ResilientStore",
    "open_scoped_store",
    "open_store",
    "schema",
    "DATASETS_PREFIX",
    "MODELS_PREFIX",
    "MODEL_METRICS_PREFIX",
    "REGISTRY_ALIAS_KEY",
    "REGISTRY_PREFIX",
    "REGISTRY_RECORDS_PREFIX",
    "SNAPSHOTS_PREFIX",
    "TEST_METRICS_PREFIX",
    "dataset_key",
    "model_key",
    "model_metrics_key",
    "registry_record_key",
    "snapshot_key",
    "test_metrics_key",
]


def open_store(url: str) -> ArtefactStore:
    """Open an artefact store from a URL-ish spec.

    - ``/path/to/dir`` or ``file:///path`` -> :class:`FilesystemStore`
    - ``gs://bucket/prefix``               -> :class:`~bodywork_tpu.store.gcs.GCSStore`

    The backend comes wrapped in the audit subsystem's
    :class:`~bodywork_tpu.audit.manifest.AuditedStore`, so every write
    through a CLI entrypoint or k8s pod records its write-time digest
    sidecar under ``audit/`` — the evidence the integrity scrubber
    (``cli fsck``) verifies cold artefacts against.
    """
    from bodywork_tpu.audit.manifest import AuditedStore

    if url.startswith("gs://"):
        from bodywork_tpu.store.gcs import GCSStore

        return AuditedStore(GCSStore.from_url(url))
    if url.startswith("file://"):
        url = url[len("file://"):]
    return AuditedStore(FilesystemStore(url))


def open_scoped_store(url: str) -> ArtefactStore:
    """:func:`open_store`, then scope to the tenant named by the
    ``BODYWORK_TPU_TENANT`` environment variable (malformed degrades to
    the root namespace with a warning — the stages env convention).

    The seam for SPAWNED serving processes (workers, dispatchers,
    supervisors), which receive their configuration through inherited
    env rather than flags. CLI entrypoints keep calling
    :func:`open_store` and apply their own flag-beats-env precedence.
    """
    from bodywork_tpu.tenancy.namespace import scoped_store, tenant_from_env

    return scoped_store(open_store(url), tenant_from_env())
