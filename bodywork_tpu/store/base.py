"""Artefact store interface (replaces reference C7, the S3 data plane).

The reference uses a single S3 bucket with four key prefixes as the
inter-stage data plane, duplicating the client code in every stage
(``stage_1_train_model.py:39-76``, ``stage_2_serve_model.py:46-70``,
``stage_3_synthetic_data_generation.py:46-61``,
``stage_4_test_model_scoring_service.py:39-63``). Versioning is by a date
embedded in the object key; "latest" = max embedded date.

This module defines that contract *once* as an abstract byte store plus the
date-key versioning helpers (``latest``/``history``). Backends: local/TPU-VM
host filesystem (the BASELINE.json north-star transport) and GCS.

Beyond the reference's four prefixes, a dedicated ``snapshots/`` prefix
(``schema.SNAPSHOTS_PREFIX``) holds consolidated-history artefacts
written by :mod:`bodywork_tpu.data.snapshot`: one date-keyed binary
columnar file per compaction, carrying every dataset day up to its
embedded date plus a manifest of covered keys, row counts, and
``version_token``\\ s (staleness is detectable without re-reading the
per-day CSVs). Snapshots are derived data — any backend may drop the
prefix and readers fall back to the per-day artefacts.

Backends that declare a ``backend_label`` class attribute get their
primitive ops instrumented through the shared obs registry
(``bodywork_tpu_store_ops_total{backend,op}`` + an op-latency
histogram), so the data plane's round-trip count is a first-class
observable next to the serving histograms.

Transparent wrappers (the per-attempt write-epoch guard, the resilience
layer's retry/breaker wrapper, the chaos fault injector) all derive from
:class:`DelegatingStore`, which delegates every primitive and metadata
op to the wrapped store — so a backend's ``get_many`` parallelism and
its ``backend_label`` instrumentation survive any wrapper stack, and
``mutable_cache`` always reaches the one long-lived real store. The
canonical composition order, innermost first::

    real backend  <-  FaultInjectingStore (chaos runs only)
                  <-  ResilientStore (retries + circuit breaker)
                  <-  EpochGuardedStore (one per stage attempt)
"""
from __future__ import annotations

import abc
import functools
import threading
import time
from datetime import date

from bodywork_tpu.utils.dates import date_from_key


class ArtefactNotFound(KeyError):
    """No artefact exists at the requested key/prefix."""


class CasConflict(RuntimeError):
    """A ``put_bytes_if_match`` compare-and-swap lost its race: the key's
    current version token no longer matches the caller's expectation
    (someone else wrote between the caller's read and its write). The
    store is untouched by the losing write — the caller re-reads and
    decides whether to retry its read-modify-write."""


#: primitive + metadata ops wrapped with obs instrumentation when a
#: backend declares ``backend_label`` (wrapper stores — epoch guards,
#: counting fixtures — declare none and stay transparent, so delegated
#: calls are counted exactly once, at the real backend)
_INSTRUMENTED_OPS = (
    "put_bytes",
    "put_bytes_if_match",
    "get_bytes",
    "list_keys",
    "delete",
    "exists",
    "version_token",
    "version_tokens",
    "get_many",
)

#: store-op latency ladder: local-filesystem stats (~µs) up through
#: tunnel/GCS round-trips (~67-200 ms measured, PERF.md §1) and retries
_STORE_OP_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
)


def _observe_store_op(backend: str, op: str, seconds: float) -> None:
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "bodywork_tpu_store_ops_total",
        "Artefact-store operations by backend and op",
    ).inc(backend=backend, op=op)
    reg.histogram(
        "bodywork_tpu_store_op_seconds",
        "Artefact-store operation latency by backend and op",
        buckets=_STORE_OP_BUCKETS,
    ).observe(seconds, backend=backend, op=op)


def _timed_op(impl, backend: str, op: str):
    @functools.wraps(impl)
    def wrapper(self, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            return impl(self, *args, **kwargs)
        finally:
            _observe_store_op(backend, op, time.perf_counter() - t0)

    wrapper.__wrapped_store_op__ = op
    return wrapper


class ArtefactStore(abc.ABC):
    """Flat byte store with ``/``-separated keys and date-key versioning."""

    #: set by real backends (e.g. ``"filesystem"``, ``"gcs"``) to opt
    #: their primitive ops into obs instrumentation; wrapper stores leave
    #: it unset so a delegated call is counted once, at the backend
    backend_label: str | None = None

    #: True for backends whose ops already run under the shared retry
    #: policy internally (GCS). ``ResilientStore`` consults it so exactly
    #: ONE layer owns retrying — wrapping a self-retrying backend in a
    #: second retry loop would multiply attempt budgets (3x3 backend
    #: hits per op) and double-count the shared retries metric.
    self_retrying: bool = False

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        label = cls.__dict__.get("backend_label")
        if not label:
            return
        for op in _INSTRUMENTED_OPS:
            impl = cls.__dict__.get(op)
            if impl is not None and not hasattr(impl, "__wrapped_store_op__"):
                setattr(cls, op, _timed_op(impl, label, op))

    @staticmethod
    def validate_key(key: str) -> str:
        """Reject keys that could escape or alias the store namespace.

        Part of the backend contract (every backend enforces it, not just
        the filesystem one where it doubles as path-traversal protection):
        a key accepted by one backend must be accepted by all, or artefacts
        written locally could be unwritable against GCS and vice versa.
        """
        if not key or key.startswith(("/", "..")) or ".." in key.split("/"):
            raise ValueError(f"invalid artefact key: {key!r}")
        return key

    # -- raw byte plane ----------------------------------------------------
    @abc.abstractmethod
    def put_bytes(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def get_bytes(self, key: str) -> bytes: ...

    @abc.abstractmethod
    def list_keys(self, prefix: str = "") -> list[str]:
        """All keys under ``prefix``, sorted lexicographically."""

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    def exists(self, key: str) -> bool:
        """True when ``key`` holds an artefact.

        Consults ``version_token`` first: a non-None token proves
        existence from metadata alone, so backends with tokens never
        download a (possibly multi-MB) payload just to answer an
        existence check. Only a None token — "no token support" OR
        "missing key", indistinguishable here — falls back to the full
        ``get_bytes`` probe. Backends with a native cheap check
        (filesystem stat, GCS ``blob.exists``) override this anyway.
        """
        if self.version_token(key) is not None:
            return True
        try:
            self.get_bytes(key)
            return True
        except ArtefactNotFound:
            return False

    def put_bytes_if_match(
        self, key: str, data: bytes, expected_token=None
    ):
        """Compare-and-swap write: persist ``data`` at ``key`` only if the
        key's current ``version_token`` equals ``expected_token``
        (``None`` = create-only: the key must not exist yet). Raises
        :class:`CasConflict` — leaving the store untouched — otherwise.
        Returns the new version token of the written artefact.

        This is the concurrency primitive the model registry's alias
        document rides (two concurrent promoters: exactly one wins, the
        loser gets a clean conflict, the document never tears). Backends
        with a native conditional write override it (GCS
        ``if_generation_match``); the filesystem backend serialises CAS
        writers through a sidecar lock file + atomic rename. This base
        implementation serialises CAS calls through a per-store-object
        lock — genuinely atomic for in-process backends (the in-memory
        test store), and only best-effort across processes, which real
        backends must not rely on. Backends without version tokens
        cannot support CAS on existing keys and raise
        ``NotImplementedError``.
        """
        self.validate_key(key)
        # setdefault on __dict__ is atomic under the GIL, so two first
        # callers can never install two different locks
        lock = self.__dict__.setdefault("_cas_lock", threading.Lock())
        with lock:
            current = self.version_token(key)
            if current is None and self.exists(key):
                raise NotImplementedError(
                    f"{type(self).__name__} has no version tokens; "
                    "put_bytes_if_match cannot verify the current content"
                )
            if expected_token is None:
                if current is not None:
                    raise CasConflict(
                        f"create-only write of {key!r} lost: key exists"
                    )
            elif current != expected_token:
                raise CasConflict(
                    f"conditional write of {key!r} lost: token changed "
                    f"({expected_token!r} -> {current!r})"
                )
            self.put_bytes(key, data)
            return self.version_token(key)

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        """Fetch many artefacts; returns ``{key: bytes}`` in input order.

        Raises :class:`ArtefactNotFound` (naming the first missing key)
        if any key is absent — callers batch keys they just listed, so a
        miss is a torn read, not a soft condition. The default is
        sequential; backends whose reads are independent round-trips
        (GCS) override with a bounded thread pool so a cold reader's
        tail fetch pays ~one round-trip, not O(keys).
        """
        return {key: self.get_bytes(key) for key in keys}

    def version_token(self, key: str):
        """Opaque token identifying the current content of ``key``, or None.

        Two reads of a key with equal non-None tokens are guaranteed to see
        identical bytes, which lets readers (e.g. the training history
        loader) cache parsed artefacts across the daily loop instead of
        re-reading O(days) objects — the reference's re-download-everything
        pattern (``stage_1_train_model.py:68-71``). Backends without a cheap
        validity check return None (no caching).
        """
        return None

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        """Version tokens for many keys at once (None values omitted).

        Backends with a batched metadata listing (e.g. GCS) override this
        to avoid one round-trip per key — otherwise a cached reader of N
        artefacts would still pay the O(N) metadata calls the cache exists
        to eliminate.
        """
        out = {}
        for key in keys:
            token = self.version_token(key)
            if token is not None:
                out[key] = token
        return out

    def mutable_cache(self, name: str) -> dict:
        """A named per-store mutable cache dict (e.g. the parsed-dataset
        cache in ``data.io``). Defined as a METHOD so wrapping stores
        (``store.epoch.EpochGuardedStore``) can delegate to the store
        they wrap — a cache attached to a throwaway per-attempt wrapper
        would be discarded with it, silently restoring the O(days)
        re-parse the cache exists to eliminate."""
        return self.__dict__.setdefault(name, {})

    # -- text convenience --------------------------------------------------
    def put_text(self, key: str, text: str) -> None:
        self.put_bytes(key, text.encode("utf-8"))

    def get_text(self, key: str) -> str:
        return self.get_bytes(key).decode("utf-8")

    # -- date-key versioning protocol -------------------------------------
    def history(self, prefix: str) -> list[tuple[str, date]]:
        """All date-keyed artefacts under ``prefix``, oldest first.

        Mirrors the reference's list-objects + regex-parse + sort-by-date
        pattern (``stage_1_train_model.py:61-67``). Keys without an embedded
        date are ignored.
        """
        keyed = []
        for key in self.list_keys(prefix):
            d = date_from_key(key)
            if d is not None:
                keyed.append((key, d))
        keyed.sort(key=lambda e: (e[1], e[0]))
        return keyed

    def latest(self, prefix: str) -> tuple[str, date]:
        """Key and date of the most recent artefact under ``prefix``.

        Mirrors ``stage_2_serve_model.py:57-62`` / ``stage_4:49-56``.
        """
        hist = self.history(prefix)
        if not hist:
            raise ArtefactNotFound(f"no date-keyed artefacts under '{prefix}'")
        return hist[-1]


class DelegatingStore(ArtefactStore):
    """Base for TRANSPARENT store wrappers (write-epoch guard, resilience
    layer, chaos fault injector): every primitive and metadata op
    delegates to the wrapped store, and no ``backend_label`` is declared
    — a delegated call is instrumented once, at the real backend.

    ``get_many`` is delegated (not inherited) so a backend's parallel
    override survives the wrapper stack; ``mutable_cache`` is delegated
    so caches live on the one long-lived real store rather than dying
    with a throwaway wrapper.
    """

    def __init__(self, inner: ArtefactStore):
        self._inner = inner

    @property
    def inner(self) -> ArtefactStore:
        return self._inner

    def put_bytes(self, key: str, data: bytes) -> None:
        self._inner.put_bytes(key, data)

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        # delegated (not inherited): the base fallback's per-object lock
        # would serialise against OTHER wrapper instances' CAS calls
        # instead of the one real backend's — the backend's own CAS
        # protocol (lock file, if-generation-match) must arbitrate
        return self._inner.put_bytes_if_match(key, data, expected_token)

    def get_bytes(self, key: str) -> bytes:
        return self._inner.get_bytes(key)

    def list_keys(self, prefix: str = "") -> list[str]:
        return self._inner.list_keys(prefix)

    def delete(self, key: str) -> None:
        self._inner.delete(key)

    def exists(self, key: str) -> bool:
        return self._inner.exists(key)

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        return self._inner.get_many(keys)

    def version_token(self, key: str):
        return self._inner.version_token(key)

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        return self._inner.version_tokens(keys)

    def mutable_cache(self, name: str) -> dict:
        return self._inner.mutable_cache(name)


def innermost_backend(store: ArtefactStore) -> ArtefactStore | None:
    """The real backend under any wrapper stack (the first store down
    the ``_inner`` chain declaring a ``backend_label``), or None."""
    seen = set()
    while store is not None and id(store) not in seen:
        seen.add(id(store))
        if store.backend_label:
            return store
        store = getattr(store, "_inner", None) or getattr(store, "inner", None)
    return None


def innermost_backend_label(store: ArtefactStore) -> str | None:
    """The real backend's ``backend_label`` under any wrapper stack, or
    None — used to label wrapper-layer metrics (retries, breaker state)
    with the backend actually being protected."""
    backend = innermost_backend(store)
    return None if backend is None else backend.backend_label
