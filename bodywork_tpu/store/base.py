"""Artefact store interface (replaces reference C7, the S3 data plane).

The reference uses a single S3 bucket with four key prefixes as the
inter-stage data plane, duplicating the client code in every stage
(``stage_1_train_model.py:39-76``, ``stage_2_serve_model.py:46-70``,
``stage_3_synthetic_data_generation.py:46-61``,
``stage_4_test_model_scoring_service.py:39-63``). Versioning is by a date
embedded in the object key; "latest" = max embedded date.

This module defines that contract *once* as an abstract byte store plus the
date-key versioning helpers (``latest``/``history``). Backends: local/TPU-VM
host filesystem (the BASELINE.json north-star transport) and GCS.
"""
from __future__ import annotations

import abc
from datetime import date

from bodywork_tpu.utils.dates import date_from_key


class ArtefactNotFound(KeyError):
    """No artefact exists at the requested key/prefix."""


class ArtefactStore(abc.ABC):
    """Flat byte store with ``/``-separated keys and date-key versioning."""

    @staticmethod
    def validate_key(key: str) -> str:
        """Reject keys that could escape or alias the store namespace.

        Part of the backend contract (every backend enforces it, not just
        the filesystem one where it doubles as path-traversal protection):
        a key accepted by one backend must be accepted by all, or artefacts
        written locally could be unwritable against GCS and vice versa.
        """
        if not key or key.startswith(("/", "..")) or ".." in key.split("/"):
            raise ValueError(f"invalid artefact key: {key!r}")
        return key

    # -- raw byte plane ----------------------------------------------------
    @abc.abstractmethod
    def put_bytes(self, key: str, data: bytes) -> None: ...

    @abc.abstractmethod
    def get_bytes(self, key: str) -> bytes: ...

    @abc.abstractmethod
    def list_keys(self, prefix: str = "") -> list[str]:
        """All keys under ``prefix``, sorted lexicographically."""

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    def exists(self, key: str) -> bool:
        try:
            self.get_bytes(key)
            return True
        except ArtefactNotFound:
            return False

    def version_token(self, key: str):
        """Opaque token identifying the current content of ``key``, or None.

        Two reads of a key with equal non-None tokens are guaranteed to see
        identical bytes, which lets readers (e.g. the training history
        loader) cache parsed artefacts across the daily loop instead of
        re-reading O(days) objects — the reference's re-download-everything
        pattern (``stage_1_train_model.py:68-71``). Backends without a cheap
        validity check return None (no caching).
        """
        return None

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        """Version tokens for many keys at once (None values omitted).

        Backends with a batched metadata listing (e.g. GCS) override this
        to avoid one round-trip per key — otherwise a cached reader of N
        artefacts would still pay the O(N) metadata calls the cache exists
        to eliminate.
        """
        out = {}
        for key in keys:
            token = self.version_token(key)
            if token is not None:
                out[key] = token
        return out

    def mutable_cache(self, name: str) -> dict:
        """A named per-store mutable cache dict (e.g. the parsed-dataset
        cache in ``data.io``). Defined as a METHOD so wrapping stores
        (``store.epoch.EpochGuardedStore``) can delegate to the store
        they wrap — a cache attached to a throwaway per-attempt wrapper
        would be discarded with it, silently restoring the O(days)
        re-parse the cache exists to eliminate."""
        return self.__dict__.setdefault(name, {})

    # -- text convenience --------------------------------------------------
    def put_text(self, key: str, text: str) -> None:
        self.put_bytes(key, text.encode("utf-8"))

    def get_text(self, key: str) -> str:
        return self.get_bytes(key).decode("utf-8")

    # -- date-key versioning protocol -------------------------------------
    def history(self, prefix: str) -> list[tuple[str, date]]:
        """All date-keyed artefacts under ``prefix``, oldest first.

        Mirrors the reference's list-objects + regex-parse + sort-by-date
        pattern (``stage_1_train_model.py:61-67``). Keys without an embedded
        date are ignored.
        """
        keyed = []
        for key in self.list_keys(prefix):
            d = date_from_key(key)
            if d is not None:
                keyed.append((key, d))
        keyed.sort(key=lambda e: (e[1], e[0]))
        return keyed

    def latest(self, prefix: str) -> tuple[str, date]:
        """Key and date of the most recent artefact under ``prefix``.

        Mirrors ``stage_2_serve_model.py:57-62`` / ``stage_4:49-56``.
        """
        hist = self.history(prefix)
        if not hist:
            raise ArtefactNotFound(f"no date-keyed artefacts under '{prefix}'")
        return hist[-1]
