"""Write-epoch guard for abandoned stage attempts (VERDICT r4 item 9).

The local runner cannot kill a timed-out batch-stage thread (Python has
no thread kill; k8s kills the whole pod instead — ``runner.py``). It
abandons the daemon thread and fails the stage, but the abandoned thread
kept a live reference to the shared store: a slow write landing AFTER
the day was failed leaves ``run_simulation`` in an unspecified state —
a later day (or a retry) could read a half-day's artefact written by a
stage the orchestrator already declared dead.

:class:`EpochGuardedStore` closes that hole. Each stage ATTEMPT gets its
own guard wrapping the real store; when the runner abandons the attempt
it revokes the epoch, after which every WRITE through the guard raises
:class:`WriteEpochRevoked` — the late write never lands. Reads stay
allowed: an abandoned reader is harmless, and failing it would only
change which exception the dead thread swallows.

The guard derives from :class:`~bodywork_tpu.store.base.DelegatingStore`
so it composes with any backend or wrapper stack (filesystem, GCS,
in-memory fakes, the resilience layer's ``ResilientStore``, the chaos
``FaultInjectingStore``): reads, ``get_many`` parallelism, and
``mutable_cache`` delegate untouched; only the write ops are epoch-
checked.
"""
from __future__ import annotations

import threading

from bodywork_tpu.store.base import ArtefactStore, DelegatingStore

__all__ = ["EpochGuardedStore", "WriteEpochRevoked"]


class WriteEpochRevoked(RuntimeError):
    """A write arrived through a store epoch the orchestrator revoked
    (the writing stage attempt was timed out and abandoned)."""


class EpochGuardedStore(DelegatingStore):
    def __init__(self, inner: ArtefactStore, label: str = "stage"):
        super().__init__(inner)
        self._label = label
        self._revoked = threading.Event()

    def revoke(self) -> None:
        """Reject all future writes through this epoch (idempotent)."""
        self._revoked.set()

    @property
    def revoked(self) -> bool:
        return self._revoked.is_set()

    def _check_writable(self, key: str) -> None:
        if self._revoked.is_set():
            raise WriteEpochRevoked(
                f"write of {key!r} rejected: the {self._label} attempt "
                "holding this store epoch was timed out and abandoned"
            )

    # -- write ops (epoch-checked; everything else delegates) --------------

    def put_bytes(self, key: str, data: bytes) -> None:
        self._check_writable(key)
        self._inner.put_bytes(key, data)

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        # a CAS write is still a write: an abandoned attempt must not be
        # able to flip e.g. the registry alias after its epoch ended
        self._check_writable(key)
        return self._inner.put_bytes_if_match(key, data, expected_token)

    def delete(self, key: str) -> None:
        self._check_writable(key)
        self._inner.delete(key)
