"""Write-epoch guard for abandoned stage attempts (VERDICT r4 item 9).

The local runner cannot kill a timed-out batch-stage thread (Python has
no thread kill; k8s kills the whole pod instead — ``runner.py``). It
abandons the daemon thread and fails the stage, but the abandoned thread
kept a live reference to the shared store: a slow write landing AFTER
the day was failed leaves ``run_simulation`` in an unspecified state —
a later day (or a retry) could read a half-day's artefact written by a
stage the orchestrator already declared dead.

:class:`EpochGuardedStore` closes that hole. Each stage ATTEMPT gets its
own guard wrapping the real store; when the runner abandons the attempt
it revokes the epoch, after which every WRITE through the guard raises
:class:`WriteEpochRevoked` — the late write never lands. Reads stay
allowed: an abandoned reader is harmless, and failing it would only
change which exception the dead thread swallows.

The guard composes with any backend (filesystem, GCS, in-memory fakes)
because it delegates the four primitive ops and inherits every
convenience method from :class:`ArtefactStore`.
"""
from __future__ import annotations

import threading

from bodywork_tpu.store.base import ArtefactStore

__all__ = ["EpochGuardedStore", "WriteEpochRevoked"]


class WriteEpochRevoked(RuntimeError):
    """A write arrived through a store epoch the orchestrator revoked
    (the writing stage attempt was timed out and abandoned)."""


class EpochGuardedStore(ArtefactStore):
    def __init__(self, inner: ArtefactStore, label: str = "stage"):
        self._inner = inner
        self._label = label
        self._revoked = threading.Event()

    def revoke(self) -> None:
        """Reject all future writes through this epoch (idempotent)."""
        self._revoked.set()

    @property
    def revoked(self) -> bool:
        return self._revoked.is_set()

    def _check_writable(self, key: str) -> None:
        if self._revoked.is_set():
            raise WriteEpochRevoked(
                f"write of {key!r} rejected: the {self._label} attempt "
                "holding this store epoch was timed out and abandoned"
            )

    # -- primitives (delegated; writes epoch-checked) ----------------------

    def put_bytes(self, key: str, data: bytes) -> None:
        self._check_writable(key)
        self._inner.put_bytes(key, data)

    def delete(self, key: str) -> None:
        self._check_writable(key)
        self._inner.delete(key)

    def get_bytes(self, key: str) -> bytes:
        return self._inner.get_bytes(key)

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        # delegated (not inherited): the default would loop THIS class's
        # get_bytes and lose the backend's parallel override
        return self._inner.get_many(keys)

    def list_keys(self, prefix: str = "") -> list[str]:
        return self._inner.list_keys(prefix)

    def exists(self, key: str) -> bool:
        return self._inner.exists(key)

    def version_token(self, key: str):
        return self._inner.version_token(key)

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        return self._inner.version_tokens(keys)

    def mutable_cache(self, name: str) -> dict:
        # caches must live on the REAL store: this wrapper is one stage
        # attempt's throwaway epoch, and a cache dying with it would
        # silently restore the O(days) history re-parse
        return self._inner.mutable_cache(name)
