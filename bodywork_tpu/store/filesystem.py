"""Filesystem artefact-store backend.

Per ``BASELINE.json``'s north star, artefacts pass between stages via the TPU
VM host filesystem (a shared volume on a GKE TPU node) rather than S3. Keys
map to paths under a root directory; writes are atomic (tmp file + rename) so
a concurrently-reading service stage never sees a torn artefact.
"""
from __future__ import annotations

import fcntl
import os
import tempfile
from pathlib import Path

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore, CasConflict


def _fsync_dir(path: Path) -> None:
    """fsync a DIRECTORY. A file fsync + ``os.replace`` alone does not
    make the rename durable across power loss — the new directory entry
    lives in directory metadata, which the kernel flushes on its own
    schedule — so every atomic write ends by syncing the parent
    directory (the classic write-file / fsync-file / rename /
    fsync-dir sequence). Module-level so the chaos torn-write test can
    spy on it. Platforms whose directories refuse ``os.open`` for
    syncing (some network filesystems) degrade silently: the rename is
    still atomic, only its power-loss durability is weakened, which is
    strictly the pre-existing behaviour."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class FilesystemStore(ArtefactStore):
    backend_label = "filesystem"

    #: how long a CAS writer waits on a contended sidecar lock before
    #: giving up with a conflict (a crashed holder's stale lock file must
    #: not wedge promotions forever — see put_bytes_if_match)
    CAS_LOCK_TIMEOUT_S = 5.0

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        return self.root / self.validate_key(key)

    def _write_atomic(self, path: Path, data: bytes) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                # fsync BEFORE the rename: without it a host crash can
                # surface the new name with zero-length content (rename
                # durable, data not) — exactly the torn-artefact class
                # the chaos soak asserts never exists
                os.fsync(f.fileno())
            os.replace(tmp, path)
            # ...and fsync the PARENT DIRECTORY after the rename: the
            # file fsync makes the bytes durable, the dir fsync makes
            # the NAME durable — without it a power loss can forget the
            # rename entirely (old content, or no file, at the key a
            # completed put reported written). Covers the CAS path too:
            # put_bytes_if_match writes through this same helper.
            _fsync_dir(path.parent)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def put_bytes(self, key: str, data: bytes) -> None:
        self._write_atomic(self._path(key), data)

    def _acquire_cas_lock(self, key: str, lock_path: Path) -> int:
        """Bounded wait for the CAS sidecar lock: an ``fcntl.flock`` on
        a persistent ``.tmp-lock.<name>`` file (the ``.tmp-`` prefix
        keeps it out of ``list_keys``; it is created once and NEVER
        unlinked — the classic flock unlink race would let two writers
        hold 'the lock' on different inodes). flock is released by the
        kernel when the holder's fd closes — including on a crash — so
        there is no stale-lock state and no lock *breaking*: breaking a
        merely-slow holder's lock would admit two writers whose token
        checks then both pass, the silent lost update CAS exists to
        prevent. A holder slower than the timeout just makes contenders
        fail with a clean conflict. Between-attempt sleeping goes
        through the SHARED retry policy (``utils.retry.call_with_retry``
        — the chaos guard pins store modules backoff-loop-free, and the
        jittered waits decorrelate contending promoters)."""
        from bodywork_tpu.utils.retry import RetryPolicy, call_with_retry

        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)

        def _try_lock():
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)  # BlockingIOError
            return fd

        try:
            return call_with_retry(
                _try_lock,
                RetryPolicy(
                    attempts=4096,  # the deadline budget is the real bound
                    base_delay_s=0.002,
                    max_delay_s=0.01,
                    deadline_s=self.CAS_LOCK_TIMEOUT_S,
                ),
                is_retryable=lambda exc: isinstance(exc, BlockingIOError),
            )
        except BlockingIOError:
            os.close(fd)
            raise CasConflict(
                f"CAS lock on {key!r} contended past "
                f"{self.CAS_LOCK_TIMEOUT_S}s"
            )
        except BaseException:
            # a real I/O fault (EIO, ENOSPC, …) is NOT a lost race —
            # mapping it to CasConflict would have promoters retry
            # forever against a broken disk reporting 'conflict'
            os.close(fd)
            raise

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        """CAS via sidecar lock + atomic rename: an ``flock`` on the
        persistent ``.tmp-lock.<name>`` sidecar (see
        :meth:`_acquire_cas_lock`) serialises concurrent CAS writers —
        across threads AND processes — then the token check and
        tmp+fsync+rename run under the lock. Plain ``put_bytes`` does
        not take the lock, which is why alias-style documents must only
        ever be written through THIS op (the registry guard test pins
        that)."""
        path = self._path(key)
        path.parent.mkdir(parents=True, exist_ok=True)
        lock_path = path.parent / f".tmp-lock.{path.name}"
        lock_fd = self._acquire_cas_lock(key, lock_path)
        try:
            current = self.version_token(key)
            if expected_token is None:
                if current is not None:
                    raise CasConflict(
                        f"create-only write of {key!r} lost: key exists"
                    )
            elif current != expected_token:
                raise CasConflict(
                    f"conditional write of {key!r} lost: token changed "
                    f"({expected_token!r} -> {current!r})"
                )
            self._write_atomic(path, data)
            return self.version_token(key)
        finally:
            # closing the fd releases the flock; the lock FILE stays on
            # disk deliberately (unlink would reopen the flock-unlink
            # race — see _acquire_cas_lock)
            os.close(lock_fd)

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()

    def get_bytes(self, key: str) -> bytes:
        path = self._path(key)
        try:
            return path.read_bytes()
        except FileNotFoundError:
            raise ArtefactNotFound(key) from None

    def list_keys(self, prefix: str = "") -> list[str]:
        # Walk only the prefix's directory subtree. Prefixes map to
        # directories (schema.ALL_PREFIXES), and walking the WHOLE root
        # per listing made every history()/latest() call O(total
        # artefacts ever written): on a 90-day store each day's
        # incremental retrain paid ~5x the listing it asked for, and the
        # cost grew forever (measured as the dominant term in the
        # config-10 flatness profile).
        dir_part, _, _name_part = prefix.rpartition("/")
        base = self.root / dir_part if dir_part else self.root
        if not base.is_dir():
            return []
        keys = []
        for dirpath, _dirnames, filenames in os.walk(base):
            for name in filenames:
                if name.startswith(".tmp-"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    keys.append(key)
        return sorted(keys)

    def delete(self, key: str) -> None:
        path = self._path(key)
        try:
            path.unlink()
        except FileNotFoundError:
            raise ArtefactNotFound(key) from None

    def version_token(self, key: str):
        # Every put_bytes is tmp-file + rename, i.e. a fresh inode, so
        # (ino, size, mtime_ns) changes on every overwrite even when the
        # filesystem's mtime granularity is coarse and the size is equal.
        try:
            st = self._path(key).stat()
        except (FileNotFoundError, ValueError):
            return None
        return (st.st_ino, st.st_size, st.st_mtime_ns)

    def __repr__(self) -> str:
        return f"FilesystemStore(root={str(self.root)!r})"
