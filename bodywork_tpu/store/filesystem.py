"""Filesystem artefact-store backend.

Per ``BASELINE.json``'s north star, artefacts pass between stages via the TPU
VM host filesystem (a shared volume on a GKE TPU node) rather than S3. Keys
map to paths under a root directory; writes are atomic (tmp file + rename) so
a concurrently-reading service stage never sees a torn artefact.
"""
from __future__ import annotations

import os
import tempfile
from pathlib import Path

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore


class FilesystemStore(ArtefactStore):
    backend_label = "filesystem"

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        return self.root / self.validate_key(key)

    def put_bytes(self, key: str, data: bytes) -> None:
        path = self._path(key)
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
                f.flush()
                # fsync BEFORE the rename: without it a host crash can
                # surface the new name with zero-length content (rename
                # durable, data not) — exactly the torn-artefact class
                # the chaos soak asserts never exists
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def exists(self, key: str) -> bool:
        return self._path(key).is_file()

    def get_bytes(self, key: str) -> bytes:
        path = self._path(key)
        try:
            return path.read_bytes()
        except FileNotFoundError:
            raise ArtefactNotFound(key) from None

    def list_keys(self, prefix: str = "") -> list[str]:
        keys = []
        for dirpath, _dirnames, filenames in os.walk(self.root):
            for name in filenames:
                if name.startswith(".tmp-"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                key = rel.replace(os.sep, "/")
                if key.startswith(prefix):
                    keys.append(key)
        return sorted(keys)

    def delete(self, key: str) -> None:
        path = self._path(key)
        try:
            path.unlink()
        except FileNotFoundError:
            raise ArtefactNotFound(key) from None

    def version_token(self, key: str):
        # Every put_bytes is tmp-file + rename, i.e. a fresh inode, so
        # (ino, size, mtime_ns) changes on every overwrite even when the
        # filesystem's mtime granularity is coarse and the size is equal.
        try:
            st = self._path(key).stat()
        except (FileNotFoundError, ValueError):
            return None
        return (st.st_ino, st.st_size, st.st_mtime_ns)

    def __repr__(self) -> str:
        return f"FilesystemStore(root={str(self.root)!r})"
