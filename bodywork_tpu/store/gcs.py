"""GCS artefact-store backend (optional).

The GKE-deployed pipeline can use a GCS bucket exactly as the reference uses
S3 (SURVEY.md C7). Requires ``google-cloud-storage``, which is not a hard
dependency — the backend raises a clear error at construction if missing, and
the rest of the framework runs on :class:`FilesystemStore`.

Listings iterate the client's paged iterator to exhaustion, so prefixes
with more than one page of blobs (1000/page on real GCS) are handled; the
contract suite drives this against a paginating fake. Transient service
errors (429/5xx classes) are retried at THIS layer through the shared
policy (:mod:`bodywork_tpu.utils.retry`): exponential backoff with FULL
jitter — the previous fixed delays synchronized across the bounded
``get_many`` thread pool into a thundering herd on a struggling service
— and cumulative sleep capped by a per-op deadline budget. The real
client retries some idempotent calls internally, but its policy is
invisible to tests and does not cover iteration of an already-started
listing — an explicit, test-exercised policy beats an assumed one.
Retries are exported as ``bodywork_tpu_store_retries_total{backend,op}``.
"""
from __future__ import annotations

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore, CasConflict
from bodywork_tpu.utils.retry import RetryPolicy, _chain, call_with_retry

#: exception class names the GCS client raises for a failed
#: ``if_generation_match`` precondition (name-matched like the transient
#: taxonomy, so the optional dependency's classes need not be importable)
_PRECONDITION_FAILED_NAMES = frozenset(
    {"PreconditionFailed", "FailedPrecondition"}
)


def _is_precondition_failure(exc: BaseException) -> bool:
    # same cause-chain walk as the transient taxonomy (utils.retry)
    return any(
        type(e).__name__ in _PRECONDITION_FAILED_NAMES
        or getattr(e, "code", None) == 412  # HTTP Precondition Failed
        for e in _chain(exc)
    )


class GCSStore(ArtefactStore):
    backend_label = "gcs"
    #: ops already run under the shared retry policy here, so a wrapping
    #: ResilientStore adds only the breaker, not a second retry loop
    self_retrying = True

    #: transient-retry policy knobs (attempts include the first try);
    #: materialised per call as a utils.retry.RetryPolicy
    RETRY_ATTEMPTS = 3
    RETRY_BASE_DELAY_S = 0.1
    RETRY_MAX_DELAY_S = 2.0
    #: per-op deadline budget: backoff sleeps stop once an op has spent
    #: this long in total, so retry sleep can never eat a caller's
    #: completion deadline
    RETRY_DEADLINE_S = 30.0
    #: bounded fan-out for ``get_many`` — enough to overlap the ~67-200 ms
    #: per-object round-trip (PERF.md §1) without stampeding the service
    GET_MANY_MAX_THREADS = 8

    def __init__(self, bucket: str, prefix: str = ""):
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "GCSStore requires the 'google-cloud-storage' package; "
                "use FilesystemStore (the default) or install it"
            ) from e
        self._client = storage.Client()
        self._bucket = self._client.bucket(bucket)
        self._prefix = prefix.strip("/")

    def _retry_policy(self) -> RetryPolicy:
        return RetryPolicy(
            attempts=self.RETRY_ATTEMPTS,
            base_delay_s=self.RETRY_BASE_DELAY_S,
            max_delay_s=self.RETRY_MAX_DELAY_S,
            deadline_s=self.RETRY_DEADLINE_S,
        )

    def _with_retries(self, op, op_name: str = "op"):
        """Run ``op`` (a thunk that fully materialises its result — paged
        iteration included, so a mid-listing drop retries the WHOLE
        listing, never splices two inconsistent pages) under the shared
        retry policy (transient-only, full jitter, deadline budget)."""

        def on_retry(exc, attempt, sleep_s):
            from bodywork_tpu.obs import get_registry

            get_registry().counter(
                "bodywork_tpu_store_retries_total",
                "Artefact-store op retries by backend and op",
            ).inc(backend=self.backend_label, op=op_name)

        return call_with_retry(op, self._retry_policy(), on_retry=on_retry)

    @classmethod
    def from_url(cls, url: str) -> "GCSStore":
        assert url.startswith("gs://"), url
        bucket, _, prefix = url[len("gs://"):].partition("/")
        return cls(bucket, prefix)

    def _blob_name(self, key: str) -> str:
        self.validate_key(key)
        return f"{self._prefix}/{key}" if self._prefix else key

    def exists(self, key: str) -> bool:
        name = self._blob_name(key)
        return self._with_retries(
            lambda: self._bucket.blob(name).exists(), "exists"
        )

    def put_bytes(self, key: str, data: bytes) -> None:
        name = self._blob_name(key)
        self._with_retries(
            lambda: self._bucket.blob(name).upload_from_string(data),
            "put_bytes",
        )

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        """CAS via GCS's native conditional write: ``if_generation_match``
        pinned to the expected generation (0 = create-only, exactly the
        ``expected_token=None`` contract). A precondition failure maps to
        :class:`CasConflict`; it is NOT transient, so the retry loop
        propagates it immediately rather than burning attempts on a race
        already lost — EXCEPT when our own earlier attempt may have
        committed before its response was dropped (upload applied
        server-side, transient error on the reply, retry now sees the
        bumped generation): the post-check below re-reads the object and
        treats current-content-equals-our-payload as the success it is,
        so a promotion that actually landed is never misreported as a
        lost race (which would leave the caller's follow-up record
        updates unapplied)."""
        name = self._blob_name(key)
        match = 0 if expected_token is None else expected_token

        def _put():
            blob = self._bucket.blob(name)
            blob.upload_from_string(data, if_generation_match=match)
            return blob.generation

        def _verify_own_write():
            # fetch + download inside ONE retried thunk: the flaky
            # network that dropped the upload's response is exactly the
            # network likely to blip the verification read, and a
            # transient here must not convert a LANDED write into a
            # reported conflict
            blob = self._bucket.get_blob(name)
            if blob is not None and blob.download_as_bytes() == data:
                return blob.generation
            return None

        try:
            return self._with_retries(_put, "put_bytes_if_match")
        except Exception as exc:
            if _is_precondition_failure(exc):
                try:
                    generation = self._with_retries(
                        _verify_own_write, "put_bytes_if_match"
                    )
                    if generation is not None:
                        return generation
                except Exception:  # noqa: BLE001 — post-check best-effort
                    pass  # cannot verify: report the conflict below
                raise CasConflict(
                    f"conditional write of {key!r} lost: generation "
                    f"{match} no longer current"
                ) from exc
            raise

    def get_bytes(self, key: str) -> bytes:
        name = self._blob_name(key)

        def _get():
            blob = self._bucket.blob(name)
            if not blob.exists():
                raise ArtefactNotFound(key)
            return blob.download_as_bytes()

        return self._with_retries(_get, "get_bytes")

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        # Each object read is an independent round-trip, so a bounded
        # thread pool overlaps them; every per-key fetch keeps the SAME
        # retry policy as a single get_bytes (the thunk each worker runs
        # IS get_bytes, wrapper and all). Results return in input order;
        # the first missing key raises, like the sequential default.
        if len(keys) <= 1:
            return {key: self.get_bytes(key) for key in keys}
        from concurrent.futures import ThreadPoolExecutor

        workers = min(self.GET_MANY_MAX_THREADS, len(keys))
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="gcs-get-many"
        ) as pool:
            blobs = list(pool.map(self.get_bytes, keys))
        return dict(zip(keys, blobs))

    def list_keys(self, prefix: str = "") -> list[str]:
        # a prefix is not a key (may legitimately be empty) — no validation
        full = f"{self._prefix}/{prefix}" if self._prefix else prefix
        strip = len(self._prefix) + 1 if self._prefix else 0
        return self._with_retries(lambda: sorted(
            b.name[strip:]
            for b in self._client.list_blobs(self._bucket, prefix=full)
        ), "list_keys")

    def delete(self, key: str) -> None:
        name = self._blob_name(key)
        # Absence-on-retry means success ONLY if a delete RPC was actually
        # issued: the earlier attempt's delete may have applied server-side
        # before its response was lost. A transient error BEFORE the
        # existence check (e.g. a 503 from exists() itself) proves nothing
        # about the blob — retrying into absence there must still raise
        # ArtefactNotFound for a key that never existed.
        state = {"delete_attempted": False}

        def _delete():
            blob = self._bucket.blob(name)
            if not blob.exists():
                if state["delete_attempted"]:
                    return  # our own delete (probably) landed: success
                raise ArtefactNotFound(key)
            state["delete_attempted"] = True
            blob.delete()

        self._with_retries(_delete, "delete")

    def version_token(self, key: str):
        # GCS object generation changes on every overwrite; invalid keys
        # report "no token" like the filesystem backend (contract: token
        # queries never raise)
        try:
            blob = self._with_retries(
                lambda: self._bucket.get_blob(self._blob_name(key)),
                "version_token",
            )
        except ValueError:
            return None
        return None if blob is None else blob.generation

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        # One paged listing per key *directory* returns every blob's
        # generation — O(#directories) requests instead of one get_blob
        # round-trip per key, without ever listing unrelated bucket
        # contents (keys from different prefixes must not degrade to a
        # whole-bucket listing).
        wanted = {}
        for k in keys:
            try:
                wanted[self._blob_name(k)] = k
            except ValueError:
                continue  # contract: token queries never raise; no token
        if not wanted:
            return {}
        dirs = {name.rsplit("/", 1)[0] + "/" if "/" in name else "" for name in wanted}
        out = {}
        for d in sorted(dirs):

            def _scan(d=d):
                found = {}
                for blob in self._client.list_blobs(self._bucket, prefix=d):
                    key = wanted.get(blob.name)
                    if key is not None and blob.generation is not None:
                        found[key] = blob.generation
                return found

            out.update(self._with_retries(_scan, "version_tokens"))
        return out
