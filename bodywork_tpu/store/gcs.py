"""GCS artefact-store backend (optional).

The GKE-deployed pipeline can use a GCS bucket exactly as the reference uses
S3 (SURVEY.md C7). Requires ``google-cloud-storage``, which is not a hard
dependency — the backend raises a clear error at construction if missing, and
the rest of the framework runs on :class:`FilesystemStore`.
"""
from __future__ import annotations

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore


class GCSStore(ArtefactStore):
    def __init__(self, bucket: str, prefix: str = ""):
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "GCSStore requires the 'google-cloud-storage' package; "
                "use FilesystemStore (the default) or install it"
            ) from e
        self._client = storage.Client()
        self._bucket = self._client.bucket(bucket)
        self._prefix = prefix.strip("/")

    @classmethod
    def from_url(cls, url: str) -> "GCSStore":
        assert url.startswith("gs://"), url
        bucket, _, prefix = url[len("gs://"):].partition("/")
        return cls(bucket, prefix)

    def _blob_name(self, key: str) -> str:
        self.validate_key(key)
        return f"{self._prefix}/{key}" if self._prefix else key

    def exists(self, key: str) -> bool:
        return self._bucket.blob(self._blob_name(key)).exists()

    def put_bytes(self, key: str, data: bytes) -> None:
        self._bucket.blob(self._blob_name(key)).upload_from_string(data)

    def get_bytes(self, key: str) -> bytes:
        blob = self._bucket.blob(self._blob_name(key))
        if not blob.exists():
            raise ArtefactNotFound(key)
        return blob.download_as_bytes()

    def list_keys(self, prefix: str = "") -> list[str]:
        # a prefix is not a key (may legitimately be empty) — no validation
        full = f"{self._prefix}/{prefix}" if self._prefix else prefix
        strip = len(self._prefix) + 1 if self._prefix else 0
        return sorted(b.name[strip:] for b in self._client.list_blobs(self._bucket, prefix=full))

    def delete(self, key: str) -> None:
        blob = self._bucket.blob(self._blob_name(key))
        if not blob.exists():
            raise ArtefactNotFound(key)
        blob.delete()

    def version_token(self, key: str):
        # GCS object generation changes on every overwrite; invalid keys
        # report "no token" like the filesystem backend (contract: token
        # queries never raise)
        try:
            blob = self._bucket.get_blob(self._blob_name(key))
        except ValueError:
            return None
        return None if blob is None else blob.generation

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        # One paged listing per key *directory* returns every blob's
        # generation — O(#directories) requests instead of one get_blob
        # round-trip per key, without ever listing unrelated bucket
        # contents (keys from different prefixes must not degrade to a
        # whole-bucket listing).
        wanted = {}
        for k in keys:
            try:
                wanted[self._blob_name(k)] = k
            except ValueError:
                continue  # contract: token queries never raise; no token
        if not wanted:
            return {}
        dirs = {name.rsplit("/", 1)[0] + "/" if "/" in name else "" for name in wanted}
        out = {}
        for d in sorted(dirs):
            for blob in self._client.list_blobs(self._bucket, prefix=d):
                key = wanted.get(blob.name)
                if key is not None and blob.generation is not None:
                    out[key] = blob.generation
        return out
