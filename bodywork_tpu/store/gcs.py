"""GCS artefact-store backend (optional).

The GKE-deployed pipeline can use a GCS bucket exactly as the reference uses
S3 (SURVEY.md C7). Requires ``google-cloud-storage``, which is not a hard
dependency — the backend raises a clear error at construction if missing, and
the rest of the framework runs on :class:`FilesystemStore`.

Listings iterate the client's paged iterator to exhaustion, so prefixes
with more than one page of blobs (1000/page on real GCS) are handled; the
contract suite drives this against a paginating fake. Transient service
errors (429/5xx classes) are retried with short exponential backoff at
THIS layer: the real client retries some idempotent calls internally, but
its policy is invisible to tests and does not cover iteration of an
already-started listing — an explicit, test-exercised policy beats an
assumed one.
"""
from __future__ import annotations

import time

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore

#: exception type names treated as transient (google.api_core classes are
#: matched by NAME because google-cloud-storage is an optional dependency
#: this module must import without)
_TRANSIENT_ERROR_NAMES = frozenset({
    "ServiceUnavailable",      # 503
    "TooManyRequests",         # 429
    "InternalServerError",     # 500
    "BadGateway",              # 502
    "GatewayTimeout",          # 504
    "DeadlineExceeded",
    "RetryError",
    "ConnectionError",
    "ConnectionResetError",
})


def _is_transient(exc: BaseException) -> bool:
    return any(
        t.__name__ in _TRANSIENT_ERROR_NAMES for t in type(exc).__mro__
    )


class GCSStore(ArtefactStore):
    backend_label = "gcs"

    #: transient-retry policy: attempts include the first try
    RETRY_ATTEMPTS = 3
    RETRY_BASE_DELAY_S = 0.1
    #: bounded fan-out for ``get_many`` — enough to overlap the ~67-200 ms
    #: per-object round-trip (PERF.md §1) without stampeding the service
    GET_MANY_MAX_THREADS = 8

    def __init__(self, bucket: str, prefix: str = ""):
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "GCSStore requires the 'google-cloud-storage' package; "
                "use FilesystemStore (the default) or install it"
            ) from e
        self._client = storage.Client()
        self._bucket = self._client.bucket(bucket)
        self._prefix = prefix.strip("/")

    def _with_retries(self, op):
        """Run ``op`` (a thunk that fully materialises its result — paged
        iteration included, so a mid-listing drop retries the WHOLE
        listing, never splices two inconsistent pages), retrying
        transient errors with exponential backoff."""
        delay = self.RETRY_BASE_DELAY_S
        for attempt in range(self.RETRY_ATTEMPTS):
            try:
                return op()
            except Exception as exc:
                if not _is_transient(exc) or attempt == self.RETRY_ATTEMPTS - 1:
                    raise
                time.sleep(delay)
                delay *= 2

    @classmethod
    def from_url(cls, url: str) -> "GCSStore":
        assert url.startswith("gs://"), url
        bucket, _, prefix = url[len("gs://"):].partition("/")
        return cls(bucket, prefix)

    def _blob_name(self, key: str) -> str:
        self.validate_key(key)
        return f"{self._prefix}/{key}" if self._prefix else key

    def exists(self, key: str) -> bool:
        name = self._blob_name(key)
        return self._with_retries(
            lambda: self._bucket.blob(name).exists()
        )

    def put_bytes(self, key: str, data: bytes) -> None:
        name = self._blob_name(key)
        self._with_retries(
            lambda: self._bucket.blob(name).upload_from_string(data)
        )

    def get_bytes(self, key: str) -> bytes:
        name = self._blob_name(key)

        def _get():
            blob = self._bucket.blob(name)
            if not blob.exists():
                raise ArtefactNotFound(key)
            return blob.download_as_bytes()

        return self._with_retries(_get)

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        # Each object read is an independent round-trip, so a bounded
        # thread pool overlaps them; every per-key fetch keeps the SAME
        # retry policy as a single get_bytes (the thunk each worker runs
        # IS get_bytes, wrapper and all). Results return in input order;
        # the first missing key raises, like the sequential default.
        if len(keys) <= 1:
            return {key: self.get_bytes(key) for key in keys}
        from concurrent.futures import ThreadPoolExecutor

        workers = min(self.GET_MANY_MAX_THREADS, len(keys))
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="gcs-get-many"
        ) as pool:
            blobs = list(pool.map(self.get_bytes, keys))
        return dict(zip(keys, blobs))

    def list_keys(self, prefix: str = "") -> list[str]:
        # a prefix is not a key (may legitimately be empty) — no validation
        full = f"{self._prefix}/{prefix}" if self._prefix else prefix
        strip = len(self._prefix) + 1 if self._prefix else 0
        return self._with_retries(lambda: sorted(
            b.name[strip:]
            for b in self._client.list_blobs(self._bucket, prefix=full)
        ))

    def delete(self, key: str) -> None:
        name = self._blob_name(key)
        # Absence-on-retry means success ONLY if a delete RPC was actually
        # issued: the earlier attempt's delete may have applied server-side
        # before its response was lost. A transient error BEFORE the
        # existence check (e.g. a 503 from exists() itself) proves nothing
        # about the blob — retrying into absence there must still raise
        # ArtefactNotFound for a key that never existed.
        state = {"delete_attempted": False}

        def _delete():
            blob = self._bucket.blob(name)
            if not blob.exists():
                if state["delete_attempted"]:
                    return  # our own delete (probably) landed: success
                raise ArtefactNotFound(key)
            state["delete_attempted"] = True
            blob.delete()

        self._with_retries(_delete)

    def version_token(self, key: str):
        # GCS object generation changes on every overwrite; invalid keys
        # report "no token" like the filesystem backend (contract: token
        # queries never raise)
        try:
            blob = self._with_retries(
                lambda: self._bucket.get_blob(self._blob_name(key))
            )
        except ValueError:
            return None
        return None if blob is None else blob.generation

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        # One paged listing per key *directory* returns every blob's
        # generation — O(#directories) requests instead of one get_blob
        # round-trip per key, without ever listing unrelated bucket
        # contents (keys from different prefixes must not degrade to a
        # whole-bucket listing).
        wanted = {}
        for k in keys:
            try:
                wanted[self._blob_name(k)] = k
            except ValueError:
                continue  # contract: token queries never raise; no token
        if not wanted:
            return {}
        dirs = {name.rsplit("/", 1)[0] + "/" if "/" in name else "" for name in wanted}
        out = {}
        for d in sorted(dirs):

            def _scan(d=d):
                found = {}
                for blob in self._client.list_blobs(self._bucket, prefix=d):
                    key = wanted.get(blob.name)
                    if key is not None and blob.generation is not None:
                        found[key] = blob.generation
                return found

            out.update(self._with_retries(_scan))
        return out
