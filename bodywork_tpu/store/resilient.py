"""Resilience layer over any artefact-store backend: retries + breaker.

:class:`ResilientStore` wraps any :class:`~bodywork_tpu.store.base.
ArtefactStore` and routes every fallible public op (``put_bytes``,
``get_bytes``, ``get_many``, ``list_keys``, ``delete``, ``exists``)
through the shared retry policy (:mod:`bodywork_tpu.utils.retry`:
transient-only, exponential backoff with full jitter, per-op deadline
budget) and a circuit breaker:

- **closed** — ops flow; consecutive op-level transient failures (i.e.
  failures that survived the retry budget) are counted;
- **open** — after ``failure_threshold`` consecutive failures, ops
  fast-fail with :class:`~bodywork_tpu.utils.retry.CircuitOpenError`
  without touching the backend, until ``reset_timeout_s`` elapses;
- **half-open** — one probe op is admitted; success closes the breaker,
  failure re-opens it.

``version_token``/``version_tokens`` delegate un-retried: their contract
is "never raise", and backends with remote tokens (GCS) already retry
internally through the same shared policy.

Exported metrics: ``bodywork_tpu_store_retries_total{backend,op}`` (one
increment per backoff sleep — shared with the GCS backend's internal
retries) and ``bodywork_tpu_store_breaker_state{backend}`` (0=closed,
1=half-open, 2=open).

Composition (see ``store/base.py``): the chaos fault injector sits
BELOW this wrapper, so injected faults exercise exactly the retry and
breaker paths production faults would; the per-attempt epoch guard sits
above.
"""
from __future__ import annotations

from bodywork_tpu.store.base import (
    ArtefactStore,
    DelegatingStore,
    innermost_backend,
)
from bodywork_tpu.utils.retry import (
    CircuitBreaker,
    RetryPolicy,
    call_with_retry,
    is_transient,
)

__all__ = ["ResilientStore"]


class ResilientStore(DelegatingStore):
    def __init__(
        self,
        inner: ArtefactStore,
        policy: RetryPolicy | None = None,
        breaker: CircuitBreaker | None = None,
        label: str | None = None,
    ):
        super().__init__(inner)
        backend = innermost_backend(inner)
        if policy is None:
            # Exactly ONE layer owns retrying: DIRECTLY over a backend
            # whose ops already run under the shared policy internally
            # (GCS), this wrapper contributes only the breaker — a second
            # retry loop would multiply attempt budgets and double-count
            # the metric. The check is on the IMMEDIATE inner store, not
            # the innermost backend: a wrapper in between (the chaos
            # fault injector) raises failures ABOVE the backend's
            # internal loop, and those only this layer can retry.
            policy = (
                RetryPolicy(attempts=1)
                if inner.self_retrying
                else RetryPolicy()
            )
        self._policy = policy
        self._label = label or (
            backend.backend_label if backend is not None else None
        ) or "wrapped"
        from bodywork_tpu.obs import get_registry

        reg = get_registry()
        self._retries = reg.counter(
            "bodywork_tpu_store_retries_total",
            "Artefact-store op retries by backend and op",
        )
        self._breaker_gauge = reg.gauge(
            "bodywork_tpu_store_breaker_state",
            "Store circuit-breaker state: 0=closed, 1=half-open, 2=open",
            aggregate="max",
        )
        if breaker is None:
            breaker = CircuitBreaker()
        # chain, don't clobber: a caller-installed state hook (e.g. an
        # alerting callback on a supplied breaker) keeps firing alongside
        # the gauge export
        caller_hook = breaker.on_state_change
        if caller_hook is None:
            breaker.on_state_change = self._record_state
        else:
            def _both(state, _caller=caller_hook):
                self._record_state(state)
                _caller(state)

            breaker.on_state_change = _both
        self._breaker = breaker
        self._record_state(breaker.state)

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def _record_state(self, state: str) -> None:
        self._breaker_gauge.set(
            CircuitBreaker.STATE_VALUES[state], backend=self._label
        )

    def _guarded(self, op: str, fn):
        """One public op: breaker admission ONCE (so a half-open probe is
        one op, internal retries included), then the shared retry policy
        around the delegated call. The breaker counts OP-level outcomes
        (a transient failure that survives the whole retry budget), not
        per-attempt ones — the retry layer is the first line of defence,
        the breaker the backstop behind it. Every admitted op records an
        outcome: a NON-transient error (e.g. ``ArtefactNotFound``) counts
        as success — the backend answered, which is exactly the health
        signal the breaker watches."""
        self._breaker.allow()  # raises CircuitOpenError when open

        def on_retry(exc, n, sleep_s):
            self._retries.inc(backend=self._label, op=op)

        try:
            result = call_with_retry(fn, self._policy, on_retry=on_retry)
        except Exception as exc:
            if is_transient(exc):
                self._breaker.record_failure()
            else:
                self._breaker.record_success()
            raise
        self._breaker.record_success()
        return result

    # -- guarded public ops ------------------------------------------------

    def put_bytes(self, key: str, data: bytes) -> None:
        self._guarded("put_bytes", lambda: self._inner.put_bytes(key, data))

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        # CAS rides the same retry+breaker as every other op. Safe to
        # retry: CasConflict is not on the transient allowlist (a lost
        # race propagates immediately, attempt budget intact), and a
        # transient failure AFTER the write applied surfaces on retry as
        # a conflict the backend disambiguates (GCS's own-write
        # post-check turns it back into success)
        return self._guarded(
            "put_bytes_if_match",
            lambda: self._inner.put_bytes_if_match(key, data, expected_token),
        )

    def get_bytes(self, key: str) -> bytes:
        return self._guarded("get_bytes", lambda: self._inner.get_bytes(key))

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        # retried as a unit: the delegated call fully materialises its
        # result, so a retry re-fetches the whole batch (never splices
        # two half-batches from different attempts)
        return self._guarded("get_many", lambda: self._inner.get_many(keys))

    def list_keys(self, prefix: str = "") -> list[str]:
        return self._guarded("list_keys", lambda: self._inner.list_keys(prefix))

    def delete(self, key: str) -> None:
        self._guarded("delete", lambda: self._inner.delete(key))

    def exists(self, key: str) -> bool:
        return self._guarded("exists", lambda: self._inner.exists(key))
