"""The four-prefix artefact schema shared by all pipeline stages.

Same layout as the reference bucket ``bodywork-mlops-project`` (SURVEY.md L2):

- ``datasets/regression-dataset-<date>.csv``       (``stage_3:49,56``)
- ``models/regressor-<date>.npz``                   (``stage_1:113-121``;
  reference uses ``.joblib`` — here models are JAX pytree checkpoints)
- ``model-metrics/regressor-<date>.csv``            (``stage_1:130-138``)
- ``test-metrics/regressor-test-results-<date>.csv``(``stage_4:122-130``)
"""
from __future__ import annotations

from datetime import date

DATASETS_PREFIX = "datasets/"
MODELS_PREFIX = "models/"
MODEL_METRICS_PREFIX = "model-metrics/"
TEST_METRICS_PREFIX = "test-metrics/"

ALL_PREFIXES = (
    DATASETS_PREFIX,
    MODELS_PREFIX,
    MODEL_METRICS_PREFIX,
    TEST_METRICS_PREFIX,
)


def dataset_key(d: date) -> str:
    return f"{DATASETS_PREFIX}regression-dataset-{d}.csv"


def model_key(d: date, suffix: str = "npz") -> str:
    return f"{MODELS_PREFIX}regressor-{d}.{suffix}"


def model_metrics_key(d: date) -> str:
    return f"{MODEL_METRICS_PREFIX}regressor-{d}.csv"


def test_metrics_key(d: date) -> str:
    return f"{TEST_METRICS_PREFIX}regressor-test-results-{d}.csv"
