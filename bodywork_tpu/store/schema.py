"""The four-prefix artefact schema shared by all pipeline stages.

Same layout as the reference bucket ``bodywork-mlops-project`` (SURVEY.md L2):

- ``datasets/regression-dataset-<date>.csv``       (``stage_3:49,56``)
- ``models/regressor-<date>.npz``                   (``stage_1:113-121``;
  reference uses ``.joblib`` — here models are JAX pytree checkpoints)
- ``model-metrics/regressor-<date>.csv``            (``stage_1:130-138``)
- ``test-metrics/regressor-test-results-<date>.csv``(``stage_4:122-130``)

Beyond the reference's four prefixes, ``snapshots/`` holds consolidated
history snapshots (``data/snapshot.py``): one binary columnar artefact
per compaction covering every dataset day up to its embedded date, so a
cold process loads all history in O(1 + tail) store reads instead of
O(days). Snapshots are derived artefacts — deleting the prefix is always
safe (readers fall back to the per-day CSVs).
"""
from __future__ import annotations

from datetime import date

DATASETS_PREFIX = "datasets/"
MODELS_PREFIX = "models/"
MODEL_METRICS_PREFIX = "model-metrics/"
TEST_METRICS_PREFIX = "test-metrics/"
SNAPSHOTS_PREFIX = "snapshots/"

ALL_PREFIXES = (
    DATASETS_PREFIX,
    MODELS_PREFIX,
    MODEL_METRICS_PREFIX,
    TEST_METRICS_PREFIX,
    SNAPSHOTS_PREFIX,
)


def dataset_key(d: date) -> str:
    return f"{DATASETS_PREFIX}regression-dataset-{d}.csv"


def model_key(d: date, suffix: str = "npz") -> str:
    return f"{MODELS_PREFIX}regressor-{d}.{suffix}"


def model_metrics_key(d: date) -> str:
    return f"{MODEL_METRICS_PREFIX}regressor-{d}.csv"


def test_metrics_key(d: date) -> str:
    return f"{TEST_METRICS_PREFIX}regressor-test-results-{d}.csv"


def snapshot_key(d: date) -> str:
    """Consolidated-history snapshot covering every dataset day <= ``d``
    (the embedded date is the most recent covered day, so the standard
    date-key protocol — ``history``/``latest`` — versions snapshots too)."""
    return f"{SNAPSHOTS_PREFIX}history-snapshot-{d}.npz"
