"""The four-prefix artefact schema shared by all pipeline stages.

Same layout as the reference bucket ``bodywork-mlops-project`` (SURVEY.md L2):

- ``datasets/regression-dataset-<date>.csv``       (``stage_3:49,56``)
- ``models/regressor-<date>.npz``                   (``stage_1:113-121``;
  reference uses ``.joblib`` — here models are JAX pytree checkpoints)
- ``model-metrics/regressor-<date>.csv``            (``stage_1:130-138``)
- ``test-metrics/regressor-test-results-<date>.csv``(``stage_4:122-130``)

Beyond the reference's four prefixes, ``snapshots/`` holds consolidated
history snapshots (``data/snapshot.py``): one binary columnar artefact
per compaction covering every dataset day up to its embedded date, so a
cold process loads all history in O(1 + tail) store reads instead of
O(days). Snapshots are derived artefacts — deleting the prefix is always
safe (readers fall back to the per-day CSVs).

``runs/`` holds the durable day-run journal (``pipeline/journal.py``):
one document per simulated day, ``runs/<date>/journal.json``, recording
per-stage intent/complete entries (artefact keys + content digests) and
the CAS-acquired run lease that keeps a rescheduled CronJob pod and a
still-alive original from interleaving writes for the same day. Delete
safety: journals are OPERATIONAL state, never results — deleting one
only forfeits crash-resume for that day (the next run re-executes every
stage, converging to the same artefacts), so the prefix is always safe
to clear. Like the alias document, journals are mutated EXCLUSIVELY
through ``ArtefactStore.put_bytes_if_match`` (never a raw ``put_bytes``)
— the lease protocol is only sound if every writer rides the CAS.

``trainstate/`` holds persisted training state for incremental retrains
(``train/incremental.py``): per-model-type JSON documents of per-day
sufficient statistics (the linear model's Gram matrix/moment vector,
row counts, label ranges) that let each day's retrain fold in only the
new day instead of refitting on all history. Delete safety: trainstate
is DERIVED state — every entry is a pure function of the persisted
datasets — so deleting the prefix is always safe; the only cost is one
full refit on the next training run, which rebuilds it. Like the alias
document and run journals, trainstate is mutated EXCLUSIVELY through
``ArtefactStore.put_bytes_if_match`` (never a raw ``put_bytes``), and
every document embeds a content digest its readers verify — a corrupt
or torn read degrades to the full-refit rebuild, never a wrong model.

``registry/`` holds the model-registry release-management layer
(``bodywork_tpu/registry/``): date-keyed per-model records under
``registry/records/`` plus the single alias document
``registry/aliases.json`` mapping ``production``/``previous`` to model
keys. Delete safety: the ALIAS DOCUMENT is authoritative for what
serves — deleting it reverts serving to the latest-checkpoint fallback
(losing gating, not data); records are append-only lineage/decision
history and are never required by the serving path, but deleting them
discards the audit trail, so treat the prefix as durable, not derived.
The alias doc is mutated exclusively through the compare-and-swap
primitive ``ArtefactStore.put_bytes_if_match`` (never a raw
``put_bytes``), so concurrent promoters cannot tear it.

``audit/`` holds the store's write-time digest manifest
(``bodywork_tpu/audit/manifest.py``): one sidecar document per covered
artefact under ``audit/digests/<key>.json`` recording the artefact's
content digest (and, for small non-rebuildable classes, a compressed
replica — the redundancy the fsck repair planner restores from).
Delete safety: sidecars are DERIVED from the primary artefacts — the
scrubber backfills a deleted digest record from the primary bytes on
its next pass — but deleting a replica forfeits the self-healing
redundancy for that artefact, so treat the prefix as cheap insurance,
not scratch space.

``quarantine/`` holds corrupt bytes the fsck repair planner moved aside
(``bodywork_tpu/audit/repair.py``): per incident, the corrupt payload
at ``quarantine/<original key>`` plus a metadata document
``quarantine/<original key>.quarantine.json`` recording what was found.
Quarantine entries are EVIDENCE, written only through the CAS primitive
and never deleted by the framework — retention is an operator decision
(docs/RESILIENCE.md §11 runbook).

``tuning/`` holds tuned serving-config documents (``bodywork_tpu/tune/``):
date-keyed JSON (schema ``bodywork_tpu.tuned_config/1``, doc_digest
embedded, digest sidecar + replica via the audit layer) mapping the
hand-set serving knobs (coalescer window/max-rows, padding-bucket
ladder, admission budget) to values fitted from observed traces, with
the decision trace that produced each value in-document. Delete safety:
tuned configs are DERIVED artefacts — a pure function of the traces
they were fitted from — and serving only ever consumes them through the
malformed-degrades loader (``tune/config.py``), so deleting the prefix
is always safe: every consumer reverts to its built-in default knob
values (the pre-tuning behaviour exactly); the only cost is re-running
``cli tune``.

``obs/flightrec/`` holds flight-recorder dumps (``obs/tracing.py``):
one content-addressed JSON document per SLO-watchdog abort/promote
verdict (schema ``bodywork_tpu.flight_record/1``) carrying the sampled
request traces that were in flight when the verdict fired — the
per-request evidence behind each auto-rollback. Delete safety: dumps
are DIAGNOSTIC EVIDENCE, never consumed by serving, training, or any
repair path — deleting the prefix only forfeits the forensic record of
past verdicts (``cli trace`` goes dark for them); nothing rebuilds
them, so treat the prefix like ``quarantine/``: cheap history whose
retention is an operator decision. Dumps get a digest sidecar + replica
via the audit layer (``PUT_SIDECAR_PREFIXES``) so at-rest rot is
detectable and restorable.

``tenants/`` is the multi-tenant namespace root (``bodywork_tpu/tenancy/``):
``tenants/<tenant-id>/`` mirrors the ENTIRE schema above for one tenant —
``tenants/acme/datasets/...``, ``tenants/acme/registry/aliases.json`` and
so on — so every subsystem (training, registry, journals, snapshots,
audit sidecars, tuned configs) becomes tenant-aware without learning a
new key grammar: a tenant-scoped store view (``tenancy.scoped_store``)
rebases all keys under the tenant prefix and everything else is
unchanged. The reserved ``default`` tenant is the UNPREFIXED root
namespace itself — scoping to ``default`` is the identity — which keeps
every pre-tenancy key byte-identical. Delete safety: ``tenants/<id>/`` is
one tenant's entire estate — datasets, models, lineage, journals — so
deleting a subtree is offboarding, not cleanup: it carries exactly the
union of the per-prefix delete-safety notes above, applied to that
tenant alone (and, by the namespacing construction, can never touch
another tenant's keys or the default namespace). The fsck scrubber
recurses into each tenant subtree with a tenant-scoped view, so per-
tenant repair is ``cli fsck --tenant <id>``.
"""
from __future__ import annotations

import re

from datetime import date

DATASETS_PREFIX = "datasets/"
MODELS_PREFIX = "models/"
MODEL_METRICS_PREFIX = "model-metrics/"
TEST_METRICS_PREFIX = "test-metrics/"
SNAPSHOTS_PREFIX = "snapshots/"
TRAINSTATE_PREFIX = "trainstate/"
RUNS_PREFIX = "runs/"
REGISTRY_PREFIX = "registry/"
REGISTRY_RECORDS_PREFIX = "registry/records/"
#: the single alias document (no embedded date: invisible to the
#: date-key ``history``/``latest`` protocol by design). Authoritative
#: mapping of ``production``/``previous`` to model keys; written ONLY
#: via ``put_bytes_if_match`` — see the module docstring's delete note.
REGISTRY_ALIAS_KEY = "registry/aliases.json"
#: tuned serving-config documents (bodywork_tpu/tune/) — derived
#: artefacts; see the module docstring's delete-safety note
TUNING_PREFIX = "tuning/"
AUDIT_PREFIX = "audit/"
AUDIT_DIGESTS_PREFIX = "audit/digests/"
QUARANTINE_PREFIX = "quarantine/"
#: flight-recorder dumps (obs/tracing.py) — diagnostic evidence; see
#: the module docstring's delete-safety note
FLIGHTREC_PREFIX = "obs/flightrec/"
#: serving-plane operational state (serve/leadership.py): the dispatcher
#: leader lease document. Like runs/ journals it is coordination state,
#: not a result — deleting it only forces a fresh election
SERVE_PREFIX = "serve/"
#: multi-tenant namespace root (bodywork_tpu/tenancy/): tenants/<id>/
#: mirrors the whole schema for one tenant; see the module docstring's
#: delete-safety note (deleting a subtree is offboarding that tenant)
TENANTS_PREFIX = "tenants/"

#: the reserved tenant whose namespace IS the unprefixed root — scoping
#: to it is the identity, keeping every pre-tenancy key byte-identical
DEFAULT_TENANT = "default"

#: the single source of truth for what a tenant id may look like. DNS-
#: label-shaped on purpose: lowercase alphanumerics and single interior
#: hyphens, 1-63 chars, so a tenant id is always safe as a store key
#: segment, a k8s label value, and a Prometheus label value. The cli
#: ``--tenant`` flag, the ``BODYWORK_TPU_TENANT`` env knob, and the key
#: grammar are all guard-pinned to agree with THIS pattern.
TENANT_ID_PATTERN = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")


def validate_tenant_id(tenant_id: str) -> str:
    """Validate ``tenant_id`` against :data:`TENANT_ID_PATTERN` and
    return it. Raises ``ValueError`` (with the offending value and the
    grammar) otherwise — every entry point funnels through here so cli
    flags, env parsing, and key construction can never disagree."""
    if not isinstance(tenant_id, str) or not TENANT_ID_PATTERN.match(tenant_id):
        raise ValueError(
            f"invalid tenant id {tenant_id!r}: want lowercase DNS-label "
            "(^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$)"
        )
    if "--" in tenant_id:
        raise ValueError(
            f"invalid tenant id {tenant_id!r}: consecutive hyphens reserved"
        )
    return tenant_id


def tenant_prefix(tenant_id: str) -> str:
    """The store-key prefix rooting ``tenant_id``'s namespace — empty
    for the reserved :data:`DEFAULT_TENANT` (identity scoping)."""
    validate_tenant_id(tenant_id)
    if tenant_id == DEFAULT_TENANT:
        return ""
    return f"{TENANTS_PREFIX}{tenant_id}/"

#: every prefix the store schema defines — and therefore every prefix
#: the integrity scrubber must audit: the fsck checker registry
#: (``bodywork_tpu/audit/fsck.py``) is guard-pinned to cover EXACTLY
#: this tuple, so a prefix added here without an auditor fails tier-1.
#: Order matters to the scrubber: datasets/ is checked (and repaired)
#: before the derived prefixes that rebuild from it.
ALL_PREFIXES = (
    DATASETS_PREFIX,
    MODELS_PREFIX,
    MODEL_METRICS_PREFIX,
    TEST_METRICS_PREFIX,
    SNAPSHOTS_PREFIX,
    TRAINSTATE_PREFIX,
    RUNS_PREFIX,
    REGISTRY_PREFIX,
    TUNING_PREFIX,
    AUDIT_PREFIX,
    QUARANTINE_PREFIX,
    FLIGHTREC_PREFIX,
    SERVE_PREFIX,
    #: last on purpose: each tenant subtree is audited AFTER the root
    #: namespace, with a tenant-scoped recursion over the prefixes above
    TENANTS_PREFIX,
)


def dispatcher_leader_key() -> str:
    """The dispatcher leadership lease document
    (``serve/leadership.py``): one ``(owner, expires_at, fence)`` doc
    per namespace, mutated exclusively through CAS — the journal-lease
    discipline applied to the serving plane."""
    return f"{SERVE_PREFIX}dispatcher-leader.json"


def dataset_key(d: date) -> str:
    return f"{DATASETS_PREFIX}regression-dataset-{d}.csv"


def model_key(d: date, suffix: str = "npz") -> str:
    return f"{MODELS_PREFIX}regressor-{d}.{suffix}"


def model_metrics_key(d: date) -> str:
    return f"{MODEL_METRICS_PREFIX}regressor-{d}.csv"


def test_metrics_key(d: date) -> str:
    return f"{TEST_METRICS_PREFIX}regressor-test-results-{d}.csv"


def registry_record_key(model_key: str) -> str:
    """Registry-record key for a model artefact key: the checkpoint's
    basename (extension dropped) under ``registry/records/``. Model keys
    embed their date, so record keys do too — the standard date-key
    protocol (``history``/``latest``) orders records chronologically."""
    base = model_key.rsplit("/", 1)[-1]
    stem = base.rsplit(".", 1)[0] if "." in base else base
    return f"{REGISTRY_RECORDS_PREFIX}{stem}.json"


def run_journal_key(d: date) -> str:
    """The day-run journal document for simulated day ``d``
    (``pipeline/journal.py``). The embedded date keeps journals visible
    to the standard date-key protocol for retention tooling, while the
    per-day subdirectory leaves room for future per-run attachments."""
    return f"{RUNS_PREFIX}{d}/journal.json"


def trainstate_key(model_type: str) -> str:
    """The persisted-sufficient-statistics document for one model type
    (``train/incremental.py``). One document per model type, no embedded
    date — like the alias document it is a live, CAS-mutated pointer
    into history, not a date-keyed artefact, so it stays invisible to
    the ``history``/``latest`` protocol by design."""
    return f"{TRAINSTATE_PREFIX}{model_type}-suffstats.json"


def snapshot_key(d: date) -> str:
    """Consolidated-history snapshot covering every dataset day <= ``d``
    (the embedded date is the most recent covered day, so the standard
    date-key protocol — ``history``/``latest`` — versions snapshots too)."""
    return f"{SNAPSHOTS_PREFIX}history-snapshot-{d}.npz"


def tuned_config_key(d: date) -> str:
    """The tuned serving-config document fitted on day ``d``
    (``bodywork_tpu/tune/``). Date-keyed so the standard
    ``history``/``latest`` protocol versions tuned configs — serving's
    ``--tuned-config latest`` resolves through ``latest(TUNING_PREFIX)``."""
    return f"{TUNING_PREFIX}tuned-config-{d}.json"


def cost_model_key(d: date) -> str:
    """The learned dispatch-cost model fitted on day ``d``
    (``bodywork_tpu/tune/costmodel.py``). Lives under ``tuning/`` with
    the tuned config (same derived-artefact delete-safety, same audit
    coverage); its distinct basename keeps tuned-config ``latest``
    resolution (which filters on basename) and the fsck checker's
    per-kind validation unambiguous."""
    return f"{TUNING_PREFIX}cost-model-{d}.json"


#: the config-lifecycle log (``registry/configlog.py``): which tuned
#: config is ACTIVE in the serving plane, which one preceded it, and
#: the applied/reverted event history. Like the registry alias document
#: it is a live CAS-mutated pointer — no embedded date, invisible to
#: the ``history``/``latest`` protocol by design, written ONLY via
#: ``put_bytes_if_match``.
CONFIG_LOG_KEY = f"{TUNING_PREFIX}config-log.json"


def audit_digest_key(key: str) -> str:
    """The write-time digest sidecar for artefact ``key``
    (``bodywork_tpu/audit/manifest.py``): the primary key path mirrored
    under ``audit/digests/`` with a ``.json`` suffix, so the sidecar
    namespace can never collide with a primary artefact and the inverse
    mapping (:func:`audit_primary_key`) is exact."""
    return f"{AUDIT_DIGESTS_PREFIX}{key}.json"


def audit_primary_key(sidecar_key: str) -> str | None:
    """Inverse of :func:`audit_digest_key`, or None for a key that is
    not a well-formed sidecar key."""
    if not sidecar_key.startswith(AUDIT_DIGESTS_PREFIX) or not (
        sidecar_key.endswith(".json")
    ):
        return None
    return sidecar_key[len(AUDIT_DIGESTS_PREFIX):-len(".json")]


#: suffix distinguishing a quarantine METADATA document from the
#: quarantined payload sitting next to it
QUARANTINE_META_SUFFIX = ".quarantine.json"


def quarantine_key(key: str) -> str:
    """Where the fsck repair planner parks corrupt bytes found at
    ``key`` — the original key path mirrored under ``quarantine/``."""
    return f"{QUARANTINE_PREFIX}{key}"


def quarantine_meta_key(key: str) -> str:
    """The metadata document describing the quarantined bytes of
    ``key`` (finding kind, digest of the corrupt payload)."""
    return f"{QUARANTINE_PREFIX}{key}{QUARANTINE_META_SUFFIX}"


def flight_record_key(seq: int, verdict: str, doc_digest: str) -> str:
    """Where one flight-recorder dump lands. ``seq`` (the count of
    dumps already stored — no wall clock, the chaos twins' determinism
    discipline) leads the name so a lexicographic listing IS write
    order; the content digest fragment keeps concurrent writers'
    distinct documents collision-free, and the verdict reads at a
    glance in an operator's listing."""
    fragment = doc_digest.removeprefix("sha256:")[:16]
    return f"{FLIGHTREC_PREFIX}flight-{seq:06d}-{verdict}-{fragment}.json"
