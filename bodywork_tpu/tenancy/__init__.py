"""Multi-tenant model fleet layer.

One device pool serving many pipelines: tenant-namespaced artefact
lifecycle (:mod:`.namespace`), stacked single-dispatch serving for many
same-architecture tenants (:mod:`.stacked`), a declarative scenario zoo
giving each tenant its own data distribution and traffic shape
(:mod:`.scenarios`), fair round-robin scheduling of per-tenant retrain
jobs (:mod:`.scheduler`), and the seeded fleet simulation that proves
zero cross-tenant blast radius under per-tenant chaos (:mod:`.fleet`).

Import discipline: :mod:`.namespace`, :mod:`.scenarios`, and
:mod:`.scheduler` are jax-free (importable by front-end processes and
the cli without pulling in a device runtime); :mod:`.stacked` and
:mod:`.fleet` own the jax-facing pieces. This package ``__init__``
therefore re-exports only the jax-free surface.
"""
from bodywork_tpu.tenancy.namespace import (  # noqa: F401
    TENANT_ENV,
    TenantStore,
    list_tenants,
    scoped_store,
    tenant_from_env,
)
from bodywork_tpu.tenancy.scenarios import (  # noqa: F401
    SCENARIOS,
    TRAFFIC_SHAPES,
    TenantSpec,
    traffic_profile,
    zoo,
)
from bodywork_tpu.tenancy.scheduler import FairScheduler  # noqa: F401
