"""The multi-tenant fleet simulation: N pipelines, one store, chaos on one.

``run_fleet_sim`` drives every tenant's daily pipeline against ONE
shared store through tenant-scoped views (``tenants/<id>/`` — see
:mod:`.namespace`), interleaved by the fair round-robin scheduler
(:mod:`.scheduler`), each tenant with its own scenario-zoo generator
(:mod:`.scenarios`). Optionally one tenant is sabotaged: its final
day's training data is NaN-poisoned at the artefact layer, so its last
candidate trains to non-finite metrics and the day's registry gate must
REJECT it (production stays on the prior healthy model — the
auto-rollback contract).

The acceptance proof is byte-identity with SOLO twins: every
non-sabotaged tenant's pipeline is re-run alone, in a fresh dedicated
store, through the EXACT same per-day driver — and its final artefacts
must compare byte-identical (``chaos.sim.compare_stores``) to its
namespace inside the shared fleet store. Any cross-tenant leak —
through a shared cache, a mis-scoped key, a scheduler-order
dependency, or the sabotaged tenant's blast radius — breaks identity
somewhere. Both runs are pure functions of (spec tuple, start, days),
so the sim is a seeded PASS/FAIL, not a probability.
"""
from __future__ import annotations

from datetime import date, timedelta
from pathlib import Path

from bodywork_tpu.store.filesystem import FilesystemStore
from bodywork_tpu.tenancy.namespace import scoped_store
from bodywork_tpu.tenancy.scenarios import TenantSpec
from bodywork_tpu.tenancy.scheduler import FairScheduler
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tenancy.fleet")

__all__ = ["run_fleet_sim", "sabotage_dataset_nan"]


def sabotage_dataset_nan(store, key: str) -> None:
    """NaN-poison every label of a persisted dataset CSV, in place —
    the per-tenant chaos fault: the tenant's next retrain folds the
    poisoned day in and trains to non-finite metrics, which the daily
    registry gate must catch (finite-metrics check) before the
    candidate can ever serve."""
    text = store.get_bytes(key).decode("utf-8")
    lines = text.splitlines()
    out = [lines[0]]
    for line in lines[1:]:
        if not line:
            continue
        x, _, _rest = line.partition(",")
        out.append(f"{x},nan")
    store.put_bytes(key, ("\n".join(out) + "\n").encode("utf-8"))
    log.warning(f"sabotaged dataset {key}: all labels -> NaN")


class _TenantPipeline:
    """One tenant's day-by-day pipeline driver.

    The SAME class drives the fleet run (interleaved with other
    tenants) and each solo twin (alone in its own store) — byte-identity
    between them is then a property of the pipeline's determinism, not
    of two different harness code paths happening to agree."""

    def __init__(self, spec: TenantSpec, store, model_type: str,
                 scoring_mode: str):
        from bodywork_tpu.chaos.sim import _apply_train_mode
        from bodywork_tpu.pipeline import LocalRunner, default_pipeline

        self.spec = spec
        self.store = store
        self.runner = LocalRunner(
            _apply_train_mode(
                default_pipeline(model_type, scoring_mode), "full"
            ),
            store,
            drift=spec.drift_config(),
        )
        self.days_run = 0
        self.results = []

    def start(self, start_day: date) -> None:
        self.start_day = start_day
        self.runner.bootstrap(start_day)

    def run_next_day(self) -> None:
        today = self.start_day + timedelta(days=self.days_run)
        self.results.append(
            self.runner.run_day(today, lookahead_train=False)
        )
        self.days_run += 1

    def finish(self) -> None:
        """The end-of-simulation consolidation ``run_simulation`` does:
        drain the background compactor, then top up the final snapshot."""
        if not self.runner._drain_compactor():
            return
        try:
            from bodywork_tpu.data.snapshot import refresh_due, write_snapshot

            if refresh_due(self.store):
                write_snapshot(self.store)
        except Exception as exc:  # cold readers keep the old snapshot
            log.warning(f"final snapshot refresh failed (non-fatal): {exc!r}")

    def latest_dataset_key(self) -> str:
        from bodywork_tpu.store.schema import DATASETS_PREFIX

        key, _ = self.store.latest(DATASETS_PREFIX)
        return key


def _tenant_days(spec: TenantSpec, days: int) -> int:
    """How many pipeline days a tenant runs in a ``days``-tick fleet:
    label-delayed tenants start late (their labels haven't landed), so
    they run fewer days — the solo twin runs the same count."""
    return max(1, days - spec.effective_label_delay)


def run_fleet_sim(
    root: str | Path,
    start: date,
    days: int,
    specs: tuple[TenantSpec, ...],
    sabotage_tenant: str | None = None,
    model_type: str = "linear",
    scoring_mode: str = "batch",
) -> dict:
    """Run the fleet + its solo twins and return the full comparison.

    Layout under ``root``: ``fleet/`` is the one shared store every
    tenant lives in (under ``tenants/<id>/``); ``solo/<id>/`` is each
    non-sabotaged tenant's dedicated-store twin. ``sabotage_tenant``
    names the tenant whose final training day is NaN-poisoned; its
    registry must reject the poisoned candidate (``gate_rejected`` in
    the summary) and every OTHER tenant must stay byte-identical to its
    twin (``comparisons[tenant]["ok"]``) — zero cross-tenant blast
    radius. Everything is a pure function of the arguments.
    """
    from bodywork_tpu.chaos.sim import compare_stores
    from bodywork_tpu.obs.tracing import configured_tracing

    if sabotage_tenant is not None and sabotage_tenant not in {
        s.tenant_id for s in specs
    }:
        raise ValueError(
            f"sabotage tenant {sabotage_tenant!r} not in the fleet "
            f"({sorted(s.tenant_id for s in specs)})"
        )
    root = Path(root)
    fleet_dir = root / "fleet"
    if fleet_dir.exists() and any(fleet_dir.iterdir()):
        raise ValueError(
            f"fleet sim target {fleet_dir} already holds artefacts; "
            "point --store at a fresh directory"
        )
    fleet_store = FilesystemStore(fleet_dir)
    scheduler = FairScheduler()
    pipelines: dict[str, _TenantPipeline] = {}

    log.info(
        f"fleet run: {len(specs)} tenant(s) x {days} day(s) -> {fleet_dir}"
        + (f" (sabotaging {sabotage_tenant!r})" if sabotage_tenant else "")
    )
    with configured_tracing(0.0):
        for spec in specs:
            pipelines[spec.tenant_id] = _TenantPipeline(
                spec, scoped_store(fleet_store, spec.tenant_id),
                model_type, scoring_mode,
            )
        for tick in range(days):
            # due = tenants whose label delay has elapsed and that still
            # have pipeline days left; the round-robin head rotates per
            # tick so no tenant systematically retrains last
            due = [
                s.tenant_id for s in specs
                if tick >= s.effective_label_delay
                and pipelines[s.tenant_id].days_run < _tenant_days(s, days)
            ]
            for tenant_id in scheduler.order(due):
                pipe = pipelines[tenant_id]
                if pipe.days_run == 0:
                    pipe.start(start)
                if (
                    sabotage_tenant == tenant_id
                    and pipe.days_run
                    == _tenant_days(pipe.spec, days) - 1
                    and pipe.days_run > 0
                ):
                    # poison the newest dataset right before the final
                    # day's retrain folds it in
                    sabotage_dataset_nan(
                        pipe.store, pipe.latest_dataset_key()
                    )
                pipe.run_next_day()
        for pipe in pipelines.values():
            pipe.finish()

    # -- the sabotaged tenant's registry verdict ---------------------------
    gate_rejected = None
    production_held = None
    if sabotage_tenant is not None:
        from bodywork_tpu.registry import ModelRegistry

        reg = ModelRegistry(pipelines[sabotage_tenant].store)
        records = {r["model_key"]: r for r in reg.records()}
        rejected = [
            k for k, r in records.items() if r.get("status") == "rejected"
        ]
        production = reg.resolve("production")
        gate_rejected = bool(rejected)
        # production must still be a FINITE model from before the
        # sabotage — i.e. not one of the rejected keys
        production_held = (
            production is not None and production not in rejected
        )

    # -- solo twins: every healthy tenant, same driver, fresh store --------
    comparisons: dict[str, dict] = {}
    with configured_tracing(0.0):
        for spec in specs:
            if spec.tenant_id == sabotage_tenant:
                continue
            solo_dir = root / "solo" / spec.tenant_id
            log.info(f"solo twin: {spec.tenant_id} -> {solo_dir}")
            solo = _TenantPipeline(
                spec, FilesystemStore(solo_dir), model_type, scoring_mode
            )
            solo.start(start)
            for _ in range(_tenant_days(spec, days)):
                solo.run_next_day()
            solo.finish()
            comparisons[spec.tenant_id] = compare_stores(
                solo.store, pipelines[spec.tenant_id].store
            )

    ok = all(c["ok"] for c in comparisons.values()) and (
        sabotage_tenant is None or (gate_rejected and production_held)
    )
    return {
        "tenants": [s.tenant_id for s in specs],
        "days": days,
        "sabotage_tenant": sabotage_tenant,
        "gate_rejected": gate_rejected,
        "production_held": production_held,
        "comparisons": comparisons,
        "ok": ok,
    }
