"""Tenant-namespaced store views.

The whole lifecycle becomes multi-tenant through ONE construction: a
:class:`TenantStore` is a transparent wrapper that rebases every key and
prefix under ``tenants/<id>/`` (``schema.tenant_prefix``), so training,
registry, journals, snapshots, audit sidecars, and tuned configs are
tenant-aware without any of them learning a tenant argument — each
subsystem keeps speaking the root key grammar against a scoped view.

The reserved ``default`` tenant is the identity: :func:`scoped_store`
returns the store UNWRAPPED, so the pre-tenancy single-tenant deployment
is byte-for-byte the default tenant and every existing artefact, test,
and committed bench record holds unchanged.

Listing stays prefix-bounded: ``list_keys(p)`` on a scoped view maps to
``list_keys("tenants/<id>/" + p)`` on the backend, so one tenant's
registry-record listing costs O(records-for-that-tenant) backend work,
never O(records-ever) across the fleet (op-budget-pinned by
tests/test_tenancy.py).
"""
from __future__ import annotations

import os

from bodywork_tpu.store.base import ArtefactStore, DelegatingStore
from bodywork_tpu.store.schema import (
    DEFAULT_TENANT,
    TENANTS_PREFIX,
    TENANT_ID_PATTERN,
    tenant_prefix,
    validate_tenant_id,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tenancy.namespace")

#: pod-environment knob selecting the tenant a stage container works
#: for — the tenant analogue of ``BODYWORK_TPU_TRAIN_MODE``, parsed with
#: the same malformed-degrades contract (:func:`tenant_from_env`)
TENANT_ENV = "BODYWORK_TPU_TENANT"


class TenantStore(DelegatingStore):
    """A store view scoped to one tenant's namespace.

    Every key/prefix is rebased under ``tenants/<id>/`` on the way in
    and stripped on the way out, so callers see a store that looks
    exactly like a dedicated single-tenant deployment. Derives from
    :class:`DelegatingStore` so the backend's ``get_many`` parallelism,
    CAS protocol, and op instrumentation survive the wrapper.

    ``mutable_cache`` is namespaced per tenant (while still living on
    the one long-lived backend object): two tenants share logical key
    names with different content, so a shared parsed-dataset cache
    would serve one tenant's rows to another.
    """

    def __init__(self, inner: ArtefactStore, tenant_id: str):
        super().__init__(inner)
        self.tenant_id = validate_tenant_id(tenant_id)
        self._prefix = tenant_prefix(tenant_id)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TenantStore({self._inner!r}, tenant={self.tenant_id!r})"

    def _rebase(self, key: str) -> str:
        return f"{self._prefix}{key}"

    def _strip(self, key: str) -> str:
        return key[len(self._prefix):]

    def put_bytes(self, key: str, data: bytes) -> None:
        self._inner.put_bytes(self._rebase(key), data)

    def put_bytes_if_match(self, key: str, data: bytes, expected_token=None):
        return self._inner.put_bytes_if_match(
            self._rebase(key), data, expected_token
        )

    def get_bytes(self, key: str) -> bytes:
        return self._inner.get_bytes(self._rebase(key))

    def list_keys(self, prefix: str = "") -> list[str]:
        # prefix-bounded on the backend: the tenant-qualified prefix goes
        # DOWN so the backend walks only this tenant's subtree
        return [
            self._strip(k)
            for k in self._inner.list_keys(self._rebase(prefix))
        ]

    def delete(self, key: str) -> None:
        self._inner.delete(self._rebase(key))

    def exists(self, key: str) -> bool:
        return self._inner.exists(self._rebase(key))

    def get_many(self, keys: list[str]) -> dict[str, bytes]:
        got = self._inner.get_many([self._rebase(k) for k in keys])
        return {self._strip(k): v for k, v in got.items()}

    def version_token(self, key: str):
        return self._inner.version_token(self._rebase(key))

    def version_tokens(self, keys: list[str]) -> dict[str, object]:
        got = self._inner.version_tokens([self._rebase(k) for k in keys])
        return {self._strip(k): v for k, v in got.items()}

    def mutable_cache(self, name: str) -> dict:
        return self._inner.mutable_cache(f"{self._prefix}{name}")


def scoped_store(store: ArtefactStore, tenant_id: str) -> ArtefactStore:
    """``store`` viewed through ``tenant_id``'s namespace.

    The reserved ``default`` tenant returns ``store`` unwrapped — the
    identity that keeps every pre-tenancy key byte-identical. Scoping an
    already-scoped view nests (``tenants/a/tenants/b/...``), which the
    key grammar permits but nothing in the framework produces; callers
    scope the root store exactly once, at store-open time (``cli
    --tenant`` / ``BODYWORK_TPU_TENANT``).
    """
    validate_tenant_id(tenant_id)
    if tenant_id == DEFAULT_TENANT:
        return store
    return TenantStore(store, tenant_id)


def tenant_of(store: ArtefactStore) -> str:
    """The tenant a store view is scoped to (``default`` for any store
    that is not a :class:`TenantStore`) — the label value for
    tenant-labelled metric families."""
    while store is not None:
        if isinstance(store, TenantStore):
            return store.tenant_id
        store = getattr(store, "_inner", None)
    return DEFAULT_TENANT


def tenant_from_env(environ=None) -> str:
    """The deployed tenant id from the pod environment (:data:`TENANT_ENV`).

    The k8s stage manifests materialise the tenant as an env var so one
    image serves every tenant. Malformed values degrade to ``default``
    with a warning — the same contract as every other env knob
    (``stages._train_env_mode``): a typo must never crash the pod, and
    degrading to the default tenant can only ever touch the operator's
    own root namespace, never another tenant's. Guard-pinned identical
    to the cli ``--tenant`` validation and the schema key charset by
    tests/test_tenancy.py.
    """
    env = os.environ if environ is None else environ
    raw = env.get(TENANT_ENV, "").strip()
    if not raw:
        return DEFAULT_TENANT
    try:
        return validate_tenant_id(raw)
    except ValueError:
        log.warning(
            f"ignoring {TENANT_ENV}={raw!r} "
            f"(want lowercase DNS-label, pattern {TENANT_ID_PATTERN.pattern})"
        )
        return DEFAULT_TENANT


def list_tenants(store: ArtefactStore) -> list[str]:
    """Every tenant id with at least one artefact under ``tenants/``,
    sorted. Subtrees whose id segment fails validation are skipped (they
    cannot have been written through :func:`scoped_store`); the
    ``default`` tenant is NOT listed — its namespace is the root itself,
    so presence there is not evidence of fleet membership."""
    seen = set()
    for key in store.list_keys(TENANTS_PREFIX):
        segment = key[len(TENANTS_PREFIX):].split("/", 1)[0]
        if segment in seen:
            continue
        if TENANT_ID_PATTERN.match(segment) and "--" not in segment:
            seen.add(segment)
    return sorted(seen)
