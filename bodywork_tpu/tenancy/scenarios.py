"""The scenario zoo: declarative per-tenant data and traffic shapes.

A :class:`TenantSpec` names a tenant, a DATA scenario, and a TRAFFIC
shape; everything downstream derives deterministically from the spec —
the data scenario maps to a :class:`~bodywork_tpu.data.drift_config.DriftConfig`
(pure function of the spec, so a tenant's fleet run and its solo twin
generate byte-identical datasets), and the traffic shape maps to a
per-tick request-rate profile for the serving harness. jax-free: specs
are carried by runners, front-ends, and the cli.

Data scenarios (all ride the existing seeded generator — distinct
tenants differ only through their derived config, never through code
paths, which is what makes the byte-identity soak meaningful):

- ``baseline``             the reference distribution, tenant-seeded
- ``covariate-shift``      the X window slides up-range, so a model
                           trained on another tenant's support is wrong
                           here — the classic serving-skew scenario
- ``seasonality``          strong fast intercept oscillation (drift
                           pressure: models age out within days)
- ``heteroscedastic``      noise scale ramps 1x→3x across the X range
- ``label-delay``          baseline data whose labels arrive
                           ``label_delay_days`` late — the retrain
                           scheduler may only train on days whose labels
                           have landed

Traffic shapes (request-rate multipliers per tick, mean 1.0 except
where the shape's point is the excursion):

- ``steady``       flat 1.0
- ``flash-crowd``  a burst window at ``burst_x`` times base rate —
                   stresses admission sub-budgets and coalescing
- ``retry-storm``  after a trigger tick, excess load decays
                   geometrically — the thundering-herd-with-backoff
                   shape a breached tenant emits
- ``diurnal``      sinusoidal day cycle (the classic serving load curve)
"""
from __future__ import annotations

import dataclasses
import math

from bodywork_tpu.data.drift_config import DriftConfig
from bodywork_tpu.store.schema import validate_tenant_id

#: the data scenarios the zoo knows, in catalogue order
SCENARIOS = (
    "baseline",
    "covariate-shift",
    "seasonality",
    "heteroscedastic",
    "label-delay",
)

#: the traffic shapes the zoo knows
TRAFFIC_SHAPES = ("steady", "flash-crowd", "retry-storm", "diurnal")

#: deterministic per-tenant seed derivation: fold the tenant id into the
#: base seed via a stable string hash (NOT Python's salted ``hash``)
_SEED_MOD = 2**31 - 1


def _tenant_seed(tenant_id: str, base_seed: int) -> int:
    h = 0
    for ch in tenant_id.encode("utf-8"):
        h = (h * 131 + ch) % _SEED_MOD
    return (base_seed * 1_000_003 + h) % _SEED_MOD


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's declarative scenario assignment.

    Frozen and jax-free, like :class:`DriftConfig`; the whole fleet
    simulation is a pure function of a tuple of these plus a start date.
    """

    tenant_id: str
    scenario: str = "baseline"
    traffic: str = "steady"
    #: folded with the tenant id into every derived seed, so two fleets
    #: with different base seeds are independent draws end to end
    base_seed: int = 42
    #: rows per simulated day (smaller than the default 1440 keeps
    #: multi-tenant sims cheap)
    n_samples: int = 24 * 60
    #: days between a row being observable (X) and its label (y) landing
    #: — only meaningful for the ``label-delay`` scenario
    label_delay_days: int = 0
    #: flash-crowd burst multiple over base rate
    burst_x: float = 4.0

    def __post_init__(self):
        validate_tenant_id(self.tenant_id)
        if self.scenario not in SCENARIOS:
            raise ValueError(
                f"unknown scenario {self.scenario!r} (want one of {SCENARIOS})"
            )
        if self.traffic not in TRAFFIC_SHAPES:
            raise ValueError(
                f"unknown traffic shape {self.traffic!r} "
                f"(want one of {TRAFFIC_SHAPES})"
            )

    @property
    def seed(self) -> int:
        return _tenant_seed(self.tenant_id, self.base_seed)

    @property
    def effective_label_delay(self) -> int:
        if self.scenario == "label-delay":
            return max(1, self.label_delay_days)
        return max(0, self.label_delay_days)

    def drift_config(self) -> DriftConfig:
        """The tenant's generative model — a pure function of the spec.

        Every scenario derives from the reference distribution by
        parameter changes only, so the generator code path (and its
        seeded determinism) is shared by the whole fleet.
        """
        base = dict(n_samples=self.n_samples, seed=self.seed)
        if self.scenario == "covariate-shift":
            # the X support slides up-range: same slope, disjoint tail
            return DriftConfig(x_low=60.0, x_high=160.0, **base)
        if self.scenario == "seasonality":
            # fast, deep intercept oscillation: ~2.8-day period at the
            # reference's day-of-year clock, amplitude 4x the reference
            return DriftConfig(freq=130.0, amplitude=2.0, kappa=2.0, **base)
        if self.scenario == "heteroscedastic":
            return DriftConfig(hetero=2.0, **base)
        # baseline and label-delay share the reference distribution —
        # label delay is a SCHEDULING property, not a data property
        return DriftConfig(**base)


def traffic_profile(
    spec: TenantSpec, n_ticks: int, base_rps: float = 100.0
) -> list[float]:
    """The tenant's request rate per tick, as absolute rps.

    Deterministic in the spec (burst placement derives from the tenant
    seed), so load harness runs are replayable. ``n_ticks`` is whatever
    granularity the harness drives at — the shapes are resolution-free.
    """
    seed = spec.seed
    out = []
    for t in range(n_ticks):
        if spec.traffic == "steady":
            mult = 1.0
        elif spec.traffic == "flash-crowd":
            # one burst window, ~15% of the run, placed by the seed
            start = seed % max(1, int(n_ticks * 0.7))
            width = max(1, int(n_ticks * 0.15))
            mult = spec.burst_x if start <= t < start + width else 1.0
        elif spec.traffic == "retry-storm":
            # trigger at ~1/3 through, then geometric decay of the
            # excess (clients retrying with backoff)
            trigger = n_ticks // 3
            if t < trigger:
                mult = 1.0
            else:
                mult = 1.0 + (spec.burst_x - 1.0) * (0.7 ** (t - trigger))
        else:  # diurnal
            mult = 1.0 + 0.6 * math.sin(2.0 * math.pi * t / max(1, n_ticks))
        out.append(base_rps * mult)
    return out


def zoo(n_tenants: int, base_seed: int = 42, n_samples: int = 24 * 60) -> tuple:
    """A default fleet: ``n_tenants`` specs cycling through the scenario
    and traffic catalogues — the quickest way to a diverse fleet for
    sims and benches (``tenant-00`` is always baseline/steady)."""
    specs = []
    for i in range(n_tenants):
        specs.append(
            TenantSpec(
                tenant_id=f"tenant-{i:02d}",
                scenario=SCENARIOS[i % len(SCENARIOS)],
                traffic=TRAFFIC_SHAPES[i % len(TRAFFIC_SHAPES)],
                base_seed=base_seed,
                n_samples=n_samples,
                label_delay_days=1 if SCENARIOS[i % len(SCENARIOS)] == "label-delay" else 0,
            )
        )
    return tuple(specs)
