"""Fair scheduling of per-tenant retrain jobs.

One device pool retrains the whole fleet; without an explicit policy the
tenant that happens to sort first (or shout loudest) would starve the
rest. :class:`FairScheduler` is deliberately simple and deterministic —
round-robin over tenants with a persistent rotating head — because the
fleet sim's byte-identity proofs require the schedule to be a pure
function of (tenant set, tick), never of wall clock or arrival jitter.

Jax-free; the runner and the cli import it freely.
"""
from __future__ import annotations


class FairScheduler:
    """Deterministic round-robin over a (possibly changing) tenant set.

    Each call to :meth:`order` returns every due tenant exactly once,
    with the head of the line advancing one position per tick — so over
    any window of N ticks, each of N tenants goes first exactly once
    (no tenant's retrain systematically lands last, where a budget or
    deadline overrun would hit it). Tenants admitted mid-flight join in
    sorted position and inherit the rotation; departed tenants drop out
    without disturbing the others' relative order.
    """

    def __init__(self) -> None:
        self._tick = 0

    def order(self, tenants) -> list[str]:
        """The service order for this tick; advances the rotation."""
        ring = sorted(set(tenants))
        if not ring:
            return []
        k = self._tick % len(ring)
        self._tick += 1
        return ring[k:] + ring[:k]

    def peek(self, tenants) -> list[str]:
        """The order :meth:`order` WOULD return, without advancing."""
        ring = sorted(set(tenants))
        if not ring:
            return []
        k = self._tick % len(ring)
        return ring[k:] + ring[:k]
