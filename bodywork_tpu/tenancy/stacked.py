"""Stacked multi-tenant serving: many same-architecture MLPs, ONE dispatch.

The device-dispatch path sustains ~2M rows/s against a ~1.5k rps
ingress — >99% idle headroom that many small models can share. A
:class:`StackedMLPPredictor` pytree-stacks up to ``capacity`` tenants'
params along a leading tenant axis and scores a coalesced multi-tenant
batch ``[capacity, rows, features]`` in one compiled executable, riding
the process-wide AOT cache (:data:`~bodywork_tpu.serve.predictor.EXECUTABLE_CACHE`)
keyed by (architecture, stack shape) — NOT by which tenants occupy the
slots, so admission, eviction, and re-admission are pure data movement:
zero new compiles (pinned by tests/test_tenancy.py).

Two stacking programs:

- ``scan`` (default): ``lax.scan`` of the plain per-tenant apply over
  the tenant axis inside one executable. One device dispatch, and each
  tenant's rows go through the EXACT scalar program the solo
  :class:`~bodywork_tpu.serve.predictor.PaddedPredictor` runs — outputs
  are byte-identical to each tenant's solo predictor (the acceptance
  bar, and the property the cross-tenant chaos proofs lean on).
- ``vmap``: ``jax.vmap`` over the tenant axis — the batched-GEMM form
  that pays off on a real MXU, at the cost of exact bitwise equality
  with the solo program (batched ``dot_general`` may reduce in a
  different order; measured ~4e-6 relative on CPU). Opt-in for
  throughput; quality gates treat it like a quantized engine.

Residency is LRU beyond the stack budget: slot state lives host-side,
the stacked device tree is rebuilt on residency changes (cold path),
and the hot path never moves params. ``canary_slots`` reserves stack
capacity for canary admissions so a fleet-wide flash crowd cannot evict
an in-flight canary; per-tenant admission sub-budgets bound how much of
a stacked batch one tenant may fill (the fleet analogue of the global
admission budget).
"""
from __future__ import annotations

import threading
from collections import OrderedDict

import numpy as np

from bodywork_tpu.serve.predictor import (
    EXECUTABLE_CACHE,
    params_shape_digest,
    _donate_inputs,
    _leaf_struct,
)
from bodywork_tpu.store.schema import validate_tenant_id
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tenancy.stacked")

#: the stacking programs (see module docstring); guard-pinned against
#: the constructor's validation by tests/test_tenancy.py
STACK_MODES = ("scan", "vmap")

#: default row buckets for the per-tenant axis — smaller than the solo
#: ladder's because a stacked batch multiplies rows by capacity
DEFAULT_STACK_BUCKETS = (8, 64, 512)


class TenantNotResident(KeyError):
    """The tenant has no stack slot (admit before dispatch)."""


class TenantOverBudget(RuntimeError):
    """A tenant's rows exceed its per-tenant admission sub-budget."""


class StackNotCompatible(ValueError):
    """An admitted model's architecture differs from the stack's."""


def _tenancy_metrics():
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    return (
        reg.counter(
            "bodywork_tpu_tenant_rows_total",
            "Rows scored through the stacked multi-tenant dispatch, "
            "by tenant",
        ),
        reg.counter(
            "bodywork_tpu_tenant_stack_dispatches_total",
            "Stacked multi-tenant device dispatches (each scores every "
            "occupied slot's rows in one executable call)",
        ),
        reg.counter(
            "bodywork_tpu_tenant_evictions_total",
            "Tenants evicted from the params stack under residency "
            "pressure, by tenant",
        ),
        reg.counter(
            "bodywork_tpu_tenant_admission_rejected_total",
            "Multi-tenant rows rejected by a per-tenant admission "
            "sub-budget, by tenant",
        ),
        reg.gauge(
            "bodywork_tpu_tenant_resident_count",
            "Tenants currently resident in the params stack",
        ),
    )


class StackedMLPPredictor:
    """Score N same-architecture tenants' MLPs in one device dispatch.

    ``capacity`` is the stack budget (slots); it is FIXED for the life
    of the predictor — every executable is lowered at
    ``[capacity, bucket, features]``, so residency churn never changes a
    program shape and therefore never compiles. ``canary_slots`` of
    that capacity are reserved for ``admit(..., canary=True)``.
    ``row_budget`` bounds rows per tenant per dispatch (the per-tenant
    admission sub-budget); None = the largest bucket.
    """

    dtype = "float32"

    def __init__(
        self,
        capacity: int,
        buckets: tuple[int, ...] = DEFAULT_STACK_BUCKETS,
        stack_mode: str = "scan",
        canary_slots: int = 0,
        row_budget: int | None = None,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if stack_mode not in STACK_MODES:
            raise ValueError(
                f"unknown stack_mode {stack_mode!r} (want one of {STACK_MODES})"
            )
        if not 0 <= canary_slots < capacity:
            raise ValueError(
                f"canary_slots must leave at least one regular slot "
                f"(capacity={capacity}, canary_slots={canary_slots})"
            )
        self.capacity = capacity
        self.buckets = tuple(sorted(buckets))
        self.stack_mode = stack_mode
        self.canary_slots = canary_slots
        self.row_budget = row_budget if row_budget else self.buckets[-1]
        self._lock = threading.RLock()
        #: tenant -> slot index, in LRU order (oldest first); canary
        #: residents are tracked in the same map with their flag below
        self._slots: OrderedDict[str, int] = OrderedDict()
        self._canary: set[str] = set()
        #: slot index -> host params tree (numpy leaves); None = free
        self._slot_params: list = [None] * capacity
        self._arch_digest = None
        self._n_features: int | None = None
        #: the device-resident stacked tree, rebuilt on residency change
        self._stacked = None
        self._compiled: dict[tuple, object] = {}
        self._metrics = None

    # -- residency ---------------------------------------------------------
    def _obs(self):
        if self._metrics is None:
            self._metrics = _tenancy_metrics()
        return self._metrics

    def resident(self) -> tuple[str, ...]:
        """Resident tenants, LRU-oldest first."""
        with self._lock:
            return tuple(self._slots)

    def is_resident(self, tenant_id: str) -> bool:
        with self._lock:
            return tenant_id in self._slots

    def _slot_budget(self, canary: bool) -> int:
        return self.canary_slots if canary else self.capacity - self.canary_slots

    def _host_params(self, model):
        import jax

        return jax.tree_util.tree_map(np.asarray, model.params)

    def admit(self, tenant_id: str, model, canary: bool = False) -> int:
        """Give ``tenant_id`` a stack slot holding ``model``'s params,
        evicting the least-recently-used tenant of the same class
        (regular/canary) if that class's slots are full. Returns the
        slot index. Idempotent for a resident tenant (refreshes params
        in place and touches LRU order). Raises
        :class:`StackNotCompatible` for a model whose architecture
        differs from the stack's."""
        validate_tenant_id(tenant_id)
        from bodywork_tpu.models.mlp import MLPRegressor

        if not isinstance(model, MLPRegressor):
            raise StackNotCompatible(
                f"stacked serving is MLP-only; got {model.info}"
            )
        if model.params is None:
            # fit() returns a NEW fitted model; admitting the unfitted
            # receiver would silently occupy no slot and break warmup
            raise StackNotCompatible(
                f"tenant {tenant_id!r} model is unfitted (params=None) "
                "— did you drop fit()'s return value?"
            )
        host = self._host_params(model)
        digest = params_shape_digest(host)
        with self._lock:
            if self._arch_digest is None:
                self._arch_digest = digest
                self._n_features = model.n_features or 1
            elif digest != self._arch_digest:
                raise StackNotCompatible(
                    f"tenant {tenant_id!r} params architecture differs "
                    "from the resident stack's (same-arch stacking only)"
                )
            if tenant_id in self._slots:
                slot = self._slots[tenant_id]
                self._slots.move_to_end(tenant_id)
                self._slot_params[slot] = host
                self._canary.discard(tenant_id)
                if canary:
                    self._canary.add(tenant_id)
                self._rebuild_stack()
                return slot
            # evict within the admission class if its slots are full
            peers = [
                t for t in self._slots if (t in self._canary) == canary
            ]
            if len(peers) >= self._slot_budget(canary):
                victim = peers[0]  # OrderedDict iterates LRU-oldest first
                slot = self._evict_locked(victim)
            else:
                slot = next(
                    i for i, p in enumerate(self._slot_params) if p is None
                )
            self._slots[tenant_id] = slot
            if canary:
                self._canary.add(tenant_id)
            self._slot_params[slot] = host
            self._rebuild_stack()
            self._obs()[4].set(len(self._slots))
            return slot

    def evict(self, tenant_id: str) -> None:
        """Free ``tenant_id``'s slot (no-op when not resident)."""
        with self._lock:
            if tenant_id in self._slots:
                self._evict_locked(tenant_id)
                self._rebuild_stack()
                self._obs()[4].set(len(self._slots))

    def _evict_locked(self, tenant_id: str) -> int:
        slot = self._slots.pop(tenant_id)
        self._slot_params[slot] = None
        self._canary.discard(tenant_id)
        self._obs()[2].inc(tenant=tenant_id)
        log.info(f"evicted tenant {tenant_id!r} from stack slot {slot}")
        return slot

    def _rebuild_stack(self) -> None:
        """Re-stack the occupied slots' host params into the device tree.

        Residency changes are the COLD path: one host->device transfer
        of the (tiny) stacked params, never a compile — free slots are
        zero-filled so the stacked shape stays ``[capacity, ...]``
        regardless of occupancy."""
        import jax

        template = next(
            (p for p in self._slot_params if p is not None), None
        )
        if template is None:
            self._stacked = None
            return
        leaves_t, treedef = jax.tree_util.tree_flatten(template)
        per_slot = []
        for p in self._slot_params:
            per_slot.append(
                jax.tree_util.tree_leaves(p) if p is not None
                else [np.zeros_like(leaf) for leaf in leaves_t]
            )
        stacked_leaves = [
            jax.device_put(np.stack(group)) for group in zip(*per_slot)
        ]
        self._stacked = jax.tree_util.tree_unflatten(treedef, stacked_leaves)

    # -- the stacked program ----------------------------------------------
    def _stacked_fn(self):
        import jax

        from bodywork_tpu.models.mlp import mlp_apply

        if self.stack_mode == "vmap":
            return jax.vmap(mlp_apply)

        def scan_apply(stacked_params, xb):
            def body(carry, args):
                params, x = args
                return carry, mlp_apply(params, x)

            _, ys = jax.lax.scan(body, None, (stacked_params, xb))
            return ys

        return scan_apply

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _compiled_for(self, bucket: int):
        import jax

        n_features = self._n_features or 1
        handle = self._compiled.get(bucket)
        if handle is not None:
            return handle
        key = (
            type(self).__name__, "MLPRegressor", self.dtype,
            self.stack_mode, self._arch_digest,
            (self.capacity, bucket, n_features),
        )

        def build():
            structs = jax.tree_util.tree_map(_leaf_struct, self._stacked)
            x_struct = jax.ShapeDtypeStruct(
                (self.capacity, bucket, n_features), np.float32
            )
            donate = (1,) if _donate_inputs() else ()
            return (
                jax.jit(self._stacked_fn(), donate_argnums=donate)
                .lower(structs, x_struct)
                .compile()
            )

        handle = EXECUTABLE_CACHE.get(key, build)
        self._compiled[bucket] = handle
        return handle

    def warmup(self, sync: bool = True) -> None:
        """Compile and execute every bucket's stacked executable before
        taking traffic. Requires at least one resident tenant (the
        architecture is learned at first admission)."""
        with self._lock:
            if self._stacked is None:
                raise TenantNotResident(
                    "warmup needs at least one admitted tenant"
                )
            n_features = self._n_features or 1
            results = []
            for b in self.buckets:
                fn = self._compiled_for(b)
                results.append(
                    fn(
                        self._stacked,
                        np.zeros(
                            (self.capacity, b, n_features), dtype=np.float32
                        ),
                    )
                )
            if sync and results:
                from bodywork_tpu.utils.sync import fence

                fence(results)
        log.info(
            f"warmed stacked buckets {self.buckets} "
            f"(capacity={self.capacity}, mode={self.stack_mode})"
        )

    # -- dispatch ----------------------------------------------------------
    def predict_multi(
        self, batches: dict[str, np.ndarray]
    ) -> dict[str, np.ndarray]:
        """Score every tenant's rows in ONE device dispatch.

        ``batches`` maps resident tenant ids to their coalesced rows
        (``[n, features]`` or ``[n]``). Raises
        :class:`TenantNotResident` for an unadmitted tenant and
        :class:`TenantOverBudget` for a tenant exceeding its admission
        sub-budget — budget enforcement happens BEFORE any device work,
        so one greedy tenant cannot cost the others a dispatch."""
        if not batches:
            return {}
        rows_c, dispatch_c, _, rejected_c, _ = self._obs()
        with self._lock:
            if self._stacked is None:
                raise TenantNotResident(
                    f"no tenants resident; admit before dispatch: "
                    f"{sorted(batches)}"
                )
            n_features = self._n_features or 1
            prepared: dict[str, np.ndarray] = {}
            max_rows = 1
            for tenant_id, X in batches.items():
                if tenant_id not in self._slots:
                    raise TenantNotResident(
                        f"tenant {tenant_id!r} not resident "
                        f"(resident: {sorted(self._slots)})"
                    )
                X = np.asarray(X, dtype=np.float32)
                if X.ndim == 1:
                    X = X[:, None]
                if X.shape[0] > self.row_budget:
                    rejected_c.inc(
                        amount=X.shape[0] - self.row_budget, tenant=tenant_id
                    )
                    raise TenantOverBudget(
                        f"tenant {tenant_id!r}: {X.shape[0]} rows exceeds "
                        f"the per-tenant sub-budget ({self.row_budget})"
                    )
                prepared[tenant_id] = X
                max_rows = max(max_rows, X.shape[0])
            bucket = self._bucket_for(max_rows)
            Xb = np.zeros(
                (self.capacity, bucket, n_features), dtype=np.float32
            )
            for tenant_id, X in prepared.items():
                Xb[self._slots[tenant_id], : X.shape[0]] = X
            fn = self._compiled_for(bucket)
            out = np.asarray(fn(self._stacked, Xb))
            results = {}
            for tenant_id, X in prepared.items():
                results[tenant_id] = out[
                    self._slots[tenant_id], : X.shape[0]
                ]
                self._slots.move_to_end(tenant_id)
                rows_c.inc(amount=X.shape[0], tenant=tenant_id)
            dispatch_c.inc()
            return results

    def predict(self, tenant_id: str, X: np.ndarray) -> np.ndarray:
        """Single-tenant convenience over :meth:`predict_multi`."""
        return self.predict_multi({tenant_id: X})[tenant_id]
