from bodywork_tpu.traffic.generator import (
    ARRIVAL_PROCESSES,
    Request,
    TrafficConfig,
    generate_request_log,
    read_request_log,
    write_request_log,
)
from bodywork_tpu.traffic.runner import LoadReport, run_open_loop

__all__ = [
    "ARRIVAL_PROCESSES",
    "LoadReport",
    "Request",
    "TrafficConfig",
    "generate_request_log",
    "read_request_log",
    "run_open_loop",
    "write_request_log",
]
