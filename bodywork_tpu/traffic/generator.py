"""Seeded open-loop traffic generation (ROADMAP open item 2).

Every serving number this repo published before config 9 was
*closed-loop*: N clients each waiting for a response before sending the
next request. A closed-loop client can never overrun the server — its
request rate adapts to the server's service rate — so those numbers say
nothing about behaviour under *open-loop* load, where arrivals come from
the outside world at their own rate ("millions of users" do not
coordinate with the scoring service). This module generates the
open-loop side: a **request log** — the full arrival sequence with
per-request payloads — as a pure function of a seed, in the same spirit
as the chaos harness's seeded fault plans (``chaos.plan``): the same
seed replays the exact same traffic, regardless of what the server under
test does with it, which is what makes A/B runs (engine vs engine, knob
vs knob) comparisons rather than anecdotes.

Arrival processes (:data:`ARRIVAL_PROCESSES`):

- ``poisson`` — memoryless arrivals at a constant mean rate: the
  classic open-loop model, and the kindest realistic one (no burst
  structure beyond exponential clumping).
- ``mmpp`` — a 2-state Markov-modulated Poisson process: the process
  alternates between a *calm* and a *burst* state (exponentially
  distributed dwell times), each emitting Poisson arrivals at its own
  rate, with the burst state ``burst_multiplier`` times hotter. The
  time-averaged rate is still ``rate_rps`` — the same offered load as
  the Poisson case, delivered in squalls. This is the traffic shape
  that actually breaks queues: admission control that survives Poisson
  can still collapse under MMPP's sustained bursts.

The traffic *mix* models the two scoring shapes the service exposes:
each arrival is a single-row ``/score/v1`` request or (with probability
``batch_fraction``) a ``batch_rows``-row ``/score/v1/batch`` request.
Feature values are drawn uniform over the drift generator's [0, 100)
domain, so the server-side work per request matches the parity workload.

Request logs round-trip through JSONL files
(:func:`write_request_log` / :func:`read_request_log`) so a captured or
generated log can be replayed later — against a different engine, a
different build, or a production candidate — byte-for-byte.
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import numpy as np

from bodywork_tpu.utils.logging import get_logger

log = get_logger("traffic.generator")

__all__ = [
    "ARRIVAL_PROCESSES",
    "TRANSPORTS",
    "Request",
    "TrafficConfig",
    "generate_request_log",
    "read_request_log",
    "write_request_log",
]

#: supported arrival processes (kept in sync with ``cli traffic run
#: --arrival`` choices by tests/test_traffic.py)
ARRIVAL_PROCESSES = ("poisson", "mmpp")

#: supported wire encodings for the same request log (kept in sync with
#: ``cli traffic run --transport`` choices by tests): "json" is the
#: frozen /score contract body, "binary" the f32 row framing
#: (serve.wire.BINARY_CONTENT_TYPE) that skips JSON float formatting on
#: both ends — same schedule, same rows, different bytes on the wire
TRANSPORTS = ("json", "binary")

#: request-log file schema tag — readers refuse logs they would
#: misinterpret instead of replaying garbage traffic
LOG_SCHEMA = "bodywork_tpu.request_log/1"


@dataclasses.dataclass(frozen=True)
class Request:
    """One scheduled request: WHEN it arrives (offset from run start),
    WHERE it goes, and exactly WHAT it carries. Frozen: a log entry is
    a fact about the schedule, never mutated by a run."""

    t_s: float
    route: str  # "/score/v1" | "/score/v1/batch"
    x: tuple[float, ...]

    @property
    def rows(self) -> int:
        """Feature rows this request scores — the offered row-shape
        unit the tuner's bucket-ladder model conditions on
        (``tune/collect.py``). Single-row scoring sends one row no
        matter how many values ride the payload."""
        return len(self.x) if self.route.endswith("/batch") else 1

    def payload(self) -> bytes:
        """The HTTP body this request sends — built here so every
        replay of a log sends byte-identical requests."""
        if self.route == "/score/v1":
            return json.dumps({"X": [self.x[0]]}).encode()
        return json.dumps({"X": list(self.x)}).encode()

    def payload_binary(self) -> bytes:
        """The same request as binary row framing
        (``serve.wire.BINARY_CONTENT_TYPE``): the rows :meth:`payload`
        encodes as JSON, framed as little-endian f32 — what
        ``--transport binary`` puts on the wire. Deterministic for the
        same log entry, like :meth:`payload`."""
        from bodywork_tpu.serve.wire import encode_binary_rows

        if self.route == "/score/v1":
            return encode_binary_rows(np.asarray([self.x[0]]))
        return encode_binary_rows(np.asarray(self.x))


@dataclasses.dataclass(frozen=True)
class TrafficConfig:
    """The knobs a request log is generated from. Everything that
    shapes the sequence is HERE, so (config, seed) fully determines the
    log — the replayability contract."""

    rate_rps: float = 100.0
    duration_s: float = 5.0
    arrival: str = "poisson"
    #: probability an arrival is a /score/v1/batch request
    batch_fraction: float = 0.0
    #: rows per batch request
    batch_rows: int = 64
    seed: int = 0
    #: mmpp: burst-state arrival rate as a multiple of the calm rate
    burst_multiplier: float = 4.0
    #: mmpp: mean dwell seconds in (calm, burst) before switching
    dwell_s: tuple[float, float] = (1.0, 0.25)

    def validate(self) -> None:
        if self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {self.duration_s}"
            )
        if self.arrival not in ARRIVAL_PROCESSES:
            raise ValueError(
                f"arrival must be one of {ARRIVAL_PROCESSES}, "
                f"got {self.arrival!r}"
            )
        if not 0.0 <= self.batch_fraction <= 1.0:
            raise ValueError(
                f"batch_fraction must be in [0, 1], got {self.batch_fraction}"
            )
        if self.batch_rows < 1:
            raise ValueError(
                f"batch_rows must be >= 1, got {self.batch_rows}"
            )
        if self.burst_multiplier <= 0:
            raise ValueError(
                f"burst_multiplier must be > 0, got {self.burst_multiplier}"
            )
        if len(self.dwell_s) != 2 or any(d <= 0 for d in self.dwell_s):
            raise ValueError(
                f"dwell_s must be two positive means, got {self.dwell_s}"
            )

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["dwell_s"] = list(self.dwell_s)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "TrafficConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown traffic config field(s): {sorted(unknown)}"
            )
        if "dwell_s" in d:
            d = {**d, "dwell_s": tuple(d["dwell_s"])}
        config = cls(**d)
        config.validate()
        return config


def _poisson_arrivals(rng: np.random.Generator, rate: float,
                      duration: float) -> list[float]:
    times: list[float] = []
    t = rng.exponential(1.0 / rate)
    while t < duration:
        times.append(t)
        t += rng.exponential(1.0 / rate)
    return times


def _mmpp_arrivals(rng: np.random.Generator, config: TrafficConfig) -> list[float]:
    """2-state MMPP with the time-averaged rate pinned to ``rate_rps``:
    the calm rate is solved so that dwell-weighted mean(calm, burst)
    equals the configured offered load — MMPP changes the SHAPE of the
    traffic, never the amount, so a Poisson-vs-MMPP pair at one
    ``rate_rps`` isolates burst tolerance."""
    w_calm = config.dwell_s[0] / (config.dwell_s[0] + config.dwell_s[1])
    w_burst = 1.0 - w_calm
    calm_rate = config.rate_rps / (w_calm + w_burst * config.burst_multiplier)
    rates = (calm_rate, calm_rate * config.burst_multiplier)

    times: list[float] = []
    t, state = 0.0, 0
    state_end = rng.exponential(config.dwell_s[state])
    while t < config.duration_s:
        gap = rng.exponential(1.0 / rates[state])
        if t + gap >= state_end:
            # exponential inter-arrivals are memoryless: jumping to the
            # state boundary and redrawing at the new state's rate is
            # exact, not an approximation
            t = state_end
            state = 1 - state
            state_end = t + rng.exponential(config.dwell_s[state])
            continue
        t += gap
        if t < config.duration_s:
            times.append(t)
    return times


def generate_request_log(config: TrafficConfig) -> list[Request]:
    """The full request sequence for ``config`` — a pure function of
    the config (including its seed): calling this twice yields equal
    lists, which is the property every replay/determinism guarantee in
    the harness rests on (pinned by tests/test_traffic.py)."""
    config.validate()
    rng = np.random.default_rng(config.seed)
    if config.arrival == "poisson":
        times = _poisson_arrivals(rng, config.rate_rps, config.duration_s)
    else:
        times = _mmpp_arrivals(rng, config)
    requests: list[Request] = []
    for t in times:
        is_batch = (
            config.batch_fraction > 0.0
            and rng.random() < config.batch_fraction
        )
        n_rows = config.batch_rows if is_batch else 1
        # the drift generator's feature domain (data/generator.py), so
        # per-request server work matches the parity workload
        x = tuple(float(v) for v in rng.uniform(0.0, 100.0, n_rows))
        requests.append(Request(
            t_s=round(float(t), 9),
            route="/score/v1/batch" if is_batch else "/score/v1",
            x=x,
        ))
    return requests


def write_request_log(path: str | Path, config: TrafficConfig,
                      requests: list[Request]) -> None:
    """JSONL: one header line (schema + generating config), then one
    line per request. Plain text so a log diffs/greps like any other
    artefact."""
    path = Path(path)
    with path.open("w") as f:
        f.write(json.dumps({
            "schema": LOG_SCHEMA,
            "config": config.to_dict(),
            "n_requests": len(requests),
        }) + "\n")
        for r in requests:
            # "rows" is derivable from (route, x) but recorded
            # explicitly so the tuner (and any log consumer) can
            # reconstruct the offered row-shape distribution without
            # knowing the route->rows rule (tune/collect.py reads it;
            # read_request_log below tolerates its absence in old logs)
            f.write(json.dumps(
                {"t_s": r.t_s, "route": r.route, "rows": r.rows,
                 "x": list(r.x)}
            ) + "\n")
    log.info(f"wrote request log: {len(requests)} requests -> {path}")


def read_request_log(path: str | Path) -> tuple[TrafficConfig, list[Request]]:
    """Load a log written by :func:`write_request_log`. The header's
    count is verified so a truncated file fails loudly instead of
    silently replaying a lighter load."""
    path = Path(path)
    with path.open() as f:
        header = json.loads(f.readline())
        if header.get("schema") != LOG_SCHEMA:
            raise ValueError(
                f"{path}: not a request log (schema "
                f"{header.get('schema')!r}, expected {LOG_SCHEMA!r})"
            )
        requests = [
            Request(t_s=e["t_s"], route=e["route"],
                    x=tuple(float(v) for v in e["x"]))
            for e in (json.loads(line) for line in f if line.strip())
        ]
    if len(requests) != header.get("n_requests"):
        raise ValueError(
            f"{path}: truncated request log "
            f"({len(requests)} of {header.get('n_requests')} requests)"
        )
    return TrafficConfig.from_dict(header["config"]), requests
