"""Open-loop load driver: replay a request log against a live service.

The driver is the *open-loop* half of the harness contract: requests
fire at their scheduled arrival times **whether or not earlier responses
have returned**. A slow server does not slow the driver down — it just
accumulates in-flight requests, exactly as real arrival-rate traffic
would. (The closed-loop helpers in ``bench.py`` are the opposite
regime: they measure the server's service rate; this measures its
behaviour at a fixed offered rate.)

Measurement protocol:

- **Latency is measured from the scheduled arrival time**, not from the
  moment the request hit the wire. Measuring from send-time is the
  classic coordinated-omission mistake: a driver that stalls behind a
  slow server under-reports exactly the latencies that matter. The
  driver's own scheduling health is reported separately
  (``send_lag_p99_s``) so a client-side stall is visible instead of
  silently polluting the server's numbers.
- **Goodput counts 200s only.** A shed 429, a degraded 503, or a
  transport error all consumed offered load without delivering a
  prediction; ``goodput_rps`` is the rate of *useful* responses — the
  number an SLO is written against.
- **Keep-alive connection pool.** Requests ride a shared pool of
  keep-alive connections (grown on demand, one in-flight request per
  connection), the shape real arrival-rate traffic has by the time it
  reaches a replica: individual users don't share sockets, but their
  requests arrive through load balancers and sidecars that do. It also
  keeps the *measurement* about request admission rather than TCP
  churn — with a connection dialed per request, an overloaded server
  pays accept/close for every request it sheds, and at rates where
  scoring is a cheap coalesced batch that churn (not the scoring) is
  what collapses, drowning the very effect config 9 exists to measure.

The transport is pluggable (``transport=`` — an async callable taking a
:class:`~bodywork_tpu.traffic.generator.Request` and returning
``(status, retry_after_s)``): tests substitute a recording/canned
transport to prove replay determinism without a socket, the CLI and
bench use the real HTTP transport.

The driver itself has a ceiling: ONE process's event loop tops out
around ~1.6k rps on one core (the committed config-14 N=4 point was
truncated there). ``shards=N`` splits the log round-robin across N
worker processes — round-robin preserves both the aggregate rate and
the arrival-time distribution of every shard — and merges the
per-shard results into ONE report, so the offered rate scales with
driver cores while every measurement rule above still holds.
"""
from __future__ import annotations

import asyncio
import dataclasses
import json
import multiprocessing
import urllib.parse

from bodywork_tpu.traffic.generator import Request
from bodywork_tpu.utils.logging import get_logger

log = get_logger("traffic.runner")

__all__ = ["LoadReport", "format_report", "run_open_loop"]

#: response head + headers cap when parsing the reply
_MAX_HEAD = 64 * 1024


@dataclasses.dataclass
class _Result:
    t_s: float            # scheduled arrival offset
    status: int           # 0 = transport error / timeout
    retry_after_s: float | None
    latency_s: float      # scheduled arrival -> response complete
    send_lag_s: float     # scheduled arrival -> actually sent
    #: the X-Bodywork-Model-Key response header (which model ANSWERED —
    #: production, canary, or a firewall fallback); None when absent
    model_key: str | None = None
    #: the X-Bodywork-Trace-Id response header (obs/tracing.py): the
    #: server-side trace this request became — the join key between
    #: client-observed latency and server-side spans; None when the
    #: service runs tracing-off
    trace_id: str | None = None
    #: feature rows this request carried (the offered row-shape unit;
    #: the tuner reconstructs the row distribution from a results log
    #: alone — replayed logs previously lost it)
    rows: int = 1


def _percentile(sorted_vals: list, q: float) -> float | None:
    """Nearest-rank percentile (the bench.py convention)."""
    if not sorted_vals:
        return None
    k = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[k]


@dataclasses.dataclass
class LoadReport:
    """One open-loop run, summarised. ``to_dict`` is the record the CLI
    prints and bench config 9 embeds."""

    requests: int
    duration_s: float
    offered_rps: float
    ok: int
    #: OK responses that completed INSIDE the offered-load window
    #: (scheduled arrival + latency <= duration). Under overload the
    #: plain ``ok`` count includes the post-window queue drain;
    #: in-window goodput is the sustainable service rate — the capacity
    #: estimator reads THIS.
    ok_in_window: int
    shed: int              # 429 (admission or injected)
    unavailable: int       # 503
    client_error: int      # other 4xx
    server_error: int      # 5xx except 503
    transport_errors: int  # connect/reset/parse failures
    timeouts: int
    goodput_rps: float
    goodput_in_window_rps: float
    shed_fraction: float
    latency: dict          # p50/p99/p999 over OK responses, seconds
    retry_after: dict      # {responses, mean_s, max_s} where the header appeared
    send_lag_p99_s: float | None
    max_in_flight: int
    #: longest service blackout observed by the driver: the maximum
    #: time-span (in scheduled-arrival time) over any run of consecutive
    #: scheduled arrivals that produced zero 200s, measured from the
    #: first failed arrival to the next successful one. THE failover
    #: headline — bench config 17 asserts this stays under the lease
    #: TTL plus one reconnect backoff when the active dispatcher dies.
    max_blackout_s: float = 0.0
    #: responses carrying an X-Bodywork-Trace-Id header — nonzero means
    #: the service ran tracing-on and the results log (when written)
    #: joins to server-side spans
    traced_responses: int = 0
    #: latency/goodput broken down by the RESPONDING model key (the
    #: X-Bodywork-Model-Key header; "unknown" bucket when absent) — how
    #: a canary sweep attributes per-version behaviour with this harness
    per_model_key: dict = dataclasses.field(default_factory=dict)
    #: driver worker processes this report aggregates (1 = the classic
    #: single-process drive, ceiling ~1.6k rps; >1 = the sharded driver)
    shards: int = 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class _ConnectionPool:
    """Keep-alive connections to one host:port, grown on demand. Each
    connection carries ONE request at a time (no pipelining); a
    connection that errored, was cancelled mid-exchange, or whose
    server answered ``Connection: close`` is discarded, never reused —
    a fresh dial replaces it on the next acquire."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self._idle: list = []

    async def acquire(self):
        """``(reader, writer, reused)`` — ``reused`` marks a pooled
        connection, which the transport may legally find half-closed
        (the server timed it out while idle) and retry fresh."""
        while self._idle:
            reader, writer = self._idle.pop()
            if reader.at_eof() or writer.is_closing():
                writer.close()
                continue
            return reader, writer, True
        reader, writer = await asyncio.open_connection(
            self.host, self.port, limit=_MAX_HEAD
        )
        return reader, writer, False

    def release(self, reader, writer, reusable: bool) -> None:
        if reusable and not reader.at_eof() and not writer.is_closing():
            self._idle.append((reader, writer))
        else:
            writer.close()

    def close(self) -> None:
        while self._idle:
            _reader, writer = self._idle.pop()
            writer.close()


async def _http_transport(pool: _ConnectionPool, request: Request,
                          kind: str = "json"):
    """One request over a pooled keep-alive connection. Returns
    ``(status, retry_after_s)``; raises on transport failure (the
    driver counts). On ANY failure — including a cancellation from the
    driver's timeout — the connection is discarded, so a half-read
    response can never bleed into the next request.

    ``kind`` selects the wire encoding (``generator.TRANSPORTS``):
    "json" sends the frozen contract body, "binary" the f32 row
    framing — the same log drives either, so a json-vs-binary pair
    isolates serialization cost from everything else.

    A *reused* connection the server closed while it idled in the pool
    (thread-per-request servers time out keep-alive sockets) fails
    before a single response byte arrives; scoring is idempotent and
    nothing was answered, so the request retries exactly once on a
    fresh dial — the same reused-idempotent rule urllib3 applies. A
    FRESH connection failing is a real transport error and propagates."""
    if kind == "binary":
        from bodywork_tpu.serve.wire import BINARY_CONTENT_TYPE

        body = request.payload_binary()
        content_type = BINARY_CONTENT_TYPE
    else:
        body = request.payload()
        content_type = "application/json"
    head = (
        f"POST {request.route} HTTP/1.1\r\n"
        f"Host: {pool.host}:{pool.port}\r\n"
        f"Content-Type: {content_type}\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode("latin-1")
    for attempt in (0, 1):
        reader, writer, reused = await pool.acquire()
        reusable = False
        try:
            try:
                writer.write(head + body)
                await writer.drain()
                status_line = await reader.readline()
            except (ConnectionResetError, BrokenPipeError):
                if reused and attempt == 0:
                    continue  # stale keep-alive: one retry, fresh dial
                raise
            if not status_line:
                if reused and attempt == 0:
                    continue  # EOF before the status line, same story
                raise ConnectionResetError("EOF before response status line")
            parts = status_line.decode("latin-1").split(" ", 2)
            status = int(parts[1])
            retry_after = None
            model_key = None
            trace_id = None
            content_length = None
            keep_alive = True
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _sep, value = line.decode("latin-1").partition(":")
                name = name.strip().lower()
                if name == "retry-after":
                    try:
                        retry_after = float(value.strip())
                    except ValueError:
                        pass
                elif name == "x-bodywork-model-key":
                    # which model version ANSWERED — the per-model-key
                    # report breakdown reads this (canary sweeps)
                    model_key = value.strip() or None
                elif name == "x-bodywork-trace-id":
                    # the server-side trace id (obs/tracing.py) — logged
                    # per request so spans join to client latencies
                    trace_id = value.strip() or None
                elif name == "content-length":
                    try:
                        content_length = int(value.strip())
                    except ValueError:
                        pass
                elif name == "connection":
                    keep_alive = value.strip().lower() != "close"
            if content_length:
                await reader.readexactly(content_length)
            # a response with no Content-Length would need a close/EOF
            # to delimit — never reusable
            reusable = keep_alive and content_length is not None
            return status, retry_after, model_key, trace_id
        finally:
            pool.release(reader, writer, reusable)
    raise ConnectionResetError("unreachable")  # pragma: no cover


def _drive_once(
    url: str,
    requests_log: list[Request],
    timeout_s: float,
    transport,
    transport_kind: str,
):
    """One process's open-loop drive: fire every request at its
    scheduled time, return ``(results, timeouts, max_in_flight)``. The
    single-shard core both :func:`run_open_loop` and each shard worker
    run."""
    parsed = urllib.parse.urlsplit(url)
    host = parsed.hostname or "127.0.0.1"
    port = parsed.port or 80
    pool: _ConnectionPool | None = None
    if transport is None:
        from bodywork_tpu.traffic.generator import TRANSPORTS

        if transport_kind not in TRANSPORTS:
            raise ValueError(
                f"transport_kind must be one of {TRANSPORTS}, "
                f"got {transport_kind!r}"
            )
        pool = _ConnectionPool(host, port)

        async def transport(req: Request):
            return await _http_transport(pool, req, kind=transport_kind)

    results: list[_Result] = []
    in_flight = 0
    max_in_flight = 0
    timeouts = 0

    async def _drive():
        nonlocal in_flight, max_in_flight, timeouts
        loop = asyncio.get_running_loop()
        t_start = loop.time()

        async def fire(req: Request):
            nonlocal in_flight, max_in_flight, timeouts
            target = t_start + req.t_s
            delay = target - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            send_lag = loop.time() - target
            in_flight += 1
            max_in_flight = max(max_in_flight, in_flight)
            model_key = None
            trace_id = None
            try:
                outcome = await asyncio.wait_for(transport(req), timeout_s)
                # the HTTP transport reports (status, retry_after,
                # model_key, trace_id); shorter tuples from older or
                # pluggable transports land in the "unknown" buckets
                if len(outcome) >= 4:
                    status, retry_after, model_key, trace_id = outcome[:4]
                elif len(outcome) == 3:
                    status, retry_after, model_key = outcome
                else:
                    status, retry_after = outcome
            except asyncio.TimeoutError:
                timeouts += 1
                status, retry_after = 0, None
            except Exception:
                status, retry_after = 0, None
            finally:
                in_flight -= 1
            results.append(_Result(
                t_s=req.t_s, status=status, retry_after_s=retry_after,
                latency_s=loop.time() - target, send_lag_s=send_lag,
                model_key=model_key, trace_id=trace_id,
                rows=req.rows,
            ))

        try:
            await asyncio.gather(*[fire(r) for r in requests_log])
        finally:
            if pool is not None:
                pool.close()

    asyncio.run(_drive())
    return results, timeouts, max_in_flight


def _shard_main(url, requests_log, timeout_s, transport_kind, conn) -> None:
    """One sharded-driver worker: drive this shard's slice of the log,
    ship the raw per-request results back over the pipe (``_Result`` is
    a plain picklable dataclass). Any failure is shipped too — a dead
    shard must fail the whole run loudly, not silently under-offer."""
    try:
        results, timeouts, max_in_flight = _drive_once(
            url, requests_log, timeout_s, None, transport_kind
        )
        conn.send(("ok", results, timeouts, max_in_flight))
    except BaseException as exc:  # noqa: BLE001 - marshalled to the parent
        conn.send(("error", repr(exc)))
    finally:
        conn.close()


def _merged_max_in_flight(results: list[_Result]) -> int:
    """Exact peak concurrency across every shard, reconstructed from the
    per-request send/complete intervals (per-shard maxima cannot be
    summed — shards do not peak at the same instant)."""
    events = []
    for r in results:
        events.append((r.t_s + r.send_lag_s, 1))
        events.append((r.t_s + r.latency_s, -1))
    events.sort()
    peak = current = 0
    for _t, delta in events:
        current += delta
        peak = max(peak, current)
    return peak


def run_open_loop(
    url: str,
    requests_log: list[Request],
    timeout_s: float = 30.0,
    transport=None,
    duration_s: float | None = None,
    results_log: str | None = None,
    transport_kind: str = "json",
    shards: int = 1,
) -> LoadReport:
    """Fire ``requests_log`` at its scheduled arrival times against
    ``url`` (scheme://host:port — any path component is ignored; each
    log entry carries its own route) and summarise the outcome.

    ``results_log`` writes one JSONL record per request (scheduled
    arrival, status, client-observed latency, send lag, answering model
    key, and the server's returned trace id) — the join table between
    this harness's client-side latencies and the server-side spans a
    flight-recorder dump or ``cli trace show`` holds for the same trace
    id (obs/tracing.py).

    ``shards=N`` drives through N worker processes, splitting the log
    round-robin (``requests_log[i::N]`` keeps every shard's rate and
    arrival distribution proportional) and merging the per-shard
    results into this one report — the escape from the single-process
    generator's ~1.6k rps ceiling (docs/PERF.md §config 14 note).
    Custom ``transport=`` callables cannot cross the process boundary,
    so sharding requires the real HTTP transport.

    Runs its own event loop, so it is callable from plain synchronous
    code (the CLI, bench children, tests); do not call it from inside a
    running loop."""
    if not requests_log:
        raise ValueError("empty request log: nothing to drive")
    if shards < 1:
        raise ValueError(f"shards must be >= 1, got {shards}")
    shards = min(shards, len(requests_log))
    span = duration_s if duration_s is not None else max(
        r.t_s for r in requests_log
    )
    span = max(span, 1e-6)
    if shards == 1:
        results, timeouts, max_in_flight = _drive_once(
            url, requests_log, timeout_s, transport, transport_kind
        )
    else:
        if transport is not None:
            raise ValueError(
                "shards > 1 requires the built-in HTTP transport "
                "(a custom transport callable cannot cross the "
                "worker-process boundary)"
            )
        # spawn, not fork: the driver may be running inside a process
        # that already holds an event loop / threads (bench, the CLI
        # after jax import) — the repo-wide child-process convention
        ctx = multiprocessing.get_context("spawn")
        workers = []
        for i in range(shards):
            parent_conn, child_conn = ctx.Pipe(duplex=False)
            proc = ctx.Process(
                target=_shard_main,
                args=(url, requests_log[i::shards], timeout_s,
                      transport_kind, child_conn),
                daemon=True,
            )
            proc.start()
            child_conn.close()
            workers.append((proc, parent_conn))
        results = []
        timeouts = 0
        errors = []
        for i, (proc, conn) in enumerate(workers):
            try:
                outcome = conn.recv()
            except EOFError:
                outcome = ("error", f"shard {i} died without a result")
            if outcome[0] == "ok":
                results.extend(outcome[1])
                timeouts += outcome[2]
            else:
                errors.append(f"shard {i}: {outcome[1]}")
            proc.join(timeout=30)
        if errors:
            raise RuntimeError(
                "sharded open-loop drive failed: " + "; ".join(errors)
            )
        max_in_flight = _merged_max_in_flight(results)

    if results_log:
        # per-request JSONL, in scheduled-arrival order (the log the
        # harness joins against server-side spans by trace id)
        from pathlib import Path as _Path

        path = _Path(results_log)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            for r in sorted(results, key=lambda r: r.t_s):
                f.write(json.dumps({
                    "t_s": _round6(r.t_s),
                    # scheduled-vs-actual send, both explicit: the
                    # tuner reconstructs the ACHIEVED arrival process
                    # (and driver health) from the log alone
                    "sent_t_s": _round6(r.t_s + r.send_lag_s),
                    "rows": r.rows,
                    "status": r.status,
                    "latency_s": _round6(r.latency_s),
                    "send_lag_s": _round6(r.send_lag_s),
                    "retry_after_s": r.retry_after_s,
                    "model_key": r.model_key,
                    "trace_id": r.trace_id,
                }) + "\n")

    ok = [r for r in results if r.status == 200]
    ok_in_window = sum(1 for r in ok if r.t_s + r.latency_s <= span)
    shed = sum(1 for r in results if r.status == 429)
    unavailable = sum(1 for r in results if r.status == 503)
    client_error = sum(
        1 for r in results if 400 <= r.status < 500 and r.status != 429
    )
    server_error = sum(
        1 for r in results if r.status >= 500 and r.status != 503
    )
    transport_errors = sum(1 for r in results if r.status == 0) - timeouts
    ok_lat = sorted(r.latency_s for r in ok)
    lags = sorted(r.send_lag_s for r in results)
    with_retry = [r.retry_after_s for r in results
                  if r.retry_after_s is not None]
    # per-responding-model-key breakdown over OK responses: how a canary
    # sweep attributes latency/goodput per version ("unknown" = no
    # attribution header — e.g. a pre-canary server or custom transport)
    by_key: dict[str, list] = {}
    for r in ok:
        by_key.setdefault(r.model_key or "unknown", []).append(r)
    per_model_key = {}
    for key, rs in sorted(by_key.items()):
        key_lat = sorted(x.latency_s for x in rs)
        per_model_key[key] = {
            "ok": len(rs),
            "ok_in_window": sum(
                1 for x in rs if x.t_s + x.latency_s <= span
            ),
            "goodput_rps": round(len(rs) / span, 3),
            "latency": {
                "p50_s": _round6(_percentile(key_lat, 50)),
                "p99_s": _round6(_percentile(key_lat, 99)),
            },
        }
    report = LoadReport(
        requests=len(results),
        duration_s=round(span, 6),
        offered_rps=round(len(results) / span, 3),
        ok=len(ok),
        ok_in_window=ok_in_window,
        shed=shed,
        unavailable=unavailable,
        client_error=client_error,
        server_error=server_error,
        transport_errors=transport_errors,
        timeouts=timeouts,
        goodput_rps=round(len(ok) / span, 3),
        goodput_in_window_rps=round(ok_in_window / span, 3),
        shed_fraction=round(shed / len(results), 6),
        latency={
            "p50_s": _round6(_percentile(ok_lat, 50)),
            "p99_s": _round6(_percentile(ok_lat, 99)),
            "p999_s": _round6(_percentile(ok_lat, 99.9)),
        },
        retry_after={
            "responses": len(with_retry),
            "mean_s": _round6(sum(with_retry) / len(with_retry))
            if with_retry else None,
            "max_s": _round6(max(with_retry)) if with_retry else None,
        },
        send_lag_p99_s=_round6(_percentile(lags, 99)),
        max_in_flight=max_in_flight,
        max_blackout_s=_max_blackout_s(results),
        traced_responses=sum(1 for r in results if r.trace_id is not None),
        per_model_key=per_model_key,
        shards=shards,
    )
    log.info(
        f"open-loop run: offered {report.offered_rps:.0f} rps x "
        f"{report.duration_s:.1f}s -> goodput {report.goodput_rps:.0f} rps, "
        f"shed {report.shed_fraction:.1%}, "
        f"p99 {report.latency['p99_s']}s"
    )
    return report


def _round6(value: float | None) -> float | None:
    return round(value, 6) if value is not None else None


def _max_blackout_s(results: list) -> float:
    """Longest run of consecutive scheduled arrivals with zero 200s,
    as a time-span: from the first failed arrival's scheduled time to
    the scheduled time of the next 200 (or of the last arrival when the
    run never recovers). A lone failure between two successes scores
    the gap to the next success — a blackout is measured by how long
    the service was dark, not by how many arrivals fell into the hole.
    Returns 0.0 when every scheduled arrival got a 200.
    """
    worst = 0.0
    run_start: float | None = None
    for r in sorted(results, key=lambda x: x.t_s):
        if r.status == 200:
            if run_start is not None:
                worst = max(worst, r.t_s - run_start)
                run_start = None
        elif run_start is None:
            run_start = r.t_s
        last_t = r.t_s
    if run_start is not None:
        worst = max(worst, last_t - run_start)
    return round(worst, 6)


def format_report(report: LoadReport) -> str:
    """The CLI's stdout shape: one JSON document."""
    return json.dumps(report.to_dict(), indent=2)
