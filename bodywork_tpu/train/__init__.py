from bodywork_tpu.train.prewarm import prewarm_async
from bodywork_tpu.train.trainer import (
    TRAIN_MODES,
    TrainResult,
    persist_metrics,
    persist_train_result,
    train_on_history,
)

__all__ = [
    "TRAIN_MODES",
    "TrainResult",
    "persist_metrics",
    "persist_train_result",
    "prewarm_async",
    "train_on_history",
]
