"""Incremental training: O(1)-per-day retrain instead of O(history).

The daily trainer refits on ALL history every simulated day, and the
committed 90-day flatness record (``SCALE_DEV_r05_cpu.json``) attributes
the residual per-day wall-clock growth exactly to that O(history)
train/eval compute (+26.9% over the horizon for the MLP, last-third/
first-third 1.21). This module makes the per-day cost flat in history
length (ROADMAP item 3), which is what unlocks hourly/minute retrain
cadence — the registry gate (PR 5) and canary watchdog (PR 8) already
make fast-cadence promotion safe; this makes it affordable. Two
mechanisms, matched to each model's math:

**Linear — exact.** The OLS fit is the normal equations over the
intercept-augmented design, and its sufficient statistics are ADDITIVE
over row blocks: ``G = Σ_day G_day``, ``c = Σ_day c_day``
(:func:`bodywork_tpu.models.linear.gram_stats`). The RUNNING cumulative
sums (plus tiny per-day scalars for staleness detection and the
prediction-bounds band) are persisted in a digest-verified,
O(1)-per-day ``trainstate/`` document
(:func:`bodywork_tpu.store.schema.trainstate_key` — deliberately not
per-day Gram blocks: the document is reread and rewritten every day,
and an O(days) payload was a measured per-day growth term), so a
retrain folds in ONLY the new day's rows and solves in closed form
(:func:`~bodywork_tpu.models.linear.solve_normal_eq`) — provably
coefficient-identical (within float tolerance) to a full refit on the
same rows, under any day ordering (new entries are accumulated in
sorted-day order; the hypothesis property test pins the equivalence
over permuted/partial day sequences). Held-out metrics come from
per-day deterministic splits (seeded by the day, so a day's train/test
membership never changes as history grows — the precondition for
per-day statistics to be exact) evaluated over the tail window: O(tail)
rows, not O(history).

**MLP — approximate.** No finite sufficient statistics exist for the
net, so the incremental path warm-starts from the checkpoint serving
would load (``resolve_serving_key`` — the gate-promoted production on a
registry store, the newest checkpoint otherwise; the donor-checkpoint
reuse practice of PAPERS.md's pjit-era training) and fine-tunes on a
replay buffer of the tail window (:meth:`MLPRegressor.fine_tune`). The
result is a CANDIDATE like any other: the runner arms the registry
gate's shadow evaluation for incremental candidates
(``INCREMENTAL_SHADOW_DAYS``), so a degraded incremental retrain is
auto-rejected and the runner falls back to a full refit THAT SAME DAY
(``LocalRunner._full_refit_fallback``) — approximation error is bounded
by the release gate, not by hope.

**Fallback, never a wedged pipeline.** Every incapacity degrades to the
full refit with the reason counted on
``bodywork_tpu_train_fallbacks_total{reason}``: a missing or
shape-incompatible donor checkpoint (``no_donor`` /
``donor_incompatible``), an absent/corrupt-past-retry-budget/stale
trainstate document (``trainstate_absent`` / ``trainstate_corrupt`` /
``trainstate_stale`` — the linear path rebuilds its statistics from all
history in the same call, re-seeding O(1) behaviour for the next day),
and the gate rejection above (``gate_rejected``).

Determinism: trainstate documents are pure functions of the persisted
dataset bytes and the split parameters — canonical JSON, embedded
content digest, no wall clock, no backend tokens — and are mutated
EXCLUSIVELY through ``ArtefactStore.put_bytes_if_match``, so the chaos
harness's byte-identical twin guarantee extends over ``trainstate/``
and concurrent writers (a runner racing a rescheduled pod) can never
tear the document.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from time import perf_counter

import numpy as np

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore, CasConflict
from bodywork_tpu.store.schema import DATASETS_PREFIX, trainstate_key
from bodywork_tpu.train.trainer import (
    TRAIN_MODES,
    TrainResult,
    _record_train_metrics,
    make_model,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("train.incremental")

__all__ = [
    "INCREMENTAL_SHADOW_DAYS",
    "IncrementalUnavailable",
    "TAIL_DAYS",
    "TRAIN_MODES",
    "count_fallback",
    "persist_trainstate",
    "read_trainstate",
    "train_incremental",
]

TRAINSTATE_SCHEMA = "bodywork_tpu.trainstate/1"

#: tail window (days) for held-out evaluation (linear) and the MLP
#: replay buffer — the incremental day's data footprint
TAIL_DAYS = 7

#: shadow-evaluation window the runner's registry gate arms for
#: INCREMENTAL candidates (docs/REGISTRY.md): the approximate MLP path
#: is only safe because a degraded fine-tune is auto-rejected there
INCREMENTAL_SHADOW_DAYS = 3

#: MLP fine-tune budget: this fraction of the config's full n_steps,
#: floored at MIN_FINE_TUNE_STEPS
FINE_TUNE_STEPS_FRACTION = 0.25
MIN_FINE_TUNE_STEPS = 100

#: trainstate read retry budget: 1 + retries attempts, kept ABOVE the
#: chaos plan's default ``max_consecutive`` cap of 2 (same contract as
#: registry/records.py) so a seeded soak's corrupt reads never escalate
#: to a full-refit rebuild that would diverge from the fault-free twin
CORRUPT_READ_RETRIES = 2


class IncrementalUnavailable(RuntimeError):
    """The incremental path cannot run for a structural reason; the
    dispatcher degrades to a full refit with ``reason`` counted."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        self.reason = reason


def count_fallback(reason: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_train_fallbacks_total",
        "Incremental-train degradations to a full refit, by reason",
    ).inc(reason=reason)


# -- per-day deterministic splits ------------------------------------------


def day_split_indices(
    n: int, day, test_size: float, seed: int
) -> tuple[np.ndarray, np.ndarray]:
    """``(train_idx, test_idx)`` for one day's ``n`` rows, seeded by
    ``(seed, day)`` — each day's split membership is fixed forever,
    independent of every other day. That per-day determinism is what
    makes per-day sufficient statistics EXACT: under the global split
    (``models.base.train_test_split``) adding a day reshuffles every
    earlier row's membership, so no per-day state could be additive.
    Same convention as the global split (first ``round(n*test_size)``
    permuted indices are the test rows)."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, day.toordinal())))
    perm = rng.permutation(n)
    n_test = int(round(n * test_size))
    return perm[n_test:], perm[:n_test]


def _window_eval_arrays(parts, window_keys, dates, test_size: float, seed: int):
    """Concatenated HELD-OUT (per-day test split) rows over the tail
    window, oldest first. Degenerate windows whose per-day test splits
    are all empty (tiny day sizes) fall back to the window's full rows —
    an in-sample metric beats a NaN one that would wedge the gate."""
    Xs, ys = [], []
    for key in window_keys:
        ds = parts[key]
        _train_idx, test_idx = day_split_indices(
            len(ds), dates[key], test_size, seed
        )
        if len(test_idx):
            Xs.append(ds.X[test_idx])
            ys.append(ds.y[test_idx])
    if not Xs:
        Xs = [parts[k].X for k in window_keys]
        ys = [parts[k].y for k in window_keys]
    return np.concatenate(Xs), np.concatenate(ys)


# -- the trainstate document -----------------------------------------------


def _payload_digest(doc: dict) -> str:
    payload = json.dumps(
        [doc["model_type"], doc["feature_dim"], doc["split"],
         doc["cum_g"], doc["cum_c"], doc["days"]],
        sort_keys=True,
    ).encode("utf-8")
    return "sha256:" + hashlib.sha256(payload).hexdigest()


def _build_doc(model_type: str, feature_dim: int, split: dict,
               days: dict, cum_g, cum_c) -> dict:
    """The trainstate document: the RUNNING cumulative statistics
    (``cum_g``/``cum_c`` — float64 sums over every covered day's train
    split, in day order) plus tiny per-day scalars (row counts + label
    range, for staleness detection and the prediction-bounds band).
    Deliberately O(1)-sized per day, not per-day Gram blocks: the
    document is read, digest-verified, and rewritten EVERY day, and an
    O(days)-sized payload made that a measured per-day growth term —
    the very thing incremental training exists to remove."""
    doc = {
        "schema": TRAINSTATE_SCHEMA,
        "model_type": model_type,
        "feature_dim": int(feature_dim),
        "split": split,
        "days": days,
        "cum_g": [[float(v) for v in row] for row in cum_g],
        "cum_c": [float(v) for v in cum_c],
    }
    doc["digest"] = _payload_digest(doc)
    return doc


def _count_corrupt() -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_train_trainstate_corrupt_total",
        "Trainstate reads that failed JSON/schema/digest validation",
    ).inc()


def read_trainstate(store: ArtefactStore, model_type: str):
    """``(doc, version_token, reason)`` for the model type's trainstate
    document. ``doc`` is None when the key is absent
    (``reason="trainstate_absent"``) or stays invalid past the retry
    budget (``reason="trainstate_corrupt"`` — the token is KEPT so the
    rebuilding writer's CAS is a repair overwrite). Validation is
    schema + embedded content digest: a torn or corrupted document can
    only ever cost one full-refit rebuild, never a wrong model."""
    key = trainstate_key(model_type)
    token = store.version_token(key)
    corrupt = False
    for _attempt in range(1 + CORRUPT_READ_RETRIES):
        try:
            raw = store.get_bytes(key)
        except ArtefactNotFound:
            return None, None, "trainstate_absent"
        try:
            doc = json.loads(raw.decode("utf-8"))
            if (
                isinstance(doc, dict)
                and doc.get("schema") == TRAINSTATE_SCHEMA
                and isinstance(doc.get("days"), dict)
                and isinstance(doc.get("cum_g"), list)
                and isinstance(doc.get("cum_c"), list)
                and doc.get("digest") == _payload_digest(doc)
            ):
                return doc, token, None
        except (UnicodeDecodeError, ValueError, KeyError, TypeError):
            pass
        corrupt = True
        _count_corrupt()
        log.warning(f"corrupt trainstate document at {key!r}; re-reading")
    assert corrupt
    return None, token, "trainstate_corrupt"


_UNSET = object()


def persist_trainstate(
    store: ArtefactStore,
    model_type: str,
    doc: dict,
    expected_token=_UNSET,
    attempts: int = 4,
) -> str:
    """CAS-write one trainstate document: LAST WRITER WINS. A lost race
    re-reads the current token and overwrites. Two divergent cumulative
    sums are never merged (they cannot be reconciled without per-day
    blocks); instead, convergence is by REFOLD: any day the final
    document does not cover reads as "new" on the next retrain and is
    folded back in — and a REBUILD (stale statistics) must overwrite a
    richer-looking incumbent unconditionally, because the incumbent's
    extra days are exactly what went stale. The CAS still guarantees the
    document never tears under concurrent writers. ``expected_token``
    lets the caller reuse the token its read was taken under; omitted,
    the current token is read first. The ONLY writer of ``trainstate/``
    — the prefix is never touched by a raw ``put_bytes``."""
    key = trainstate_key(model_type)
    last: CasConflict | None = None
    for _attempt in range(attempts):
        if expected_token is _UNSET:
            # the token alone (same metadata probe the alias writer
            # uses): last-writer-wins needs no payload read
            token = store.version_token(key)
        else:
            token = expected_token
            expected_token = _UNSET  # any retry re-reads
        # compact separators, NO indent: indent forces json's pure-Python
        # encoder — machine state, not a human-facing record (registry
        # records keep their indent)
        data = json.dumps(
            doc, sort_keys=True, separators=(",", ":")
        ).encode("utf-8")
        try:
            store.put_bytes_if_match(key, data, token)
            return key
        except CasConflict as exc:
            last = exc  # concurrent writer: re-read the token, retry
    raise last


# -- linear: exact sufficient statistics -----------------------------------


def _day_entry(ds, test_size: float, seed: int) -> dict:
    """One day's additive statistics: the train split's Gram blocks plus
    the FULL day's row count and label range (bounds must match the full
    refit's, which sees every row)."""
    from bodywork_tpu.models.linear import gram_stats

    X = np.asarray(ds.X, dtype=np.float64)
    y = np.asarray(ds.y, dtype=np.float64).ravel()
    train_idx, _test_idx = day_split_indices(len(y), ds.date, test_size, seed)
    G, c = gram_stats(X[train_idx], y[train_idx])
    return {
        "g": G.tolist(),
        "c": c.tolist(),
        "n_rows": int(len(y)),
        "n_train": int(len(train_idx)),
        "y_min": float(np.min(y)),
        "y_max": float(np.max(y)),
    }


def accumulate_entries(entries: dict, cum_g=None, cum_c=None):
    """Fold per-day :func:`_day_entry` statistics onto a cumulative
    ``(G, c)`` pair, adding the new entries IN SORTED-DAY ORDER
    (sequential float64 accumulation — the same operation every prior
    day's fold performed, so a rebuild from scratch reproduces the
    incrementally-grown sums bit-for-bit when days arrive in order, and
    within float tolerance under any arrival order)."""
    first = next(iter(entries.values()))
    dim = len(first["c"])
    G = (np.zeros((dim, dim)) if cum_g is None
         else np.asarray(cum_g, dtype=np.float64).copy())
    c = (np.zeros(dim) if cum_c is None
         else np.asarray(cum_c, dtype=np.float64).copy())
    for key in sorted(entries):
        entry = entries[key]
        G += np.asarray(entry["g"], dtype=np.float64)
        c += np.asarray(entry["c"], dtype=np.float64)
    return G, c


def solve_from_days(days: dict, config=None) -> dict:
    """Accumulate per-day statistics (sorted-day order) and solve the
    normal equations — the pure-function core the property tests pin
    against an independent full refit."""
    from bodywork_tpu.models.linear import solve_normal_eq

    G, c = accumulate_entries(days)
    return solve_normal_eq(G, c, config)


def _bounds_from_days(days: dict) -> dict:
    """The serving-side sanity band from per-day label ranges — the same
    formula as ``trainer._prediction_bounds`` over all history's rows
    (a global min/max decomposes over days exactly)."""
    lo = min(e["y_min"] for e in days.values())
    hi = max(e["y_max"] for e in days.values())
    span = max(hi - lo, 1e-6)
    margin = 0.5 * span
    return {"lo": lo - margin, "hi": hi + margin}


def _load_parts(store: ArtefactStore, hist, keys):
    """Parsed datasets for ``keys`` through the standard three-tier
    loader (parsed cache -> snapshot slices -> batched fetch) — the
    incremental path reads O(tail) days through the same machinery the
    full path reads O(history) through."""
    from bodywork_tpu.data.io import load_history_parts

    subset = [(k, d) for k, d in hist if k in keys]
    tokens = store.version_tokens([k for k, _d in subset])
    return load_history_parts(store, subset, tokens)


def incremental_train_linear(
    store: ArtefactStore,
    model_kwargs: dict | None = None,
    test_size: float = 0.2,
    split_seed: int = 42,
    tail_days: int = TAIL_DAYS,
    persist: bool = True,
) -> TrainResult:
    """The exact incremental linear retrain (module docstring §linear).
    An absent/corrupt/stale trainstate document degrades IN-CALL to the
    full-statistics rebuild — O(history) once, with the reason counted
    and recorded on the result — and re-seeds O(tail) behaviour for
    every following day."""
    import jax

    from bodywork_tpu.models import LinearRegressor

    model = make_model("linear", **(model_kwargs or {}))
    hist = store.history(DATASETS_PREFIX)
    if not hist:
        raise ArtefactNotFound(f"no datasets under '{DATASETS_PREFIX}'")
    dates = dict(hist)
    hist_keys = [k for k, _d in hist]
    data_date = hist[-1][1]
    split = {"test_size": test_size, "seed": split_seed}

    t0 = perf_counter()
    doc, _token, reason = read_trainstate(store, "linear")
    days: dict = {}
    cum_g = cum_c = None
    if doc is not None:
        day_set = {str(d) for _k, d in hist}
        if doc.get("split") != split:
            reason = "trainstate_stale"
        elif not set(doc["days"]) <= day_set:
            # a covered day's dataset was DELETED: the cumulative sum
            # would include rows that no longer exist — rebuild from
            # what does
            reason = "trainstate_stale"
        else:
            days = dict(doc["days"])
            cum_g, cum_c = doc["cum_g"], doc["cum_c"]
    new_keys = [k for k in hist_keys if str(dates[k]) not in days]
    tail_keys = hist_keys[-max(tail_days, 1):]
    needed = list(dict.fromkeys(new_keys + tail_keys))
    parts = _load_parts(store, hist, set(needed))
    feature_dim = parts[needed[0]].X.shape[1]
    stale = None
    if days and doc.get("feature_dim") != feature_dim:
        # schema change under the statistics: the stored cumulative Gram
        # has the wrong shape
        stale = "feature dimension changed"
    elif days:
        # covered days whose datasets were OVERWRITTEN since folding
        # (same date, different contents) would keep stale sums
        # silently. The tail window's rows are already loaded, so its
        # covered days get a free consistency check against the stored
        # scalars (computed exactly as _day_entry computed them).
        # Overwrites of PRE-tail days that preserve row count and label
        # range are the residual blind spot — deletion, the common
        # retention operation, is caught by the day-set check above.
        for key in tail_keys:
            meta = days.get(str(dates[key]))
            if meta is None:
                continue
            y64 = np.asarray(parts[key].y, dtype=np.float64).ravel()
            if (
                meta.get("n_rows") != len(y64)
                or meta.get("y_min") != float(np.min(y64))
                or meta.get("y_max") != float(np.max(y64))
            ):
                stale = f"covered day {dates[key]} was overwritten"
                break
    if stale is not None:
        log.warning(f"linear trainstate stale ({stale}); rebuilding")
        reason = "trainstate_stale"
        days = {}
        cum_g = cum_c = None
        new_keys = hist_keys
        needed = list(dict.fromkeys(new_keys + tail_keys))
        parts = _load_parts(store, hist, set(needed))
    if reason is not None:
        count_fallback(reason)
        log.warning(
            f"linear trainstate {reason}: rebuilding statistics from all "
            f"{len(new_keys)} day(s) (full-refit-cost day; next day is "
            "O(tail) again)"
        )
    if new_keys:
        new_entries = {
            str(dates[key]): _day_entry(parts[key], test_size, split_seed)
            for key in new_keys
        }
        cum_g, cum_c = accumulate_entries(new_entries, cum_g, cum_c)
        for day_str, entry in new_entries.items():
            # the document keeps per-day SCALARS only (staleness
            # detection + the bounds band); the Gram blocks live in the
            # cumulative sum — see _build_doc
            days[day_str] = {
                k: entry[k] for k in ("n_rows", "n_train", "y_min", "y_max")
            }

    from bodywork_tpu.models.linear import solve_normal_eq

    host_params = solve_normal_eq(cum_g, cum_c, model.config)
    fitted = LinearRegressor(model.config, jax.device_put(host_params))
    fitted._host_params = host_params
    X_eval, y_eval = _window_eval_arrays(
        parts, tail_keys, dates, test_size, split_seed
    )
    metrics = fitted.evaluate(X_eval, y_eval)
    n_rows = sum(e["n_rows"] for e in days.values())
    rows_touched = sum(len(parts[k]) for k in needed)
    _record_train_metrics(
        fitted, metrics, perf_counter() - t0, n_rows,
        mode="incremental", rows_touched=rows_touched,
    )
    log.info(
        f"incremental linear fold: {len(new_keys)} new day(s) into "
        f"{len(days)} covered, {rows_touched} rows touched of {n_rows} "
        f"total: MAPE={metrics['MAPE']:.4f} r2={metrics['r_squared']:.4f}"
    )
    bounds = _bounds_from_days(days)
    result = TrainResult(
        fitted, metrics, data_date, None, None, n_rows,
        prediction_bounds=bounds, mode="incremental",
        rows_touched=rows_touched, fallback_reason=reason,
        pending_trainstate=_build_doc(
            "linear", feature_dim, split, days, cum_g, cum_c
        ),
    )
    if persist:
        # ONE owner of the persistence protocol (model + metrics +
        # candidate registration + the pending trainstate CAS):
        # trainer.persist_train_result — the same path the deferred
        # lookahead collection takes
        from bodywork_tpu.train.trainer import persist_train_result

        result = persist_train_result(store, result)
    return result


# -- mlp: warm-start + replay buffer ---------------------------------------


def _load_donor(store: ArtefactStore):
    """The warm-start donor: exactly the checkpoint serving would load
    (production alias on a registry store, newest checkpoint otherwise).
    ANY failure — no checkpoint, corrupt alias, unreadable bytes — is an
    IncrementalUnavailable, never a wedged pipeline."""
    from bodywork_tpu.models.checkpoint import load_model

    try:
        model, _d = load_model(store, None, device=False)
        return model
    except Exception as exc:
        raise IncrementalUnavailable(
            "no_donor", f"no donor checkpoint for warm start: {exc!r}"
        ) from exc


def incremental_train_mlp(
    store: ArtefactStore,
    model_kwargs: dict | None = None,
    test_size: float = 0.2,
    split_seed: int = 42,
    fit_seed: int | None = None,
    tail_days: int = TAIL_DAYS,
    persist: bool = True,
) -> TrainResult:
    """The approximate incremental MLP retrain (module docstring §mlp):
    warm-start from the serving checkpoint, fine-tune on the tail-window
    replay buffer, evaluate on the window's held-out splits. The result
    is a candidate gated WITH shadow evaluation by the runner — quality
    is enforced at the release gate, not assumed here."""
    template = make_model("mlp", **(model_kwargs or {}))
    cfg = template.config
    hist = store.history(DATASETS_PREFIX)
    if not hist:
        raise ArtefactNotFound(f"no datasets under '{DATASETS_PREFIX}'")
    dates = dict(hist)
    data_date = hist[-1][1]

    t0 = perf_counter()
    donor = _load_donor(store)
    if donor.model_type != "mlp":
        raise IncrementalUnavailable(
            "donor_incompatible",
            f"donor is {donor.model_type!r}, cannot warm-start an mlp",
        )
    if tuple(donor.config.hidden) != tuple(cfg.hidden):
        raise IncrementalUnavailable(
            "donor_incompatible",
            f"donor hidden={list(donor.config.hidden)} != "
            f"requested {list(cfg.hidden)}",
        )
    window_keys = [k for k, _d in hist[-max(tail_days, 1):]]
    parts = _load_parts(store, hist, set(window_keys))
    feature_dim = parts[window_keys[0]].X.shape[1]
    if donor.n_features != feature_dim:
        raise IncrementalUnavailable(
            "donor_incompatible",
            f"donor expects {donor.n_features} feature(s), data has "
            f"{feature_dim}",
        )
    Xs, ys = [], []
    for key in window_keys:
        ds = parts[key]
        train_idx, _test_idx = day_split_indices(
            len(ds), dates[key], test_size, split_seed
        )
        Xs.append(ds.X[train_idx])
        ys.append(ds.y[train_idx])
    X_train, y_train = np.concatenate(Xs), np.concatenate(ys)
    X_eval, y_eval = _window_eval_arrays(
        parts, window_keys, dates, test_size, split_seed
    )
    ft_steps = max(MIN_FINE_TUNE_STEPS,
                   int(cfg.n_steps * FINE_TUNE_STEPS_FRACTION))
    # deterministic per (config seed, day): chaos twins replay the same
    # minibatch draws, and successive days still see fresh randomness
    base_seed = cfg.seed if fit_seed is None else fit_seed
    tuned = donor.fine_tune(
        X_train, y_train, n_steps=ft_steps,
        seed=int(base_seed) + data_date.toordinal(),
    )
    metrics = tuned.evaluate(X_eval, y_eval)
    rows_touched = sum(len(parts[k]) for k in window_keys)
    _record_train_metrics(
        tuned, metrics, perf_counter() - t0, rows_touched,
        mode="incremental", rows_touched=rows_touched,
    )
    log.info(
        f"incremental mlp fine-tune: {ft_steps} step(s) from donor "
        f"{donor.info} on {len(window_keys)}-day replay "
        f"({rows_touched} rows): MAPE={metrics['MAPE']:.4f} "
        f"r2={metrics['r_squared']:.4f}"
    )
    from bodywork_tpu.train.trainer import _prediction_bounds

    # the sanity band comes from the replay window's labels (ALL rows,
    # like the full path over its history) — under drift the recent
    # window is the honest range for what this candidate will serve
    bounds = _prediction_bounds(
        np.concatenate([parts[k].y for k in window_keys])
    )
    result = TrainResult(
        tuned, metrics, data_date, None, None, rows_touched,
        prediction_bounds=bounds, mode="incremental",
        rows_touched=rows_touched,
    )
    if persist:
        # ONE owner of the persistence protocol — see the linear path
        from bodywork_tpu.train.trainer import persist_train_result

        result = persist_train_result(store, result)
    return result


# -- dispatch --------------------------------------------------------------


def train_incremental(
    store: ArtefactStore,
    model_type: str = "linear",
    model_kwargs: dict | None = None,
    test_size: float = 0.2,
    split_seed: int = 42,
    fit_seed: int | None = None,
    persist: bool = True,
    tail_days: int = TAIL_DAYS,
) -> TrainResult:
    """Mode dispatcher with the degradation contract: any structural
    incapacity of the incremental path falls back to the full refit with
    the reason counted and recorded on the result — a missing donor can
    cost one O(history) day, never a failed pipeline."""
    try:
        if model_type == "linear":
            return incremental_train_linear(
                store, model_kwargs=model_kwargs, test_size=test_size,
                split_seed=split_seed, tail_days=tail_days, persist=persist,
            )
        if model_type == "mlp":
            return incremental_train_mlp(
                store, model_kwargs=model_kwargs, test_size=test_size,
                split_seed=split_seed, fit_seed=fit_seed,
                tail_days=tail_days, persist=persist,
            )
        raise IncrementalUnavailable(
            "unsupported_model", f"no incremental path for {model_type!r}"
        )
    except IncrementalUnavailable as exc:
        count_fallback(exc.reason)
        log.warning(
            f"incremental {model_type} train unavailable "
            f"({exc.reason}: {exc}); falling back to a full refit"
        )
        from bodywork_tpu.train.trainer import train_on_history

        result = train_on_history(
            store, model_type, test_size=test_size, split_seed=split_seed,
            fit_seed=fit_seed, model_kwargs=model_kwargs, persist=persist,
        )
        return dataclasses.replace(result, fallback_reason=exc.reason)
