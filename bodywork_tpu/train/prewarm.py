"""Background pre-compilation of the next days' train/eval row buckets.

The daily retrain pads the growing dataset history into power-of-two row
buckets (``models.base.pad_rows``) so the number of distinct XLA programs
stays logarithmic in history size — but the first day whose history crosses
into a new bucket still pays that bucket's compile on the critical path
(~1.3 s for the linear program, several seconds for the MLP scan). Bucket
row counts are knowable ahead of time (monotone in history size), so they
are compiled early, off the critical path.

Two design constraints learned the hard way:

- Warm by **dispatch only** (``fit_and_evaluate(materialize=False)``):
  fetching the result would block on a full dummy training run, which on a
  slow backend (CPU CI) starves the real pipeline. Compilation is
  synchronous at dispatch time, which is all the jit cache needs.
- Warm through **one serialized worker**: a thread per bucket compiles
  N programs concurrently and contends with the day loop for host CPU;
  the queue keeps at most one background compile in flight, in request
  order (enqueue nearest-day buckets first).

This removes the per-bucket-crossing latency spike from the steady-state
day loop entirely (the reference has no analogue — sklearn on CPU has no
compile step, which is exactly why the TPU build must hide this cost).
"""
from __future__ import annotations

import atexit
import threading

import numpy as np

from bodywork_tpu.models.base import _bucket_rows
from bodywork_tpu.utils.logging import get_logger

log = get_logger("train.prewarm")

#: buckets already compiled (or queued to compile) this process, keyed by
#: (model_type, frozen model kwargs, fit bucket, eval bucket, n_features)
_warmed: set[tuple] = set()
_queue: list[tuple] = []
_worker: threading.Thread | None = None
_lock = threading.Lock()
_cancelled = threading.Event()


@atexit.register
def _drain() -> None:
    """Stop the worker before interpreter teardown: killing a daemon thread
    mid-XLA-compile aborts the whole process (pthread cancellation unwinds
    through C++ noexcept frames -> std::terminate). The cancel flag drops
    queued buckets; exit blocks on at most the one in-flight compile."""
    import logging

    # log streams (e.g. pytest capture) may already be closed at exit
    logging.raiseExceptions = False
    _cancelled.set()
    with _lock:
        worker = _worker
    if worker is not None:
        worker.join()


def _caller_device():
    """The caller's effective default device (respects the thread-local
    ``jax.default_device`` context an A/B runner pins its threads with)."""
    import jax

    return jax.config.jax_default_device


def _key(
    model_type: str,
    model_kwargs: dict | None,
    fit_b: int,
    eval_b: int,
    n_features: int,
    device,
):
    frozen = tuple(sorted((model_kwargs or {}).items(), key=repr))
    return (model_type, repr(frozen), fit_b, eval_b, n_features, str(device))


def next_buckets(n_total_next: int, test_size: float) -> tuple[int, int]:
    """(fit_bucket, eval_bucket) the trainer will use for a history of
    ``n_total_next`` rows, mirroring ``train_test_split`` + ``pad_rows``."""
    n_test = int(round(n_total_next * test_size))
    n_train = n_total_next - n_test
    return _bucket_rows(n_train, 1024), _bucket_rows(max(n_test, 1), 256)


def register_compiled(
    model_type: str,
    model_kwargs: dict | None,
    n_total: int,
    test_size: float = 0.2,
    n_features: int = 1,
) -> None:
    """Record that a real fit just compiled the buckets for ``n_total``
    rows, so ``prewarm_async`` never re-queues a bucket the jit cache
    already holds."""
    fit_b, eval_b = next_buckets(n_total, test_size)
    device = _caller_device()
    with _lock:
        _warmed.add(
            _key(model_type, model_kwargs, fit_b, eval_b, n_features, device)
        )


def _work_loop() -> None:
    global _worker
    while True:
        with _lock:
            if not _queue or _cancelled.is_set():
                _worker = None
                return
            model_type, model_kwargs, fit_b, eval_b, n_features, device, key = (
                _queue.pop(0)
            )
        try:
            import contextlib

            import jax

            from bodywork_tpu.train.trainer import make_model

            model = make_model(model_type, **(model_kwargs or {}))
            # Arrays sized exactly to the bucket round-trip pad_rows
            # unchanged, so this compiles precisely the trainer's fused
            # program at the trainer's shapes — including the feature
            # width. Values are irrelevant (nothing is fetched).
            x1 = np.linspace(0.0, 100.0, fit_b, dtype=np.float32)
            X = np.tile(x1[:, None], (1, n_features))
            y = (1.0 + 0.5 * x1).astype(np.float32)
            xe1 = np.linspace(0.0, 100.0, eval_b, dtype=np.float32)
            Xe = np.tile(xe1[:, None], (1, n_features))
            ye = (1.0 + 0.5 * xe1).astype(np.float32)
            # compile for the enqueuing caller's device (an A/B variant
            # pinned off device 0 must not warm — or contend with — the
            # default device), not the worker thread's own default
            ctx = (
                jax.default_device(device)
                if device is not None
                else contextlib.nullcontext()
            )
            with ctx:
                model.fit_and_evaluate(X, y, Xe, ye, materialize=False)
            log.info(
                f"pre-warmed {model_type} buckets fit={fit_b} eval={eval_b}"
            )
        except Exception as exc:  # never let warmup kill the pipeline
            log.warning(f"bucket pre-warm failed (non-fatal): {exc!r}")
            with _lock:
                _warmed.discard(key)


def wait_idle(timeout_s: float | None = None) -> bool:
    """Block until every queued bucket compile has finished (or the timeout
    elapses; returns False then). Lets an orchestrator that knows its whole
    horizon pay ALL compiles during bootstrap — steady-state days then never
    race the background worker for a bucket-crossing compile."""
    import time as _time

    deadline = None if timeout_s is None else _time.monotonic() + timeout_s
    while True:
        with _lock:
            worker = _worker
            empty = not _queue
        if worker is None and empty:
            return True
        if worker is not None:
            remaining = None if deadline is None else deadline - _time.monotonic()
            if remaining is not None and remaining <= 0:
                return False
            worker.join(timeout=remaining)
        if deadline is not None and _time.monotonic() > deadline:
            with _lock:
                done = _worker is None and not _queue
            return done


def prewarm_async(
    model_type: str,
    model_kwargs: dict | None,
    n_total_next: int,
    test_size: float = 0.2,
    n_features: int = 1,
) -> threading.Thread | None:
    """Queue a compile of the fused fit+eval programs for ``n_total_next``
    history rows on the single background worker, if not already compiled
    or queued this process.

    Over-estimating ``n_total_next`` is safe in the sense that buckets are
    monotone (an early-warmed larger bucket is hit from cache later), but
    callers should enqueue their *nearest*-day estimates first — the queue
    compiles in order. Returns the worker thread when this call queued a
    new compile, None when the buckets were already warm/queued.
    """
    global _worker
    fit_b, eval_b = next_buckets(n_total_next, test_size)
    device = _caller_device()
    key = _key(model_type, model_kwargs, fit_b, eval_b, n_features, device)
    with _lock:
        if key in _warmed or _cancelled.is_set():
            return None
        _warmed.add(key)
        _queue.append(
            (model_type, model_kwargs, fit_b, eval_b, n_features, device, key)
        )
        if _worker is None:
            _worker = threading.Thread(
                target=_work_loop, name="bucket-prewarm", daemon=True
            )
            _worker.start()
        return _worker
