"""Background pre-compilation of the next day's train/eval row buckets.

The daily retrain pads the growing dataset history into power-of-two row
buckets (``models.base.pad_rows``) so the number of distinct XLA programs
stays logarithmic in history size — but the first day whose history crosses
into a new bucket still pays that bucket's compile on the critical path
(~1.3 s measured on v5e). Tomorrow's row count is bounded by today's plus
the generator's per-day sample count, and buckets are monotone in row
count, so tomorrow's buckets are knowable *today*: compile them now, on a
daemon thread, overlapped with the serve/generate/test stages.

This removes the per-bucket-crossing latency spike from the steady-state
day loop entirely (the reference has no analogue — sklearn on CPU has no
compile step, which is exactly why the TPU port must hide this cost).
"""
from __future__ import annotations

import atexit
import threading

import numpy as np

from bodywork_tpu.models.base import _bucket_rows
from bodywork_tpu.utils.logging import get_logger

log = get_logger("train.prewarm")

#: buckets already compiled (or being compiled) this process, keyed by
#: (model_type, frozen model kwargs, fit bucket, eval bucket)
_warmed: set[tuple] = set()
_lock = threading.Lock()
_live: list[threading.Thread] = []
_cancelled = threading.Event()


@atexit.register
def _drain() -> None:
    """Join in-flight warm threads before interpreter teardown: killing a
    daemon thread mid-XLA-compile aborts the whole process (pthread
    cancellation unwinds through C++ noexcept frames -> std::terminate).
    The cancel flag stops threads that haven't started their fit yet, so
    exit blocks on at most the one in-flight XLA call — not on dummy
    trainings for buckets no future day will use."""
    import logging

    # log streams (e.g. pytest capture) may already be closed at exit;
    # don't let the warm thread's completion log print handler diagnostics
    logging.raiseExceptions = False
    _cancelled.set()
    for t in list(_live):
        t.join()


def _key(
    model_type: str,
    model_kwargs: dict | None,
    fit_b: int,
    eval_b: int,
    n_features: int,
):
    frozen = tuple(sorted((model_kwargs or {}).items(), key=repr))
    return (model_type, repr(frozen), fit_b, eval_b, n_features)


def next_buckets(n_total_next: int, test_size: float) -> tuple[int, int]:
    """(fit_bucket, eval_bucket) the trainer will use for a history of
    ``n_total_next`` rows, mirroring ``train_test_split`` + ``pad_rows``."""
    n_test = int(round(n_total_next * test_size))
    n_train = n_total_next - n_test
    return _bucket_rows(n_train, 1024), _bucket_rows(max(n_test, 1), 256)


def register_compiled(
    model_type: str,
    model_kwargs: dict | None,
    n_total: int,
    test_size: float = 0.2,
    n_features: int = 1,
) -> None:
    """Record that a real fit just compiled the buckets for ``n_total``
    rows, so ``prewarm_async`` never re-runs a dummy fit of a bucket the
    jit cache already holds."""
    fit_b, eval_b = next_buckets(n_total, test_size)
    with _lock:
        _warmed.add(_key(model_type, model_kwargs, fit_b, eval_b, n_features))


def prewarm_async(
    model_type: str,
    model_kwargs: dict | None,
    n_total_next: int,
    test_size: float = 0.2,
    n_features: int = 1,
) -> threading.Thread | None:
    """Compile the fit + fused-eval programs for ``n_total_next`` history
    rows on a daemon thread, if not already compiled this process.

    Over-estimating ``n_total_next`` is safe: buckets are monotone, so the
    estimate's bucket is >= the actual bucket, and any bucket warmed early
    is simply hit from cache on the day it is first needed. Warming
    *executes* the fit (a dummy one) rather than AOT-lowering it, because
    only execution populates the jit dispatch cache the real train hits;
    the dedupe set bounds that cost to once per bucket per process.
    """
    fit_b, eval_b = next_buckets(n_total_next, test_size)
    key = _key(model_type, model_kwargs, fit_b, eval_b, n_features)
    with _lock:
        if key in _warmed:
            return None
        _warmed.add(key)

    def _work():
        try:
            if _cancelled.is_set():  # process is exiting; skip the fit
                return
            from bodywork_tpu.train.trainer import make_model

            model = make_model(model_type, **(model_kwargs or {}))
            # Arrays sized exactly to the bucket round-trip pad_rows
            # unchanged, so this compiles precisely tomorrow's programs —
            # including the feature width, which must match the real data.
            # Values are irrelevant (results are discarded); a non-trivial
            # slope keeps the dummy fit numerically tame.
            x1 = np.linspace(0.0, 100.0, fit_b, dtype=np.float32)
            X = np.tile(x1[:, None], (1, n_features))
            y = (1.0 + 0.5 * x1).astype(np.float32)
            xe1 = np.linspace(0.0, 100.0, eval_b, dtype=np.float32)
            Xe = np.tile(xe1[:, None], (1, n_features))
            ye = (1.0 + 0.5 * xe1).astype(np.float32)
            # compile exactly the program the trainer runs: the fused
            # single-transfer fit+eval (models/fused.py)
            model.fit_and_evaluate(X, y, Xe, ye)
            log.info(
                f"pre-warmed {model_type} buckets fit={fit_b} eval={eval_b}"
            )
        except Exception as exc:  # never let warmup kill the pipeline
            log.warning(f"bucket pre-warm failed (non-fatal): {exc!r}")
            with _lock:
                _warmed.discard(key)
        finally:
            with _lock:
                if t in _live:
                    _live.remove(t)

    t = threading.Thread(target=_work, name="bucket-prewarm", daemon=True)
    with _lock:
        _live.append(t)
    t.start()
    return t
