"""Training orchestration over the artefact store (reference C2,
``stage_1_train_model.py:31-36``).

Flow (same contract as the reference's ``main()``):
load all dataset history -> 80/20 split (seed 42) -> fit regressor (jitted on
TPU) -> metrics on held-out split -> persist date-keyed model checkpoint +
metrics CSV.
"""
from __future__ import annotations

import dataclasses
import io
from datetime import date
from time import perf_counter

import pandas as pd

from bodywork_tpu.data.io import load_all_datasets
from bodywork_tpu.models import (
    LinearConfig,
    LinearRegressor,
    MLPConfig,
    MLPRegressor,
    Regressor,
    save_model,
    train_test_split,
)
from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import model_metrics_key
from bodywork_tpu.utils.logging import get_logger

log = get_logger("train")

#: the training modes ``train_on_history`` dispatches between: ``full``
#: refits on all history (the reference's semantics, the default);
#: ``incremental`` folds in only the new day
#: (:mod:`bodywork_tpu.train.incremental` — exact for the linear model
#: via persisted sufficient statistics, warm-start + replay for the
#: MLP, both degrading to ``full`` rather than failing). Pinned equal
#: to the ``cli train --mode`` choices and the stage env parsing by
#: tests/test_incremental.py.
TRAIN_MODES = ("full", "incremental")


@dataclasses.dataclass
class TrainResult:
    model: Regressor
    metrics: dict[str, float]
    data_date: date
    #: None until the artefacts are persisted (see ``persist_train_result``
    #: — a lookahead train defers persistence to its stage's DAG position)
    model_artefact_key: str | None
    metrics_artefact_key: str | None
    n_rows: int
    #: serving-side sanity band from the training labels (``{"lo", "hi"}``)
    #: — recorded on the registry candidate so the prediction-sanity
    #: firewall (serve.app) can catch absurd outputs before serialization
    prediction_bounds: dict | None = None
    #: how this model was produced: ``full`` refit or ``incremental``
    #: (train/incremental.py). An incremental request that fell back to
    #: a full refit reports ``full`` + a ``fallback_reason``.
    mode: str = "full"
    #: dataset rows actually READ to produce this result — the
    #: incremental path's O(tail) vs the full path's O(history), and the
    #: number the run-day train span + rows-touched counter record
    rows_touched: int | None = None
    #: why an incremental request did not run (or ran at full-refit
    #: cost): trainstate_absent/corrupt/stale, no_donor,
    #: donor_incompatible, gate_rejected (set by the runner's same-day
    #: fallback). None = no degradation.
    fallback_reason: str | None = None
    #: the ``trainstate/`` document this run wrote (incremental linear;
    #: journalled by ``stages.stage_artefact_keys`` so crash-resume
    #: re-verifies or rebuilds it)
    trainstate_artefact_key: str | None = None
    #: deferred trainstate document (lookahead trains must not write
    #: before their stage's DAG position; ``persist_train_result``
    #: CAS-writes it)
    pending_trainstate: dict | None = dataclasses.field(
        default=None, repr=False
    )


def _prediction_bounds(y) -> dict:
    """Sanity bounds for served predictions, from training-label
    statistics: the observed label range widened by half a range on each
    side. Wide enough that legitimate extrapolation under drift never
    trips it, tight enough that a NaN-adjacent or wildly-scaled output
    (the stage-4 live-scoring failure mode) is caught before a client
    sees it. Deterministic from the dataset bytes, so chaos-twin
    registry records stay byte-identical."""
    import numpy as np

    arr = np.asarray(y, dtype=np.float64)
    lo, hi = float(np.min(arr)), float(np.max(arr))
    span = max(hi - lo, 1e-6)  # degenerate label sets still get a band
    margin = 0.5 * span
    return {"lo": lo - margin, "hi": hi + margin}


def _register_candidate(
    store: ArtefactStore, model_key_: str, metrics_key: str,
    data_date: date, model_bytes: bytes,
    prediction_bounds: dict | None = None,
) -> None:
    """Register the freshly persisted checkpoint as a registry CANDIDATE
    (``bodywork_tpu.registry``): training no longer implicitly publishes
    — the checkpoint takes traffic only after the promotion gate flips
    the ``production`` alias. ``model_bytes`` is the very buffer
    save_model wrote, so the lineage digest costs neither a checkpoint
    re-download nor a second serialisation. Registration failure is
    non-fatal: the artefacts are already durable, and a registry-less
    consumer still serves the latest checkpoint exactly as before."""
    try:
        from bodywork_tpu.registry.records import register_candidate

        register_candidate(
            store, model_key_, metrics_key=metrics_key, day=data_date,
            model_bytes=model_bytes, prediction_bounds=prediction_bounds,
        )
    except Exception as exc:
        log.warning(f"candidate registration failed (non-fatal): {exc!r}")


def persist_train_result(store: ArtefactStore, result: TrainResult) -> TrainResult:
    """Write a computed-but-unpersisted TrainResult's model + metrics
    artefacts (and register the checkpoint as a registry candidate) and
    return the result with its keys filled in."""
    from bodywork_tpu.models.checkpoint import save_model_bytes

    data = save_model_bytes(result.model)
    model_key_ = save_model(store, result.model, result.data_date, data=data)
    metrics_key = persist_metrics(store, result.metrics, result.data_date)
    _register_candidate(
        store, model_key_, metrics_key, result.data_date, data,
        prediction_bounds=result.prediction_bounds,
    )
    trainstate_key_ = result.trainstate_artefact_key
    if result.pending_trainstate is not None:
        # a deferred incremental fold: CAS-write the statistics at this
        # stage's DAG position, like the model/metrics above
        from bodywork_tpu.train.incremental import persist_trainstate

        trainstate_key_ = persist_trainstate(
            store, result.model.model_type, result.pending_trainstate
        )
    return dataclasses.replace(
        result,
        model_artefact_key=model_key_,
        metrics_artefact_key=metrics_key,
        trainstate_artefact_key=trainstate_key_,
        pending_trainstate=None,
    )


def _record_train_metrics(
    fitted, metrics: dict[str, float], fit_s: float, n_rows: int,
    mode: str = "full", rows_touched: int | None = None,
) -> None:
    """Export training telemetry through the shared obs registry, so the
    day loop's train signal and the serving hot path land on the same
    ``/metrics`` surface (a run-day pod or in-process runner scrape shows
    fit time, step time, loss, and held-out quality next to the serving
    histograms). ``rows_touched`` (default: all of history, the full
    path's footprint) feeds the per-mode counter the incremental-train
    flatness claim is monitored by (docs/OBSERVABILITY.md)."""
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "bodywork_tpu_train_runs_total", "Completed training runs"
    ).inc()
    reg.counter(
        "bodywork_tpu_train_rows_touched_total",
        "Dataset rows read to produce each training run's model, by "
        "train mode (full = O(history) per run, incremental = O(tail))",
    ).inc(n_rows if rows_touched is None else rows_touched, mode=mode)
    reg.histogram(
        "bodywork_tpu_train_fit_seconds",
        "Fit + held-out eval wall-clock per training run",
    ).observe(fit_s)
    reg.gauge(
        "bodywork_tpu_train_rows", "Rows in the latest training history"
    ).set(n_rows)
    reg.gauge(
        "bodywork_tpu_train_mape_ratio", "Held-out MAPE of the latest fit"
    ).set(metrics["MAPE"])
    reg.gauge(
        "bodywork_tpu_train_r2_ratio", "Held-out r_squared of the latest fit"
    ).set(metrics["r_squared"])
    final_loss = getattr(fitted, "final_loss", None)
    if final_loss is not None:
        reg.gauge(
            "bodywork_tpu_train_final_loss",
            "Training loss at the last optimisation step",
        ).set(final_loss)
    n_steps = getattr(getattr(fitted, "config", None), "n_steps", None)
    if n_steps:
        # the timed window is the fused fit+eval program (one dispatch),
        # so this is an UPPER bound on true per-step time — say so
        # rather than claiming a precision the measurement lacks
        reg.gauge(
            "bodywork_tpu_train_step_seconds",
            "Fit+eval wall-clock / optimisation steps of the latest fit "
            "(upper bound on per-step time)",
        ).set(fit_s / n_steps)


def make_model(model_type: str, **kwargs) -> Regressor:
    """Build a model from a registry name plus either a ``config=`` object
    or flat config fields (``make_model("mlp", n_steps=300)``) — the flat
    form is what YAML pipeline specs can express (``StageSpec.args``)."""
    if model_type == "linear":
        cls, cfg_cls = LinearRegressor, LinearConfig
    elif model_type == "mlp":
        cls, cfg_cls = MLPRegressor, MLPConfig
    else:
        raise ValueError(f"unknown model type: {model_type!r}")
    if "config" in kwargs:
        return cls(**kwargs)
    if kwargs:
        if cfg_cls is MLPConfig and "hidden" in kwargs:
            kwargs["hidden"] = tuple(kwargs["hidden"])
        return cls(cfg_cls(**kwargs))
    return cls()


def persist_metrics(
    store: ArtefactStore, metrics: dict[str, float], data_date: date
) -> str:
    """Write a one-row metrics CSV with the reference's exact column schema
    ``date,MAPE,r_squared,max_residual`` (``stage_1:84-89,128-142``)."""
    record = pd.DataFrame(
        {
            "date": [data_date],
            "MAPE": [metrics["MAPE"]],
            "r_squared": [metrics["r_squared"]],
            "max_residual": [metrics["max_residual"]],
        }
    )
    key = model_metrics_key(data_date)
    buf = io.StringIO()
    record.to_csv(buf, header=True, index=False)
    store.put_text(key, buf.getvalue())
    log.info(f"persisted train metrics to {key}")
    return key


def _multihost_nonzero_process() -> bool:
    """True in a worker that joined a multi-process ``jax.distributed``
    cluster and is NOT process 0 — the processes that must not persist
    (one cluster, one writer)."""
    import jax

    return jax.process_count() > 1 and jax.process_index() != 0


def _fit_sharded(model, model_type, split, mesh_data, mesh_model, fit_seed):
    """Fit over a dp x tp mesh and evaluate on the held-out split."""
    if model_type != "mlp":
        raise ValueError(
            f"sharded training (mesh_data={mesh_data}, "
            f"mesh_model={mesh_model}) requires model_type='mlp', "
            f"got {model_type!r}"
        )
    import jax

    from bodywork_tpu.models.metrics import regression_metrics
    from bodywork_tpu.parallel import make_mesh, multihost_init, train_mlp_sharded

    multihost_init()
    devices = jax.devices()
    data = mesh_data if mesh_data else max(len(devices) // mesh_model, 1)
    n_needed = data * mesh_model
    if n_needed > len(devices):
        raise ValueError(
            f"mesh {data}x{mesh_model} needs {n_needed} devices, "
            f"have {len(devices)}"
        )
    mesh = make_mesh(data=data, model=mesh_model, devices=devices[:n_needed])
    fitted = train_mlp_sharded(
        split.X_train, split.y_train, model.config, mesh, seed=fit_seed
    )
    metrics = regression_metrics(split.y_test, fitted.predict(split.X_test))
    return fitted, metrics


def train_on_history(
    store: ArtefactStore,
    model_type: str = "linear",
    test_size: float = 0.2,
    split_seed: int = 42,
    fit_seed: int | None = None,
    model_kwargs: dict | None = None,
    prewarm_next: bool = False,
    rows_per_day: int | None = None,
    persist: bool = True,
    mesh_data: int | None = None,
    mesh_model: int = 1,
    mode: str = "full",
) -> TrainResult:
    """Run the full train stage against an artefact store.

    ``mode="incremental"`` routes to the O(1)-per-day path
    (:mod:`bodywork_tpu.train.incremental`): exact persisted sufficient
    statistics for the linear model, warm-start + replay fine-tuning
    for the MLP — both degrading to this full refit (reason counted on
    ``bodywork_tpu_train_fallbacks_total``) rather than failing. The
    default ``full`` refit on all history is byte-identical to the
    pre-incremental behaviour.

    With ``prewarm_next``, tomorrow's padded-row buckets are compiled on a
    background thread after training, so the days whose grown history first
    crosses into a larger bucket don't pay the XLA compile on the critical
    path (see :mod:`bodywork_tpu.train.prewarm`). Only useful to callers
    that retrain repeatedly in one process (the local day-loop runner);
    one-shot processes (CLI, per-day k8s jobs) gain nothing and would
    block at exit joining the warm thread, so it defaults off.
    ``rows_per_day`` bounds tomorrow's history growth (defaults to the
    standard generator's daily sample count).

    ``mesh_data``/``mesh_model`` > 1 route the fit through the dp x tp
    sharded training step (:func:`~bodywork_tpu.parallel.train_mlp_sharded`)
    over a ``(mesh_data, mesh_model)`` device mesh — MLP only (the linear
    model is closed-form; sharding it has nothing to parallelise). On a
    multi-host pool the process joins the JAX cluster first
    (:func:`~bodywork_tpu.parallel.multihost_init`), so the mesh may span
    hosts. The fitted model checkpoints and serves exactly like the
    single-device one.
    """
    if mode not in TRAIN_MODES:
        raise ValueError(
            f"unknown train mode {mode!r}; expected one of {TRAIN_MODES}"
        )
    use_mesh = (mesh_data or 0) > 1 or mesh_model > 1
    if mode == "incremental":
        if use_mesh:
            raise ValueError(
                "incremental training does not support a device mesh "
                "(the fold/fine-tune workloads are O(tail); shard the "
                "full refit instead)"
            )
        from bodywork_tpu.train.incremental import train_incremental

        return train_incremental(
            store, model_type, model_kwargs=model_kwargs,
            test_size=test_size, split_seed=split_seed, fit_seed=fit_seed,
            persist=persist,
        )
    ds = load_all_datasets(store)
    split = train_test_split(ds.X, ds.y, test_size=test_size, seed=split_seed)
    model = make_model(model_type, **(model_kwargs or {}))
    fit_t0 = perf_counter()
    if use_mesh:
        fitted, metrics = _fit_sharded(
            model, model_type, split, mesh_data, mesh_model, fit_seed
        )
    else:
        # fused fit+eval: one XLA program, one device->host transfer for
        # params and metrics together (models/fused.py)
        fitted, metrics = model.fit_and_evaluate(
            split.X_train, split.y_train, split.X_test, split.y_test,
            seed=fit_seed,
        )
    _record_train_metrics(fitted, metrics, perf_counter() - fit_t0, len(ds))
    log.info(
        f"trained {fitted.info} on {len(ds)} rows to {ds.date}: "
        f"MAPE={metrics['MAPE']:.4f} r2={metrics['r_squared']:.4f} "
        f"max_resid={metrics['max_residual']:.2f}"
    )
    # persist=False defers the artefact writes to the caller (a lookahead
    # train must not mutate the store before its stage's DAG position —
    # an aborted day would otherwise leave a future-dated model behind)
    bounds = _prediction_bounds(ds.y)
    if persist and use_mesh and _multihost_nonzero_process():
        # a multi-process cluster runs ONE global program whose result is
        # replicated into every worker; only process 0 writes the (byte-
        # identical) artefacts — N workers racing the same keys against a
        # shared store would be pure write amplification
        log.info("non-zero process in a multihost cluster: skipping persist")
        persist = False
    if persist:
        from bodywork_tpu.models.checkpoint import save_model_bytes

        data = save_model_bytes(fitted)
        model_key_ = save_model(store, fitted, ds.date, data=data)
        metrics_key = persist_metrics(store, metrics, ds.date)
        _register_candidate(store, model_key_, metrics_key, ds.date, data,
                            prediction_bounds=bounds)
    else:
        model_key_ = metrics_key = None
    if prewarm_next and not use_mesh:
        # the prewarm machinery compiles the single-device fused-fit
        # buckets, which the sharded path never dispatches
        from bodywork_tpu.data.generator import DriftConfig
        from bodywork_tpu.train.prewarm import prewarm_async, register_compiled

        # today's fit already compiled today's buckets — seed the dedupe so
        # a no-boundary-crossing day never spawns a redundant dummy fit
        register_compiled(
            model_type,
            model_kwargs,
            len(ds),
            test_size,
            n_features=ds.X.shape[1],
        )

        # Warm the buckets for tomorrow AND the day after: a bucket compile
        # (~2 s) can outlast the rest of today's loop, so warming only one
        # day ahead still races the next train. Two days' lead hides the
        # whole compile off the critical path; the dedupe cache makes the
        # extra call free when no new bucket is crossed.
        per_day = (
            rows_per_day if rows_per_day is not None else DriftConfig().n_samples
        )
        for days_ahead in (1, 2):
            prewarm_async(
                model_type,
                model_kwargs,
                len(ds) + days_ahead * per_day,
                test_size,
                n_features=ds.X.shape[1],
            )
    return TrainResult(
        fitted, metrics, ds.date, model_key_, metrics_key, len(ds),
        prediction_bounds=bounds, rows_touched=len(ds),
    )
