"""Self-tuning runtime (ROADMAP item 5): a learned cost model over
observed traces that closes the loop on the hand-set serving knobs.

Three layers:

- :mod:`bodywork_tpu.tune.collect` — the trace collector: obs registry
  snapshots, day-report spans, and ``traffic run`` request/results logs
  normalise into ONE :class:`~bodywork_tpu.tune.collect.ObservationTable`,
  plus the active dispatch-cost probe.
- :mod:`bodywork_tpu.tune.model` — the analytical+fitted cost model:
  a pure function of the table -> a tuned knob set with a per-knob
  decision trace (chosen vs default, basis, evidence).
- :mod:`bodywork_tpu.tune.config` — the tuned-config artifact: a
  schema-tagged, digest-stamped JSON document under the ``tuning/``
  store prefix, consumed by ``serve``/``serve_stage``/the multiproc
  workers through the malformed-degrades resolver
  (:func:`~bodywork_tpu.tune.config.resolve_serving_knobs`).

``cli tune`` drives all three; bench config 13 proves tuned >= hand-set
on seeded traffic profiles. Two online layers close the loop against
LIVE traffic (bench config 18):

- :mod:`bodywork_tpu.tune.costmodel` — the learned dispatch-cost model:
  a seeded closed-form ridge over probe samples, persisted under
  ``tuning/``, pricing unprobed ladder rungs for the fitter and
  per-request cost for the admission layer's cost-priced shed.
- :mod:`bodywork_tpu.tune.online` — the online re-tune controller
  (reload-watcher sibling of the SLO watchdog): incremental log
  ingestion, drift detection, mid-flight knob application, and the
  config-canary guard that auto-reverts a regressing config in one
  CAS (``registry/configlog.py``).

This ``__init__`` re-exports only the jax-free config layer — the
collector's probe (which needs the real predictor) imports lazily, so
fsck and the CLI parser stay light.
"""
from bodywork_tpu.tune.config import (
    KNOB_DEFAULTS,
    TUNED_CONFIG_ENV,
    TUNED_CONFIG_SCHEMA,
    TUNED_KNOB_ENV,
    ResolvedKnobs,
    load_tuned_config,
    resolve_serving_knobs,
    validate_knobs,
    write_tuned_config,
)

__all__ = [
    "KNOB_DEFAULTS",
    "TUNED_CONFIG_ENV",
    "TUNED_CONFIG_SCHEMA",
    "TUNED_KNOB_ENV",
    "ResolvedKnobs",
    "load_tuned_config",
    "resolve_serving_knobs",
    "validate_knobs",
    "write_tuned_config",
]
