"""Trace collector: observed serving behaviour -> one observation table.

The tuner's inputs already exist, scattered across three subsystems the
earlier PRs built: the obs registry records per-phase hot-path
histograms and flush accounting, the day-report spans carry per-stage
and per-op timings, and the traffic harness's JSONL logs are a full
seeded record of what was offered (arrival times, per-request row
shapes) and what came back (status, latency, send lag). This module
normalises all of them into one :class:`ObservationTable` — the only
shape the cost model (``tune/model.py``) reads — so a fit is a pure
function of the table regardless of which sources fed it.

Sources (each ingestor is additive; call any subset):

- :func:`ingest_request_log` — a ``traffic run`` request log (the
  SCHEDULE: scheduled arrival times + per-request row counts). Yields
  the offered arrival process (inter-arrival samples) and the offered
  row-shape distribution.
- :func:`ingest_results_log` — a ``traffic run --results-out`` log (the
  OUTCOME: status, latency from scheduled arrival, send lag, rows).
  Yields observed goodput — the measured service rate when the drive
  was saturated — and completes the row-shape picture for replayed logs.
- :func:`ingest_obs_snapshot` — an obs registry snapshot (the dict
  ``Registry.snapshot()`` returns, or a JSON file of it, e.g. a
  multiproc worker snapshot): coalescer flush occupancy + flush
  reasons, device-dispatch and scoring-latency histogram moments,
  per-op store costs.
- :func:`ingest_day_report` — a ``run-day --report-out`` document:
  span durations per stage/category (the cold-path costs: snapshot
  refresh cadence inputs, per-op store spans).
- :func:`probe_dispatch_costs` — the one ACTIVE source: time the
  serving checkpoint's padded dispatch at each candidate bucket
  (median of ``reps`` calls, first call untimed). This is the measured
  per-bucket cost curve the bucket-ladder and window decisions need —
  the "learned from measured executions" half of the hybrid, à la the
  TPU learned-cost-model paper (PAPERS.md).

Everything here is numpy + stdlib; jax is only touched inside
:func:`probe_dispatch_costs` (the probe needs the real predictor).
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path

import numpy as np

from bodywork_tpu.utils.logging import get_logger

log = get_logger("tune.collect")

__all__ = [
    "IngestCursor",
    "ObservationTable",
    "ingest_day_report",
    "ingest_obs_snapshot",
    "ingest_request_log",
    "ingest_request_log_incremental",
    "ingest_results_log",
    "ingest_results_log_incremental",
    "probe_dispatch_costs",
]


@dataclasses.dataclass
class ObservationTable:
    """Everything the cost model may condition on, normalised. Empty
    fields mean "never observed" — each knob's model degrades to the
    hand-set default when its evidence is missing (and says so in the
    decision trace)."""

    #: seconds between consecutive scheduled arrivals (request logs)
    interarrival_s: list = dataclasses.field(default_factory=list)
    #: per-request row counts (request/results logs)
    row_counts: list = dataclasses.field(default_factory=list)
    #: bucket -> measured seconds per padded dispatch (the probe)
    dispatch_cost_s: dict = dataclasses.field(default_factory=dict)
    #: coalescer flush occupancy (rows flushed / max_rows): histogram
    #: moments from the obs snapshot
    occupancy_sum: float = 0.0
    occupancy_count: int = 0
    #: flush-reason counts (window | max_rows | saturation)
    flush_reasons: dict = dataclasses.field(default_factory=dict)
    #: device-dispatch histogram moments (obs snapshot)
    dispatch_sum_s: float = 0.0
    dispatch_count: int = 0
    #: scoring-latency histogram moments (obs snapshot)
    scoring_sum_s: float = 0.0
    scoring_count: int = 0
    #: admission queue-delay EWMA samples (obs snapshot / healthz docs)
    queue_delay_s: list = dataclasses.field(default_factory=list)
    #: OK responses per second observed by a results log whose offered
    #: rate exceeded it — the measured service rate under saturation
    saturated_goodput_rps: float | None = None
    #: results-log latency samples (from scheduled arrival), seconds
    latency_s: list = dataclasses.field(default_factory=list)
    #: per-op store costs: op -> mean seconds (obs snapshot / day report)
    store_op_cost_s: dict = dataclasses.field(default_factory=dict)
    #: day-report span seconds by span name (cold-path cadence evidence)
    span_seconds: dict = dataclasses.field(default_factory=dict)
    #: where each piece of evidence came from (the fit's audit trail)
    sources: list = dataclasses.field(default_factory=list)

    # -- derived views the cost model reads ---------------------------------
    def arrival_rate_rps(self) -> float | None:
        """Mean offered arrival rate from the inter-arrival samples."""
        if not self.interarrival_s:
            return None
        mean = float(np.mean(self.interarrival_s))
        return 1.0 / mean if mean > 0 else None

    def row_quantiles(self) -> dict | None:
        """The offered row-shape distribution, summarised."""
        if not self.row_counts:
            return None
        rows = np.asarray(self.row_counts)
        return {
            "p50": int(np.percentile(rows, 50)),
            "p90": int(np.percentile(rows, 90)),
            "p99": int(np.percentile(rows, 99)),
            "max": int(rows.max()),
            "n": int(rows.size),
        }

    def mean_occupancy(self) -> float | None:
        if self.occupancy_count == 0:
            return None
        return self.occupancy_sum / self.occupancy_count

    def mean_dispatch_s(self) -> float | None:
        if self.dispatch_count == 0:
            return None
        return self.dispatch_sum_s / self.dispatch_count

    def service_rate_rps(self) -> float | None:
        """The measured single-service rate: a saturated drive's
        goodput when one was observed (the direct measurement), else
        the inverse mean scoring latency (the closed-loop proxy)."""
        if self.saturated_goodput_rps is not None:
            return self.saturated_goodput_rps
        if self.scoring_count and self.scoring_sum_s > 0:
            return self.scoring_count / self.scoring_sum_s
        return None

    def summary(self) -> dict:
        """The in-document observation summary (what the tuned config
        records as its evidence — replaying the same table reproduces
        the same fit, byte-identically)."""
        rate = self.arrival_rate_rps()
        service = self.service_rate_rps()
        return {
            "arrival_rate_rps": round(rate, 3) if rate else None,
            "interarrival_samples": len(self.interarrival_s),
            "row_shape": self.row_quantiles(),
            "dispatch_cost_s": {
                str(b): round(c, 6)
                for b, c in sorted(self.dispatch_cost_s.items())
            } or None,
            "mean_flush_occupancy": (
                round(self.mean_occupancy(), 4)
                if self.mean_occupancy() is not None else None
            ),
            "flush_reasons": dict(self.flush_reasons) or None,
            "service_rate_rps": round(service, 3) if service else None,
            "queue_delay_samples": len(self.queue_delay_s),
            "store_op_cost_s": {
                k: round(v, 6)
                for k, v in sorted(self.store_op_cost_s.items())
            } or None,
            # day-report span evidence rides the record even though no
            # CURRENT knob model conditions on it: the cold-path knobs
            # (compaction cadence, get_many concurrency — ROADMAP item
            # 5) will, and a tune's evidence must be auditable from its
            # document alone either way
            "span_seconds": {
                k: round(v, 6)
                for k, v in sorted(
                    self.span_seconds.items(), key=lambda kv: -kv[1]
                )[:12]
            } or None,
            "sources": list(self.sources),
        }


def _request_rows(entry: dict) -> int:
    """Rows one logged request carries: the explicit ``rows`` field
    (written since this PR) or the payload length for older logs."""
    rows = entry.get("rows")
    if isinstance(rows, int) and rows >= 1:
        return rows
    x = entry.get("x")
    if isinstance(x, list) and x:
        return len(x) if entry.get("route", "").endswith("/batch") else 1
    return 1


def ingest_request_log(table: ObservationTable, path: str | Path) -> int:
    """Fold one ``traffic run`` request log (JSONL, schema
    ``bodywork_tpu.request_log/1``) into the table: scheduled
    inter-arrival gaps + per-request row counts. Returns the number of
    requests ingested."""
    path = Path(path)
    with path.open() as f:
        header = json.loads(f.readline())
        if header.get("schema") != "bodywork_tpu.request_log/1":
            raise ValueError(
                f"{path}: not a request log "
                f"(schema {header.get('schema')!r})"
            )
        prev_t = None
        n = 0
        for line in f:
            if not line.strip():
                continue
            entry = json.loads(line)
            t = float(entry["t_s"])
            if prev_t is not None and t >= prev_t:
                table.interarrival_s.append(t - prev_t)
            prev_t = t
            table.row_counts.append(_request_rows(entry))
            n += 1
    table.sources.append(f"request_log:{path.name}")
    return n


def ingest_results_log(table: ObservationTable, path: str | Path) -> int:
    """Fold one ``traffic run --results-out`` log into the table:
    per-request outcome (latency, status, rows, scheduled-vs-actual
    send). When the drive was SATURATED (offered clearly exceeded
    goodput), the OK rate is the measured service rate — the admission
    budget's denominator."""
    path = Path(path)
    ok = 0
    shed = 0
    n = 0
    last_t = 0.0
    prev_t = None
    with path.open() as f:
        for line in f:
            if not line.strip():
                continue
            entry = json.loads(line)
            t = float(entry["t_s"])
            n += 1
            last_t = max(last_t, t)
            if prev_t is not None and t >= prev_t:
                table.interarrival_s.append(t - prev_t)
            prev_t = t
            if "rows" in entry:
                table.row_counts.append(_request_rows(entry))
            status = entry.get("status")
            if status == 200:
                ok += 1
                if entry.get("latency_s") is not None:
                    table.latency_s.append(float(entry["latency_s"]))
            elif status == 429:
                shed += 1
            if entry.get("retry_after_s") is not None:
                table.queue_delay_s.append(float(entry["retry_after_s"]))
    if n == 0:
        raise ValueError(f"{path}: empty results log")
    span = max(last_t, 1e-6)
    offered = n / span
    goodput = ok / span
    # saturated when the server visibly shed (sheds ARE the at-budget
    # signal — a 2% shed fraction never happens off saturation) or the
    # offered rate clearly outran the answered rate: the OK rate then
    # IS the measured service rate for this traffic shape
    if ok and (shed / n > 0.02 or offered > 1.3 * goodput):
        table.saturated_goodput_rps = max(
            table.saturated_goodput_rps or 0.0, goodput
        )
    table.sources.append(f"results_log:{path.name}")
    return n


# -- incremental ingestion (the online controller's O(new entries) path) ---


@dataclasses.dataclass
class IngestCursor:
    """Byte-offset resume state for ONE growing log file.

    The offline ``cli tune`` flow reads each file once, so the whole-
    file ingestors above are fine there — but the online controller
    (``tune/online.py``) re-ingests its watch files EVERY poll, and a
    whole-file re-read per poll makes a long-lived controller O(file)
    instead of O(new entries). The cursor carries everything a resumed
    parse needs: the byte offset of the first unconsumed line, the last
    scheduled arrival (inter-arrival gaps must bridge poll boundaries),
    and the running outcome counts the results-log saturation heuristic
    is defined over (it is a whole-drive rate, not a tail rate).

    Only COMPLETE lines are ever consumed — a partially-written tail
    line (the live writer mid-append) stays un-offset for the next
    poll, so a torn JSON line can never poison the table."""

    offset: int = 0
    last_t: float | None = None
    entries: int = 0
    ok: int = 0
    shed: int = 0
    span_t: float = 0.0


def _count_ingest(kind: str, entries: int, n_bytes: int) -> None:
    from bodywork_tpu.obs import get_registry

    reg = get_registry()
    reg.counter(
        "bodywork_tpu_tune_ingest_entries_total",
        "Log entries folded into tuning observation tables by the "
        "incremental ingestors, by log kind",
    ).inc(entries, kind=kind)
    reg.counter(
        "bodywork_tpu_tune_ingest_bytes_total",
        "Bytes consumed by the incremental tuning-log ingestors, by "
        "log kind — per-poll deltas prove the controller stays "
        "O(new entries), not O(file)",
    ).inc(n_bytes, kind=kind)


def _new_complete_lines(path: Path, offset: int):
    """``(lines, new_offset, bytes_consumed)`` for every complete line
    appended since ``offset``."""
    with path.open("rb") as f:
        f.seek(offset)
        chunk = f.read()
    end = chunk.rfind(b"\n")
    if end < 0:
        return [], offset, 0
    consumed = end + 1
    return (
        chunk[:consumed].decode("utf-8").splitlines(),
        offset + consumed,
        consumed,
    )


def ingest_request_log_incremental(
    table: ObservationTable, path: str | Path,
    cursor: IngestCursor | None = None,
) -> IngestCursor:
    """Fold every request-log entry appended since ``cursor`` into the
    table and return the advanced cursor (a fresh one reads from the
    top, validating the header exactly like :func:`ingest_request_log`).
    Entry semantics are identical to the whole-file ingestor; only the
    I/O pattern differs."""
    path = Path(path)
    cursor = cursor or IngestCursor()
    lines, new_offset, n_bytes = _new_complete_lines(path, cursor.offset)
    start = 0
    if cursor.offset == 0 and lines:
        header = json.loads(lines[0])
        if header.get("schema") != "bodywork_tpu.request_log/1":
            raise ValueError(
                f"{path}: not a request log "
                f"(schema {header.get('schema')!r})"
            )
        start = 1
    n = 0
    for line in lines[start:]:
        if not line.strip():
            continue
        entry = json.loads(line)
        t = float(entry["t_s"])
        if cursor.last_t is not None and t >= cursor.last_t:
            table.interarrival_s.append(t - cursor.last_t)
        cursor.last_t = t
        table.row_counts.append(_request_rows(entry))
        n += 1
    cursor.offset = new_offset
    cursor.entries += n
    if n:
        table.sources.append(f"request_log:{path.name}[+{n}]")
    _count_ingest("request_log", n, n_bytes)
    return cursor


def ingest_results_log_incremental(
    table: ObservationTable, path: str | Path,
    cursor: IngestCursor | None = None,
) -> IngestCursor:
    """Incremental sibling of :func:`ingest_results_log`. The
    saturation heuristic runs over the cursor's RUNNING totals (ok /
    shed / span) — saturation is a whole-drive property, and judging it
    from one poll's tail alone would flap."""
    path = Path(path)
    cursor = cursor or IngestCursor()
    lines, new_offset, n_bytes = _new_complete_lines(path, cursor.offset)
    n = 0
    for line in lines:
        if not line.strip():
            continue
        entry = json.loads(line)
        t = float(entry["t_s"])
        cursor.span_t = max(cursor.span_t, t)
        if cursor.last_t is not None and t >= cursor.last_t:
            table.interarrival_s.append(t - cursor.last_t)
        cursor.last_t = t
        if "rows" in entry:
            table.row_counts.append(_request_rows(entry))
        status = entry.get("status")
        if status == 200:
            cursor.ok += 1
            if entry.get("latency_s") is not None:
                table.latency_s.append(float(entry["latency_s"]))
        elif status == 429:
            cursor.shed += 1
        if entry.get("retry_after_s") is not None:
            table.queue_delay_s.append(float(entry["retry_after_s"]))
        n += 1
    cursor.offset = new_offset
    cursor.entries += n
    if cursor.entries and cursor.ok:
        span = max(cursor.span_t, 1e-6)
        offered = cursor.entries / span
        goodput = cursor.ok / span
        if cursor.shed / cursor.entries > 0.02 or offered > 1.3 * goodput:
            table.saturated_goodput_rps = max(
                table.saturated_goodput_rps or 0.0, goodput
            )
    if n:
        table.sources.append(f"results_log:{path.name}[+{n}]")
    _count_ingest("results_log", n, n_bytes)
    return cursor


def _histogram_moments(entry: dict) -> tuple[float, int]:
    total = 0.0
    count = 0
    for sample in entry.get("samples", []):
        total += float(sample.get("sum", 0.0))
        count += int(sample.get("count", 0))
    return total, count


def ingest_obs_snapshot(table: ObservationTable,
                        snapshot: dict | str | Path) -> None:
    """Fold one obs registry snapshot (``Registry.snapshot()`` dict, or
    a JSON file holding one — e.g. a multiproc worker's flushed
    snapshot) into the table: coalescer occupancy + flush reasons,
    dispatch/scoring histogram moments, per-op store costs."""
    label = "obs_snapshot:dict"
    if not isinstance(snapshot, dict):
        path = Path(snapshot)
        snapshot = json.loads(path.read_text())
        label = f"obs_snapshot:{path.name}"
        if not isinstance(snapshot, dict):
            raise ValueError(f"{path}: not a registry snapshot document")
    occ = snapshot.get("bodywork_tpu_serve_batch_occupancy_ratio")
    if occ:
        s, c = _histogram_moments(occ)
        table.occupancy_sum += s
        table.occupancy_count += c
    flush = snapshot.get("bodywork_tpu_serve_batch_flush_total")
    if flush:
        for sample in flush.get("samples", []):
            reason = sample.get("labels", {}).get("reason", "unknown")
            table.flush_reasons[reason] = (
                table.flush_reasons.get(reason, 0)
                + int(sample.get("value", 0))
            )
    dispatch = snapshot.get("bodywork_tpu_device_dispatch_seconds")
    if dispatch:
        s, c = _histogram_moments(dispatch)
        table.dispatch_sum_s += s
        table.dispatch_count += c
    scoring = snapshot.get("bodywork_tpu_scoring_latency_seconds")
    if scoring:
        s, c = _histogram_moments(scoring)
        table.scoring_sum_s += s
        table.scoring_count += c
    ops = snapshot.get("bodywork_tpu_store_op_seconds")
    if ops:
        for sample in ops.get("samples", []):
            op = sample.get("labels", {}).get("op", "unknown")
            count = int(sample.get("count", 0))
            if count:
                table.store_op_cost_s[op] = (
                    float(sample.get("sum", 0.0)) / count
                )
    table.sources.append(label)


def ingest_day_report(table: ObservationTable, path: str | Path) -> None:
    """Fold one ``run-day`` report (``bodywork_tpu.day_report/1``) into
    the table: span seconds by name — the cold-path timings (snapshot
    refresh, stage walls) a compaction-cadence or prefetch tuner
    conditions on."""
    path = Path(path)
    doc = json.loads(path.read_text())
    if doc.get("schema") != "bodywork_tpu.day_report/1":
        raise ValueError(
            f"{path}: not a day report (schema {doc.get('schema')!r})"
        )
    for span in doc.get("spans", []):
        name = span.get("name", "unknown")
        table.span_seconds[name] = (
            table.span_seconds.get(name, 0.0)
            + float(span.get("duration_s", 0.0))
        )
    table.sources.append(f"day_report:{path.name}")


def probe_dispatch_costs(
    store,
    buckets: tuple[int, ...],
    reps: int = 5,
    n_features: int | None = None,
) -> dict:
    """Measure the serving checkpoint's padded-dispatch cost at each
    bucket (median of ``reps`` timed calls after one untimed warm
    call): ``{bucket: seconds_per_dispatch}``. This is the cost curve
    the bucket-ladder and window models condition on — measured on the
    ACTUAL model the store would serve, through the same
    ``PaddedPredictor`` dispatch path serving uses."""
    import time

    from bodywork_tpu.models.checkpoint import load_model, resolve_serving_key
    from bodywork_tpu.serve.predictor import PaddedPredictor

    served_key, _source = resolve_serving_key(store)
    model, _d = load_model(store, served_key)
    predictor = PaddedPredictor(model, tuple(sorted(set(buckets))))
    if n_features is None:
        n_features = getattr(model, "n_features", None) or 1
    costs: dict = {}
    for bucket in predictor.buckets:
        X = np.zeros((bucket, n_features), dtype=np.float32)
        predictor.predict(X)  # compile + first-run, untimed
        samples = []
        for _ in range(reps):
            t0 = time.perf_counter()
            predictor.predict(X)
            samples.append(time.perf_counter() - t0)
        costs[int(bucket)] = float(np.median(samples))
    return costs
