"""The tuned-config artifact: how a fitted knob set reaches serving.

``cli tune`` (``tune/model.py``) closes ROADMAP item 5's loop by turning
observed traces into a small JSON document of serving knobs — the four
throughput-critical hand-set values the serving plane exposes today:

- ``batch_window_ms`` / ``batch_max_rows`` — the request coalescer's
  flush policy (``serve/batcher.py``),
- ``buckets`` — the padded-shape ladder the predictor compiles
  (``serve/predictor.py DEFAULT_BUCKETS``),
- ``max_pending`` — the admission budget (``serve/admission.py
  DEFAULT_MAX_PENDING``).

The document lives under the ``tuning/`` store prefix (date-keyed, so
the standard ``history``/``latest`` protocol versions it), is
schema-tagged (:data:`TUNED_CONFIG_SCHEMA`), embeds a ``doc_digest``
(``utils/integrity.py``) plus the full decision trace that produced it,
and gets a digest sidecar + compressed replica through the audit layer
(``audit/manifest.py``) so at-rest rot is detectable and restorable.

Consumption contract (the part that must never take serving down):

- ``cli serve --tuned-config REF`` / env :data:`TUNED_CONFIG_ENV`
  (materialised on the k8s serve Deployment) name a store key or the
  literal ``"latest"``;
- per knob, an EXPLICIT caller value (CLI flag, spec arg, or the knob's
  own env var) always wins over the tuned value, which wins over the
  built-in default — tuning fills gaps, it never overrides an operator;
- a missing, malformed, digest-failing, or out-of-range document
  DEGRADES: bad knob values are dropped one at a time (the
  ``policy_from_env`` convention), an unreadable document reverts every
  knob to its built-in default — with a warning and the
  ``bodywork_tpu_tune_config_state`` gauge flipped to 2, never a
  crash-looping pod. Deleting the whole ``tuning/`` prefix is therefore
  always safe: serving reverts to the hand-set defaults.

Deliberately jax-free and stdlib-only: the fsck checker and the cli
parser both import this module.
"""
from __future__ import annotations

import dataclasses
import json
from datetime import date

from bodywork_tpu.store.base import ArtefactNotFound, ArtefactStore
from bodywork_tpu.store.schema import TUNING_PREFIX, tuned_config_key
from bodywork_tpu.utils.integrity import doc_digest, stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tune.config")

__all__ = [
    "TUNED_CONFIG_ENV",
    "TUNED_CONFIG_SCHEMA",
    "TUNED_KNOB_ENV",
    "KNOB_DEFAULTS",
    "DISPATCHER_SCOPED_KNOBS",
    "ResolvedKnobs",
    "load_tuned_config",
    "resolve_serving_knobs",
    "validate_knobs",
    "write_tuned_config",
]

#: schema tag readers refuse to misinterpret (the request-log convention)
TUNED_CONFIG_SCHEMA = "bodywork_tpu.tuned_config/1"

#: the env knob naming WHICH tuned config a serving pod consumes: a
#: ``tuning/`` store key or the literal ``latest`` (empty = off). The
#: k8s serve Deployment materialises it next to the per-knob env vars.
TUNED_CONFIG_ENV = "BODYWORK_TPU_TUNED_CONFIG"

#: tuned-config schema keys -> the per-knob env var that OVERRIDES each
#: (parsed at pod boot by ``stages._serve_tuned_env_knobs`` /
#: ``stages._serve_env_knobs`` and materialised on the k8s serve
#: Deployment). Guard-pinned three ways by tests/test_tune.py: a knob in
#: only some layers would be unreachable or silently dead.
TUNED_KNOB_ENV = {
    "batch_window_ms": "BODYWORK_TPU_BATCH_WINDOW_MS",
    "batch_max_rows": "BODYWORK_TPU_BATCH_MAX_ROWS",
    "buckets": "BODYWORK_TPU_BUCKETS",
    "max_pending": "BODYWORK_TPU_MAX_PENDING",
}

#: the hand-set defaults the tuner competes against (duplicated as
#: plain values so this module — imported by fsck and the CLI parser —
#: never pays the serve/jax import closure; pinned == the serving
#: modules' own constants by tests/test_tune.py)
KNOB_DEFAULTS = {
    "batch_window_ms": 2.0,   # serve.batcher.DEFAULT_WINDOW_MS
    "batch_max_rows": 64,     # serve.batcher.DEFAULT_MAX_ROWS
    "buckets": (1, 8, 64, 512, 4096),  # serve.predictor.DEFAULT_BUCKETS
    "max_pending": 512,       # serve.admission.DEFAULT_MAX_PENDING
}

#: which tuned knobs bind WHERE in disaggregated serving
#: (``serve --frontends N``): these three shape the single
#: device-owning dispatcher — the ONE coalescer batches form in, the
#: ONE predictor's compiled shape set — and are resolved by
#: ``serve.dispatch.dispatcher_main``. ``max_pending`` is the odd one
#: out: admission must stay UPSTREAM of the row-queue (shed before
#: parse), so the supervisor (``serve.multiproc``) resolves it once and
#: hands the concrete value to every front-end's shared budget. In the
#: flat topologies every knob binds in the one serving process and this
#: split is invisible.
DISPATCHER_SCOPED_KNOBS = ("batch_window_ms", "batch_max_rows", "buckets")


def _valid_window(v) -> float | None:
    # 0.0 is a VALID fitted value: "coalescing off" — at arrival rates
    # that cannot fill a batch, the window (and the dispatcher thread's
    # wakeups) is pure latency tax and the cost model disables it
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if 0.0 <= v <= 1000.0 else None


def _valid_max_rows(v) -> int | None:
    try:
        v = int(v)
    except (TypeError, ValueError):
        return None
    return v if 1 <= v <= 8192 else None


def _valid_buckets(v) -> tuple[int, ...] | None:
    if isinstance(v, (str, bytes)):
        # a string is iterable character-by-character — "18" must not
        # validate as the ladder (1, 8)
        return None
    try:
        buckets = tuple(int(b) for b in v)
    except (TypeError, ValueError):
        return None
    if not 1 <= len(buckets) <= 8:
        return None
    if list(buckets) != sorted(set(buckets)) or buckets[0] < 1:
        return None
    if buckets[-1] > 65536:
        return None
    return buckets


def _valid_max_pending(v) -> int | None:
    try:
        v = int(v)
    except (TypeError, ValueError):
        return None
    return v if 1 <= v <= 1_000_000 else None


_VALIDATORS = {
    "batch_window_ms": _valid_window,
    "batch_max_rows": _valid_max_rows,
    "buckets": _valid_buckets,
    "max_pending": _valid_max_pending,
}


def validate_knobs(knobs: dict) -> tuple[dict, list[str]]:
    """Per-knob validation with the policy_from_env contract: each bad
    value is DROPPED individually (returned in the rejects list) so one
    typo'd knob cannot discard the rest of the tuned document. Unknown
    keys are rejected too — a future schema's knob must not be applied
    by a reader that cannot validate it."""
    if knobs is not None and not isinstance(knobs, dict):
        # a parseable document whose knobs field is the wrong SHAPE
        # (list/string/number) must degrade like any other malformed
        # input, not crash the serving boot with an AttributeError
        return {}, ["knobs"]
    accepted: dict = {}
    rejected: list[str] = []
    for key, value in (knobs or {}).items():
        validator = _VALIDATORS.get(key)
        valid = validator(value) if validator is not None else None
        if valid is None:
            rejected.append(key)
        else:
            accepted[key] = valid
    return accepted, rejected


def _tune_state_gauge():
    from bodywork_tpu.obs import get_registry

    return get_registry().gauge(
        "bodywork_tpu_tune_config_state",
        "Tuned serving config: 0=built-in defaults (no config named), "
        "1=tuned config applied, 2=named config missing/malformed — "
        "degraded to defaults",
        aggregate="max",
    )


def write_tuned_config(store: ArtefactStore, doc: dict,
                       day: date | None = None) -> tuple[str, str]:
    """Persist a tuned-config document (stamping schema + doc_digest)
    at its date-keyed ``tuning/`` location; returns ``(key, digest)``.
    ``doc`` is the tuner's output (``tune.model.fit_tuned_config``):
    knobs + decision trace + observation summary."""
    payload = dict(doc)
    payload["schema"] = TUNED_CONFIG_SCHEMA
    accepted, rejected = validate_knobs(payload.get("knobs"))
    if rejected:
        raise ValueError(
            f"refusing to write a tuned config with invalid knob(s) "
            f"{sorted(rejected)} — the writer must never rely on the "
            "reader's degrade path"
        )
    payload["knobs"] = {
        k: (list(v) if isinstance(v, tuple) else v)
        for k, v in accepted.items()
    }
    payload = stamp_doc(payload)
    key = tuned_config_key(day or date.today())
    store.put_bytes(
        key,
        json.dumps(payload, indent=2, sort_keys=True).encode("utf-8"),
    )
    log.info(
        f"tuned config -> {key} "
        f"({payload['doc_digest'][:23]}..., {len(accepted)} knobs)"
    )
    return key, payload["doc_digest"]


def _resolve_ref(store: ArtefactStore, ref: str) -> str | None:
    """A tuned-config reference -> a concrete store key: ``latest``
    resolves through the standard date-key protocol, restricted to
    ``tuned-config-*`` basenames — ``tuning/`` also holds the learned
    cost model (date-keyed) and the config-lifecycle log, and a cost
    model fitted AFTER the newest tuned config must not shadow it.
    Anything else is taken as the key itself."""
    if ref == "latest":
        try:
            hist = [
                (key, d) for key, d in store.history(TUNING_PREFIX)
                if key.rsplit("/", 1)[-1].startswith("tuned-config-")
            ]
            if not hist:
                return None
            return hist[-1][0]
        except ArtefactNotFound:
            return None
    return ref


def load_tuned_config(
    store: ArtefactStore, ref: str | None
) -> tuple[dict | None, str | None, dict | None]:
    """Load + validate a tuned config; returns ``(knobs, digest, doc)``.

    EVERY failure degrades to ``(None, None, None)`` with a warning —
    an absent key, unparseable bytes, a wrong schema tag, a failing
    doc_digest. Individually invalid knob values are dropped (warned,
    rest kept). The read retries ride the store's own resilience layer;
    this function adds no retry of its own (a corrupt read past the
    store's budget IS the degrade signal)."""
    if not ref:
        return None, None, None
    key = _resolve_ref(store, ref)
    if key is None:
        log.warning(
            f"tuned config {ref!r}: no tuning/ artefacts in the store; "
            "serving with built-in defaults"
        )
        return None, None, None
    try:
        raw = store.get_bytes(key)
    except ArtefactNotFound:
        log.warning(
            f"tuned config {key!r} not found; serving with built-in "
            "defaults"
        )
        return None, None, None
    except Exception as exc:
        log.warning(
            f"tuned config {key!r} unreadable ({exc!r}); serving with "
            "built-in defaults"
        )
        return None, None, None
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, ValueError):
        log.warning(
            f"tuned config {key!r} is not valid JSON; serving with "
            "built-in defaults"
        )
        return None, None, None
    if not isinstance(doc, dict) or doc.get("schema") != TUNED_CONFIG_SCHEMA:
        log.warning(
            f"tuned config {key!r} has schema "
            f"{doc.get('schema') if isinstance(doc, dict) else None!r} "
            f"(expected {TUNED_CONFIG_SCHEMA!r}); serving with built-in "
            "defaults"
        )
        return None, None, None
    if verify_doc(doc) is False:
        log.warning(
            f"tuned config {key!r} fails its embedded doc_digest "
            "(at-rest corruption?); serving with built-in defaults"
        )
        return None, None, None
    knobs, rejected = validate_knobs(doc.get("knobs"))
    if rejected:
        log.warning(
            f"tuned config {key!r}: dropping invalid knob(s) "
            f"{sorted(rejected)}; keeping the {len(knobs)} valid one(s)"
        )
    if not knobs:
        log.warning(
            f"tuned config {key!r} holds no applicable knobs; serving "
            "with built-in defaults"
        )
        return None, None, None
    return knobs, doc_digest(doc), doc


@dataclasses.dataclass
class ResolvedKnobs:
    """The effective serving knobs after the explicit > tuned > default
    merge, plus the evidence /healthz surfaces: the applied document's
    digest (None = defaults) and, per knob, where its value came from
    (``explicit`` | ``tuned`` | ``default``)."""

    batch_window_ms: float | None
    batch_max_rows: int | None
    buckets: tuple[int, ...] | None
    max_pending: int | None
    tuned_digest: str | None
    sources: dict

    def tuned_knob_count(self) -> int:
        return sum(1 for s in self.sources.values() if s == "tuned")


def resolve_serving_knobs(
    store: ArtefactStore | None,
    tuned_ref: str | None,
    batch_window_ms: float | None = None,
    batch_max_rows: int | None = None,
    buckets: tuple[int, ...] | None = None,
    max_pending: int | None = None,
) -> ResolvedKnobs:
    """The ONE merge point serving boots through (``serve_latest_model``,
    ``serve_stage``, the multiproc workers): explicit caller values win,
    then the tuned config's, then None (each consumer's built-in
    default applies downstream, exactly as before this layer existed —
    byte-identical with no tuned config named).

    Sets the ``bodywork_tpu_tune_config_state`` gauge: 0 = no config
    named, 1 = tuned values applied, 2 = a config was NAMED but could
    not be applied (the operator-visible degrade)."""
    explicit = {
        "batch_window_ms": batch_window_ms,
        "batch_max_rows": batch_max_rows,
        "buckets": buckets,
        "max_pending": max_pending,
    }
    knobs = digest = None
    if tuned_ref and store is not None:
        knobs, digest, _doc = load_tuned_config(store, tuned_ref)
    sources: dict = {}
    values: dict = {}
    for name, explicit_value in explicit.items():
        if explicit_value is not None:
            values[name], sources[name] = explicit_value, "explicit"
        elif knobs is not None and name in knobs:
            values[name], sources[name] = knobs[name], "tuned"
        else:
            values[name], sources[name] = None, "default"
    applied = any(s == "tuned" for s in sources.values())
    if tuned_ref:
        _tune_state_gauge().set(1.0 if applied else 2.0)
        if applied:
            log.info(
                f"tuned config applied ({digest[:23]}...): "
                + ", ".join(
                    f"{k}={values[k]}" for k, s in sources.items()
                    if s == "tuned"
                )
            )
    else:
        _tune_state_gauge().set(0.0)
    raw_buckets = values["buckets"]
    return ResolvedKnobs(
        batch_window_ms=values["batch_window_ms"],
        batch_max_rows=values["batch_max_rows"],
        buckets=tuple(raw_buckets) if raw_buckets else None,
        max_pending=values["max_pending"],
        tuned_digest=digest if applied else None,
        sources=sources,
    )
