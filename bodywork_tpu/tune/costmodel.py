"""The learned dispatch-cost model (ROADMAP item 3c).

PR 15's tuner prices a bucket ladder with a MEASURED per-bucket probe
(``tune.collect.probe_dispatch_costs``): honest, but blind outside the
probed rungs — a candidate ladder containing an unprobed bucket keeps
its default, and admission cannot price a request at all. Following "A
Learned Performance Model for TPUs" (PAPERS.md), this module fits a
small closed-form ridge regressor over engineered shape features on the
(bucket, n_features, dtype, mesh) -> dispatch-seconds samples the probe
and exemplar-tagged traces already produce, so that:

- ``tune.model.fit_tuned_config`` can price UNPROBED ladder rungs
  (``cost_model=`` parameter) instead of skipping them;
- the admission layer can estimate a request's dispatch cost BEFORE
  parse-side queueing (``serve.admission`` cost-priced shed, via
  :func:`cost_pricer`);
- the online controller (``tune.online``) re-prices drifted traffic
  without re-running the probe on the serving box.

Model choice, deliberately boring: ridge over log-cost in float64 on
the host. Dispatch cost spans ~4 decades over the ladder, so fitting
``log(seconds)`` makes RELATIVE error the objective (the quantity the
tuner's knee/window arguments consume) and keeps every prediction
positive by construction. Closed-form normal equations — no iterations,
no learning rate, bit-deterministic for a given (samples, seed); the
seeded part is only the held-out split whose relative error the
artefact reports about itself.

The fitted model persists as a digest-stamped JSON artefact under the
``tuning/`` prefix (``tuning/cost-model-<day>.json``), loaded through
the same degrade-never-crash contract as the tuned config: any
validation failure returns ``(None, None)`` and callers fall back to
measured-curve-only behaviour.
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass

from bodywork_tpu.store.base import ArtefactStore
from bodywork_tpu.store.schema import TUNING_PREFIX, cost_model_key
from bodywork_tpu.utils.integrity import doc_digest, stamp_doc, verify_doc
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tune.costmodel")

__all__ = [
    "COST_MODEL_SCHEMA",
    "CostSample",
    "FEATURE_NAMES",
    "cost_pricer",
    "fit_cost_model",
    "load_cost_model",
    "predict_cost",
    "samples_from_probe",
    "write_cost_model",
]

COST_MODEL_SCHEMA = "bodywork_tpu.cost_model/1"

#: engineered features, in weight order. Chosen for what actually moves
#: dispatch cost on this serving path: a fixed per-dispatch floor
#: (bias), the padded row count and total element count (linear terms),
#: their logs (the sub-linear small-shape regime where launch overhead
#: dominates), bytes-per-element for the quantized dtypes, and the
#: per-device row share for sharded meshes.
FEATURE_NAMES = (
    "bias",
    "log2_bucket",
    "bucket",
    "bucket_x_features",
    "log2_bucket_x_features",
    "dtype_bytes",
    "mesh_devices",
    "rows_per_device",
)

#: bytes per element for the serving dtypes (serve.predictor
#: SERVE_DTYPES); unknown dtypes price as float32 rather than failing —
#: a pricer must degrade, never crash the admission path
_DTYPE_BYTES = {"float32": 4.0, "bfloat16": 2.0, "int8": 1.0}

#: cost floor: predictions are clamped here so a wild extrapolation can
#: never return zero/negative seconds to a divider
_MIN_COST_S = 1e-7

#: minimum samples for a fit (one per weight would interpolate noise;
#: the probe's default 7-rung curve clears this)
MIN_SAMPLES = 4


@dataclass(frozen=True)
class CostSample:
    """One measured dispatch: shape in, seconds out."""

    bucket: int
    n_features: int
    seconds: float
    dtype: str = "float32"
    mesh_devices: int = 1


def _features(bucket: int, n_features: int, dtype: str,
              mesh_devices: int) -> list[float]:
    b = float(max(1, int(bucket)))
    f = float(max(1, int(n_features)))
    m = float(max(1, int(mesh_devices)))
    return [
        1.0,
        math.log2(b + 1.0),
        b,
        b * f,
        math.log2(b * f + 1.0),
        _DTYPE_BYTES.get(dtype, 4.0),
        m,
        b / m,
    ]


def samples_from_probe(
    curve: dict[int, float],
    n_features: int,
    dtype: str = "float32",
    mesh_devices: int = 1,
) -> list[CostSample]:
    """The probe's per-bucket median curve
    (``tune.collect.probe_dispatch_costs``) as training samples."""
    return [
        CostSample(bucket=int(b), n_features=int(n_features),
                   seconds=float(s), dtype=dtype,
                   mesh_devices=mesh_devices)
        for b, s in sorted(curve.items())
        if s is not None and s > 0
    ]


def fit_cost_model(
    samples: list[CostSample],
    seed: int = 0,
    ridge: float = 1e-6,
    holdout_fraction: float = 0.25,
) -> dict:
    """Closed-form ridge over log-cost, float64 host numpy. Returns the
    model DOCUMENT body (weights + the held-out relative error it is
    honest about); the writer stamps schema and digest. Deterministic:
    the same (samples, seed) always produce byte-identical weights.

    The held-out split (seeded permutation, ``holdout_fraction`` of the
    samples, at least one) is fitted WITHOUT its members and scored on
    them — ``holdout.mean_rel_err``/``max_rel_err`` are the honest
    extrapolation bound consumers read before trusting a priced rung.
    The shipped weights are then refitted on ALL samples (discarding
    the holdout's information would make the artefact strictly worse
    than its own evaluation).

    Raises ``ValueError`` below :data:`MIN_SAMPLES` — a curve that thin
    should keep the measured-only behaviour, not ship a fake model.
    """
    import numpy as np

    rows = [s for s in samples if s.seconds > 0]
    if len(rows) < MIN_SAMPLES:
        raise ValueError(
            f"cost model needs >= {MIN_SAMPLES} positive samples, "
            f"got {len(rows)}"
        )

    def _design(subset):
        X = np.array(
            [_features(s.bucket, s.n_features, s.dtype, s.mesh_devices)
             for s in subset],
            dtype=np.float64,
        )
        y = np.log(np.array([s.seconds for s in subset], dtype=np.float64))
        return X, y

    def _solve(X, y):
        k = X.shape[1]
        reg = ridge * np.eye(k, dtype=np.float64)
        reg[0, 0] = 0.0  # never shrink the per-dispatch floor
        return np.linalg.solve(X.T @ X + reg, X.T @ y)

    rng = np.random.default_rng(seed)
    order = rng.permutation(len(rows))
    n_holdout = max(1, int(round(holdout_fraction * len(rows))))
    # never hold out so much the train side drops below identifiability
    n_holdout = min(n_holdout, len(rows) - MIN_SAMPLES + 1)
    n_holdout = max(1, n_holdout)
    holdout = [rows[i] for i in order[:n_holdout]]
    train = [rows[i] for i in order[n_holdout:]]
    if not train:  # degenerate tiny set: score in-sample, say so
        train = rows

    Xt, yt = _design(train)
    w_eval = _solve(Xt, yt)
    Xh, yh = _design(holdout)
    pred = np.exp(Xh @ w_eval)
    truth = np.exp(yh)
    rel = np.abs(pred - truth) / truth
    mean_rel = float(rel.mean())
    max_rel = float(rel.max())

    Xa, ya = _design(rows)
    weights = _solve(Xa, ya)

    from bodywork_tpu.obs import get_registry

    get_registry().gauge(
        "bodywork_tpu_tune_costmodel_holdout_error_ratio",
        "Mean held-out relative error of the last fitted dispatch-cost "
        "model (|predicted - measured| / measured)",
    ).set(mean_rel)
    get_registry().counter(
        "bodywork_tpu_tune_costmodel_fits_total",
        "Dispatch-cost-model fits by outcome",
    ).inc(outcome="fitted")
    log.info(
        f"cost model fitted on {len(rows)} samples; held-out relative "
        f"error mean {mean_rel:.1%} / max {max_rel:.1%} over "
        f"{len(holdout)} sample(s)"
    )
    return {
        "schema": COST_MODEL_SCHEMA,
        "target": "log_seconds",
        "feature_names": list(FEATURE_NAMES),
        "weights": [float(v) for v in weights],
        "ridge": ridge,
        "seed": seed,
        "n_samples": len(rows),
        "samples": [
            {"bucket": s.bucket, "n_features": s.n_features,
             "seconds": s.seconds, "dtype": s.dtype,
             "mesh_devices": s.mesh_devices}
            for s in rows
        ],
        "holdout": {
            "n": len(holdout),
            "fraction": holdout_fraction,
            "mean_rel_err": mean_rel,
            "max_rel_err": max_rel,
            "in_sample": train is rows,
        },
    }


def predict_cost(
    doc: dict,
    bucket: int,
    n_features: int,
    dtype: str = "float32",
    mesh_devices: int = 1,
) -> float:
    """Predicted dispatch seconds for one padded shape, floored at
    :data:`_MIN_COST_S` (an extrapolation must never hand a divider
    zero)."""
    weights = doc["weights"]
    feats = _features(bucket, n_features, dtype, mesh_devices)
    log_cost = sum(w * f for w, f in zip(weights, feats))
    # exp() overflow guard: a corrupt weight vector prices as "huge",
    # which every consumer treats as "don't" — the safe direction
    return max(_MIN_COST_S, math.exp(min(log_cost, 50.0)))


def cost_pricer(
    doc: dict,
    n_features: int,
    buckets: tuple[int, ...] | None = None,
    dtype: str = "float32",
    mesh_devices: int = 1,
):
    """A ``rows -> estimated dispatch seconds`` callable for the
    admission layer's cost-priced shed: the request prices as the cost
    of the LADDER RUNG it would pad to (the shape the device actually
    runs), or its own pow2 cover when no ladder is given."""
    ladder = tuple(sorted(buckets)) if buckets else None

    def price(rows: int = 1) -> float:
        rows = max(1, int(rows))
        if ladder:
            cover = next((b for b in ladder if b >= rows), ladder[-1])
        else:
            cover = 1 if rows <= 1 else 2 ** math.ceil(math.log2(rows))
        return predict_cost(doc, cover, n_features, dtype, mesh_devices)

    return price


# -- the persisted artefact ------------------------------------------------


def write_cost_model(store: ArtefactStore, doc: dict, day) -> tuple[str, str]:
    """Persist one fitted model under ``tuning/cost-model-<day>.json``
    (stamped; same prefix and audit coverage as the tuned config).
    Returns ``(key, doc_digest)``."""
    if doc.get("schema") != COST_MODEL_SCHEMA or not isinstance(
        doc.get("weights"), list
    ):
        raise ValueError("not a cost-model document")
    stamped = stamp_doc(dict(doc))
    key = cost_model_key(day)
    store.put_bytes(
        key, json.dumps(stamped, sort_keys=True, indent=1).encode("utf-8")
    )
    log.info(f"wrote cost model {key} ({stamped['doc_digest'][:23]}…)")
    return key, stamped["doc_digest"]


def load_cost_model(store: ArtefactStore, ref: str = "latest"):
    """``(doc, digest)`` for a stored cost model, degrading to
    ``(None, None)`` on ANY failure (absent, unparseable, wrong schema,
    digest mismatch, malformed weights) — consumers then price nothing
    and the measured curve carries on alone, exactly the tuned-config
    loader's contract."""
    try:
        if ref == "latest":
            candidates = [
                k for k in store.list_keys(TUNING_PREFIX)
                if k.rsplit("/", 1)[-1].startswith("cost-model-")
            ]
            if not candidates:
                return None, None
            key = max(candidates)  # date-keyed: lexicographic == newest
        else:
            key = ref
        raw = store.get_bytes(key)
        doc = json.loads(raw.decode("utf-8"))
        if (
            not isinstance(doc, dict)
            or doc.get("schema") != COST_MODEL_SCHEMA
            or verify_doc(doc) is False
            or not isinstance(doc.get("weights"), list)
            or len(doc["weights"]) != len(FEATURE_NAMES)
            or not all(
                isinstance(w, (int, float)) and math.isfinite(w)
                for w in doc["weights"]
            )
        ):
            log.warning(f"cost model {key!r} failed validation; ignoring it")
            return None, None
        return doc, doc.get("doc_digest") or doc_digest(doc)
    except Exception as exc:
        log.warning(f"cost model {ref!r} unreadable ({exc!r}); ignoring it")
        return None, None
