"""The cost model: one observation table -> a tuned serving config.

An analytical-plus-fitted hybrid in the spirit of "A Learned Performance
Model for Tensor Processing Units" (PAPERS.md): where a clean queueing
argument exists the knob is solved in closed form over MEASURED inputs
(arrival rate, per-bucket dispatch cost, saturated service rate), and
where the input is a distribution the knob is fitted to its observed
quantiles (the padding-bucket ladder over the offered row shapes). No
knob is ever guessed: a knob whose evidence is missing keeps its
hand-set default, and the decision trace says so.

Per-knob models (each decision records chosen vs default + its basis):

- ``batch_max_rows`` — the smallest measured bucket achieving
  :data:`THROUGHPUT_KNEE` of the cost curve's peak rows/s. Beyond the
  knee, bigger flushes add latency linearly while adding throughput
  sublinearly; at it, a full flush pads to exactly one compiled shape.
- ``batch_window_ms`` — the coalescer window is worth holding requests
  for only while (a) the measured arrival rate can actually FILL a
  batch within it and (b) the wait it adds is commensurate with the
  dispatch cost it amortises. Window = ``WINDOW_DISPATCH_MULTIPLE`` x
  the measured dispatch cost at the chosen flush size, clamped to
  [:data:`MIN_WINDOW_MS`, :data:`MAX_WINDOW_MS`] — and set to ``0.0``
  (coalescing OFF, direct per-request dispatch) when the expected
  arrivals per maximum window (``rate x MAX_WINDOW``) cannot reach
  :data:`MIN_FILL_ROWS`: a window sparse traffic cannot fill is pure
  latency tax, and on a small box the dispatcher thread's sub-ms
  wakeups are themselves measurable tail cost (the profile-1
  regression the bench measures).
- ``buckets`` — the ladder is fitted to the offered row-shape
  quantiles: next-power-of-two covers of {1, p50, p90, p99, max} (plus
  the flush size, so a full coalesced batch pads to a compiled shape).
  The hand-set ladder pads a 700-row request to 4096; the fitted one
  stops at its 1024 cover — 4x less wasted compute per dispatch.
- ``max_pending`` — Little's-law sizing of the admission budget: the
  queue the service should HOLD is the work it can clear within the
  queue-delay budget, ``service_rate x QUEUE_BUDGET_S`` (clamped).
  Requires a MEASURED service rate (a saturated drive's goodput, or
  the scoring-latency inverse as the closed-loop proxy); without one
  the budget keeps its default — a guessed budget is how SLOs die.

Every decision is exported through obs
(``bodywork_tpu_tune_decisions_total{knob,source}``) and, when a span
recorder is passed, as one span per knob with chosen-vs-default meta —
the decision trace ``cli tune --trace-out`` renders and the tuned
document embeds.
"""
from __future__ import annotations

import math

from bodywork_tpu.tune.collect import ObservationTable
from bodywork_tpu.tune.config import KNOB_DEFAULTS, validate_knobs
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tune.model")

__all__ = [
    "MAX_WINDOW_MS",
    "MIN_WINDOW_MS",
    "QUEUE_BUDGET_S",
    "THROUGHPUT_KNEE",
    "fit_tuned_config",
]

#: window clamp: below ~0.3 ms the dispatcher's own wakeup jitter
#: dominates; above ~10 ms the window is a visible latency tax on every
#: idle-service request
MIN_WINDOW_MS = 0.3
MAX_WINDOW_MS = 10.0
#: the window pays when it can assemble at least this many rows
MIN_FILL_ROWS = 2.0
#: window as a multiple of the measured per-dispatch cost it amortises
WINDOW_DISPATCH_MULTIPLE = 4.0
#: batch_max_rows knee: smallest bucket at >= this fraction of the cost
#: curve's peak throughput
THROUGHPUT_KNEE = 0.7
#: admission sizing: the queue the service may hold is what it can
#: clear in this many seconds (the queue-delay budget a shed's
#: Retry-After is honest about)
QUEUE_BUDGET_S = 0.25
#: admission budget clamp (a tiny budget sheds healthy bursts; a huge
#: one recreates the unbounded queue admission exists to prevent)
MIN_MAX_PENDING = 32
MAX_MAX_PENDING = 4096

#: candidate ladder rungs: powers of two (the compiled-shape-count
#: argument for the hand-set ladder, kept)
_MAX_BUCKET = 4096


def _pow2_cover(n: int) -> int:
    """The smallest power of two >= n (the padded shape covering n)."""
    return 1 if n <= 1 else 2 ** math.ceil(math.log2(n))


def _count_decision(knob: str, source: str) -> None:
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_tune_decisions_total",
        "Tuner knob decisions by knob and source (fitted=model chose "
        "from evidence, default=evidence missing, kept default)",
    ).inc(knob=knob, source=source)


def _decide_max_rows(table: ObservationTable, default: int) -> dict:
    curve = table.dispatch_cost_s
    if not curve:
        return {
            "knob": "batch_max_rows", "chosen": default, "default": default,
            "source": "default",
            "basis": "no measured dispatch-cost curve",
        }
    throughput = {
        b: (b / c if c > 0 else 0.0) for b, c in curve.items() if b >= 1
    }
    peak = max(throughput.values())
    knee = min(
        b for b, t in sorted(throughput.items())
        if t >= THROUGHPUT_KNEE * peak
    )
    chosen = max(8, min(512, knee))
    return {
        "knob": "batch_max_rows", "chosen": chosen, "default": default,
        "source": "fitted",
        "basis": (
            f"smallest measured bucket at >={THROUGHPUT_KNEE:.0%} of peak "
            f"dispatch throughput (knee={knee} rows, peak="
            f"{peak:.0f} rows/s), clamped to [8, 512]"
        ),
        "evidence": {
            "throughput_rows_per_s": {
                str(b): round(t, 1) for b, t in sorted(throughput.items())
            },
        },
    }


def _decide_window(table: ObservationTable, default: float,
                   max_rows: int) -> dict:
    rate = table.arrival_rate_rps()
    curve = table.dispatch_cost_s
    if rate is None:
        return {
            "knob": "batch_window_ms", "chosen": default, "default": default,
            "source": "default",
            "basis": "no measured arrival process",
        }
    fill_at_max = rate * (MAX_WINDOW_MS / 1e3)
    if fill_at_max < MIN_FILL_ROWS:
        chosen = 0.0
        basis = (
            f"measured arrival rate {rate:.1f} rps cannot assemble "
            f"{MIN_FILL_ROWS:.0f} rows within the {MAX_WINDOW_MS:.0f} ms "
            f"window cap (expected fill {fill_at_max:.2f}); the window "
            "— and the dispatcher thread's wakeups — is pure latency "
            "tax at this rate, so coalescing is DISABLED (0 = off, "
            "direct per-request dispatch)"
        )
    else:
        # the window is worth the dispatch cost it amortises: hold for
        # a few dispatch-times, bounded by what the arrival rate fills
        flush_cost_s = None
        if curve:
            cover = min(
                (b for b in curve if b >= max_rows), default=max(curve)
            )
            flush_cost_s = curve[cover]
        window_s = (
            WINDOW_DISPATCH_MULTIPLE * flush_cost_s
            if flush_cost_s is not None
            else max_rows / (4.0 * rate)
        )
        fill_bound_s = max_rows / rate  # past this the batch is full anyway
        chosen = min(window_s, fill_bound_s) * 1e3
        chosen = min(max(chosen, MIN_WINDOW_MS), MAX_WINDOW_MS)
        basis = (
            f"{WINDOW_DISPATCH_MULTIPLE:.0f}x the measured "
            f"{(flush_cost_s or 0) * 1e3:.2f} ms flush-size dispatch "
            f"cost, capped by the {max_rows}-row fill time at "
            f"{rate:.0f} rps, clamped to "
            f"[{MIN_WINDOW_MS}, {MAX_WINDOW_MS}] ms"
        )
    chosen = round(chosen, 3)
    return {
        "knob": "batch_window_ms", "chosen": chosen, "default": default,
        "source": "fitted", "basis": basis,
        "evidence": {
            "arrival_rate_rps": round(rate, 2),
            "expected_fill_at_cap": round(fill_at_max, 3),
        },
    }


def _decide_buckets(table: ObservationTable, default: tuple,
                    max_rows: int, coalescing_on: bool) -> dict:
    shape = table.row_quantiles()
    if shape is None:
        return {
            "knob": "buckets", "chosen": list(default),
            "default": list(default), "source": "default",
            "basis": "no observed row-shape distribution",
        }
    rungs = {1}
    for q in ("p50", "p90", "p99", "max"):
        rungs.add(min(_pow2_cover(shape[q]), _MAX_BUCKET))
    if coalescing_on:
        # coalesced flushes take EVERY size from 1 to max_rows, not
        # just the offered per-request shapes: without intermediate
        # rungs a 5-row flush pads to the flush-size cover — the exact
        # padding waste the ladder exists to avoid (found empirically:
        # a {1, 512} ladder under moderate coalesced load inflated
        # per-flush compute ~100x)
        flush_cover = _pow2_cover(max_rows)
        rungs.add(flush_cover)
        rungs.update(b for b in (8, 64) if b < flush_cover)
    chosen = tuple(sorted(rungs))[:8]
    basis = (
        "power-of-two covers of the observed row-shape quantiles "
        f"(p50={shape['p50']}, p90={shape['p90']}, "
        f"p99={shape['p99']}, max={shape['max']} over "
        f"{shape['n']} requests)"
    )
    if coalescing_on:
        basis += (
            " plus the geometric coalesced-flush ladder up to the "
            "flush size (flushes take every size from 1 to max_rows)"
        )
    basis += f" — the largest rung is the max cover, not {max(default)}"
    return {
        "knob": "buckets", "chosen": list(chosen),
        "default": list(default), "source": "fitted",
        "basis": basis,
        "evidence": {"row_shape": shape},
    }


def _decide_max_pending(table: ObservationTable, default: int) -> dict:
    service = table.service_rate_rps()
    if service is None:
        return {
            "knob": "max_pending", "chosen": default, "default": default,
            "source": "default",
            "basis": "no measured service rate (no saturated drive, no "
                     "scoring-latency evidence)",
        }
    chosen = int(
        min(max(round(service * QUEUE_BUDGET_S), MIN_MAX_PENDING),
            MAX_MAX_PENDING)
    )
    measured_how = (
        "saturated-drive goodput"
        if table.saturated_goodput_rps is not None
        else "inverse mean scoring latency"
    )
    return {
        "knob": "max_pending", "chosen": chosen, "default": default,
        "source": "fitted",
        "basis": (
            f"Little's law over the measured service rate "
            f"({service:.0f} rps by {measured_how}) x the "
            f"{QUEUE_BUDGET_S}s queue-delay budget, clamped to "
            f"[{MIN_MAX_PENDING}, {MAX_MAX_PENDING}]"
        ),
        "evidence": {
            "service_rate_rps": round(service, 1),
            "queue_budget_s": QUEUE_BUDGET_S,
        },
    }


def _price_unprobed_rungs(table: ObservationTable, cost_model: dict):
    """Augment the measured dispatch-cost curve with LEARNED prices for
    every candidate power-of-two rung the probe never timed
    (``tune/costmodel.py``) — measured entries always win. Returns
    ``(augmented_table, provenance)`` without mutating the caller's
    table; provenance (model digest + priced rungs + the model's own
    held-out error bound) rides the tuned document so an audit can see
    exactly which decisions leaned on extrapolation."""
    import dataclasses as _dc

    from bodywork_tpu.tune.costmodel import predict_cost

    samples = cost_model.get("samples") or []
    if not samples:
        return table, None

    def _majority(field, default):
        counts: dict = {}
        for s in samples:
            v = s.get(field, default)
            counts[v] = counts.get(v, 0) + 1
        return max(counts.items(), key=lambda kv: (kv[1], str(kv[0])))[0]

    n_features = int(_majority("n_features", 1))
    dtype = str(_majority("dtype", "float32"))
    mesh = int(_majority("mesh_devices", 1))
    candidates = [2 ** i for i in range(int(math.log2(_MAX_BUCKET)) + 1)]
    priced = {
        b: predict_cost(cost_model, b, n_features, dtype, mesh)
        for b in candidates if b not in table.dispatch_cost_s
    }
    provenance = {
        "digest": cost_model.get("doc_digest"),
        "priced_buckets": sorted(priced),
        "measured_buckets": sorted(table.dispatch_cost_s),
        "holdout": cost_model.get("holdout"),
    }
    if not priced:
        return table, provenance
    augmented = _dc.replace(
        table,
        dispatch_cost_s={**priced, **table.dispatch_cost_s},
        sources=list(table.sources) + ["cost_model"],
    )
    return augmented, provenance


def fit_tuned_config(
    table: ObservationTable,
    defaults: dict | None = None,
    recorder=None,
    cost_model: dict | None = None,
) -> dict:
    """Fit every knob from ``table``; returns the tuned-config document
    body (knobs + decision trace + observation summary — the writer
    stamps schema and digest). A PURE function of the table: the same
    observations always produce the same config, which is what makes a
    tune replayable from archived traces.

    ``recorder`` (an ``obs.spans.SpanRecorder``) gets one span per knob
    with chosen-vs-default meta — the decision trace ``cli tune
    --trace-out`` renders through the existing Chrome emitter.

    ``cost_model`` (a loaded ``tune.costmodel`` document) prices the
    candidate ladder rungs the probe never measured, so the knee and
    window decisions see the FULL power-of-two curve instead of
    degrading wherever the probe was thin; the document records which
    rungs were priced vs measured (still a pure function — of the table
    AND the model document)."""
    defaults = {**KNOB_DEFAULTS, **(defaults or {})}
    cost_model_provenance = None
    if cost_model is not None:
        table, cost_model_provenance = _price_unprobed_rungs(
            table, cost_model
        )
    max_rows_decision = _decide_max_rows(table, defaults["batch_max_rows"])
    max_rows = max_rows_decision["chosen"]
    window_decision = _decide_window(
        table, defaults["batch_window_ms"], max_rows
    )
    decisions = [
        max_rows_decision,
        window_decision,
        _decide_buckets(
            table, tuple(defaults["buckets"]), max_rows,
            # the ladder must cover coalesced flush sizes whenever the
            # served config coalesces — fitted OR default window > 0
            coalescing_on=window_decision["chosen"] > 0,
        ),
        _decide_max_pending(table, defaults["max_pending"]),
    ]
    # ONLY fitted knobs enter the document: for the window and the
    # admission budget the default VALUE is not the default BEHAVIOUR
    # (a bare boot leaves coalescing off and thread-engine admission
    # unarmed) — writing a default-sourced 2.0 ms / 512 would turn
    # both ON the moment the document is applied, which is exactly the
    # "knob whose evidence is missing keeps its hand-set default"
    # contract violated. The decision trace still records every kept
    # default.
    knobs = {
        d["knob"]: d["chosen"] for d in decisions if d["source"] == "fitted"
    }
    accepted, rejected = validate_knobs(knobs)
    assert not rejected, f"cost model produced invalid knob(s): {rejected}"
    for d in decisions:
        _count_decision(d["knob"], d["source"])
        if recorder is not None:
            with recorder.span(
                f"tune-{d['knob']}", category="tune",
                knob=d["knob"], chosen=d["chosen"], default=d["default"],
                source=d["source"], basis=d["basis"],
            ):
                pass
    fitted = sum(1 for d in decisions if d["source"] == "fitted")
    from bodywork_tpu.obs import get_registry

    get_registry().counter(
        "bodywork_tpu_tune_runs_total",
        "Tuner fits by outcome (fitted=at least one knob left its "
        "default on evidence, insufficient_data=every knob kept its "
        "default)",
    ).inc(outcome="fitted" if fitted else "insufficient_data")
    log.info(
        f"tuned {fitted}/{len(decisions)} knobs from "
        f"{len(table.sources)} source(s): "
        + ", ".join(
            f"{d['knob']}={d['chosen']}" for d in decisions
            if d["source"] == "fitted"
        )
    )
    doc = {
        "knobs": {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in accepted.items()
        },
        "decisions": decisions,
        "observations": table.summary(),
        "defaults": {
            k: (list(v) if isinstance(v, tuple) else v)
            for k, v in defaults.items()
        },
    }
    if cost_model_provenance is not None:
        doc["cost_model"] = cost_model_provenance
    return doc
