"""Online re-tune controller: the tuning loop closed against live traffic.

The offline tuner (``cli tune``) fits knobs from archived traces and an
operator applies them at the NEXT boot. That leaves the lifecycle loop
open exactly where the paper closes it for models: drift. A serving
process that boots into a uniform single-row workload and drifts into a
bursty wide-batch one is running knobs fitted for traffic that no
longer exists — and nobody re-runs the tuner, because re-running it
means noticing. This module is the noticing:

- :class:`OnlineTuneController` runs inside the reload-watcher loop
  (``serve/reload.py`` polls it right after the SLO watchdog — the two
  are siblings: one judges model releases, this one judges CONFIG
  releases). Each poll it ingests its watch logs INCREMENTALLY
  (byte-offset cursors, ``tune.collect.IngestCursor`` — O(new entries)
  per poll, counted on ``bodywork_tpu_tune_ingest_bytes_total``) into a
  sliding window of per-poll observation tables.
- **Drift detection** is a pure comparison of the merged window's
  arrival rate / row shape against the reference shape the ACTIVE
  config was fitted on. Past ``drift_threshold`` (with enough
  samples), the controller refits: ``fit_tuned_config`` over the
  merged window — priced by the learned cost model
  (``tune/costmodel.py``) wherever the window lacks probe evidence —
  writes the new document through the existing writer, records the
  apply in the config log (``registry/configlog.py``, ONE CAS), and
  applies the knobs MID-FLIGHT: coalescer window/max-rows mutate in
  place (``RequestCoalescer.reconfigure``), the admission budget is an
  attribute store, and a bucket-ladder change is a warmed predictor
  swap the AOT executable cache makes zero-compile (the watcher's
  ``apply_bucket_ladder``).
- **Config-as-canary**: every applied config opens a guard window
  against the service-wide baseline captured at apply
  (``ops.slo.serve_window_snapshot``). A post-apply window that burns
  the error budget or regresses p99 past ``revert_p99_ratio`` is
  auto-reverted — previous knob values re-applied in place, the revert
  recorded in exactly ONE CAS, the flight recorder dumped as evidence
  (verdict ``config_revert``) — within ``verdict_polls`` polls, by the
  same verdict style the model watchdog uses. A healthy window
  graduates silently (no CAS: the log already says what is active).

Determinism: NOTHING in this module reads a clock or draws randomness —
poll decisions are pure functions of (window deltas, cursor state,
policy, seed), the property the no-wall-clock guard test pins
statically. "Time" only enters as the poll cadence the watcher imposes
and the timestamps already recorded in the logs it reads.
"""
from __future__ import annotations

import dataclasses
from datetime import date

from bodywork_tpu.tune.collect import (
    IngestCursor,
    ObservationTable,
    ingest_request_log_incremental,
    ingest_results_log_incremental,
)
from bodywork_tpu.utils.logging import get_logger

log = get_logger("tune.online")

__all__ = [
    "MUTABLE_LIVE_KNOBS",
    "OnlineTunePolicy",
    "OnlineTuneController",
    "policy_from_env",
]

#: every knob the controller can mutate on a LIVE service — pinned
#: four ways by tests/test_tune.py (== the tuner's fittable knobs ==
#: validate_knobs' accepted keys == TUNED_KNOB_ENV): a knob the tuner
#: can fit but the controller cannot apply would silently partial-apply
#: every online refit
MUTABLE_LIVE_KNOBS = (
    "batch_window_ms",
    "batch_max_rows",
    "buckets",
    "max_pending",
)

#: bodywork_tpu_tune_online_state encoding
STATE_IDLE, STATE_GUARDING, STATE_REVERTED = 0.0, 1.0, 2.0


@dataclasses.dataclass
class OnlineTunePolicy:
    """The controller's knobs. Defaults are sized like the SLO
    watchdog's: decisive within seconds of a real shift under even
    light traffic, while the sample floors keep a handful of unlucky
    requests from triggering a refit or a revert."""

    #: merged-window interarrival samples required before the drift
    #: decision may fire (and before the reference shape is pinned)
    min_window_requests: int = 200
    #: relative change in arrival rate OR row-shape p90 vs the active
    #: config's reference shape that counts as drift
    drift_threshold: float = 0.5
    #: per-poll tables kept in the sliding window
    window_polls: int = 10
    #: polls to sit out after an apply/revert before the next drift
    #: decision (the new regime needs a window of its own evidence)
    cooldown_polls: int = 3
    #: the guard window's poll budget: a breach must fire within this
    #: many polls of an apply; surviving them healthy graduates
    verdict_polls: int = 6
    #: service-wide requests required in the post-apply window before a
    #: guard verdict may fire
    min_verdict_requests: int = 20
    #: guard breach: post-apply windowed error rate at/above this
    revert_error_rate: float = 0.02
    #: guard breach: post-apply p99 at/above this multiple of the
    #: pre-apply window's p99
    revert_p99_ratio: float = 2.0
    #: latency samples required on BOTH windows before the p99 verdict
    revert_min_latency_samples: int = 20
    #: recorded into every refit state dump; reserved for future
    #: sampled decisions — determinism demands it be pinned NOW so a
    #: replay of today's records stays bit-stable when it is used
    seed: int = 0

    def validate(self) -> None:
        if self.min_window_requests < 1:
            raise ValueError("min_window_requests must be >= 1")
        if self.drift_threshold <= 0.0:
            raise ValueError("drift_threshold must be > 0")
        if self.window_polls < 1:
            raise ValueError("window_polls must be >= 1")
        if self.cooldown_polls < 0:
            raise ValueError("cooldown_polls must be >= 0")
        if self.verdict_polls < 1:
            raise ValueError("verdict_polls must be >= 1")
        if self.min_verdict_requests < 1:
            raise ValueError("min_verdict_requests must be >= 1")
        if not 0.0 < self.revert_error_rate <= 1.0:
            raise ValueError("revert_error_rate must be in (0, 1]")
        if self.revert_p99_ratio <= 1.0:
            raise ValueError("revert_p99_ratio must be > 1")
        if self.revert_min_latency_samples < 1:
            raise ValueError("revert_min_latency_samples must be >= 1")


def policy_from_env() -> OnlineTunePolicy:
    """The deployed controller knobs from ``BODYWORK_TPU_TUNE_*`` env
    vars (the k8s serve Deployment materialises them —
    ``pipeline/k8s.py``), with the SLO policy's per-field degrade
    contract: a malformed or out-of-range value is warned and ignored,
    every other override survives, the pod never crashes on a typo."""
    import os

    policy = OnlineTunePolicy()
    for env_name, field, cast in (
        ("BODYWORK_TPU_TUNE_MIN_WINDOW_REQUESTS", "min_window_requests", int),
        ("BODYWORK_TPU_TUNE_DRIFT_THRESHOLD", "drift_threshold", float),
        ("BODYWORK_TPU_TUNE_COOLDOWN_POLLS", "cooldown_polls", int),
        ("BODYWORK_TPU_TUNE_VERDICT_POLLS", "verdict_polls", int),
        (
            "BODYWORK_TPU_TUNE_MIN_VERDICT_REQUESTS",
            "min_verdict_requests", int,
        ),
        ("BODYWORK_TPU_TUNE_REVERT_ERROR_RATE", "revert_error_rate", float),
        ("BODYWORK_TPU_TUNE_REVERT_P99_RATIO", "revert_p99_ratio", float),
    ):
        raw = os.environ.get(env_name, "").strip()
        if not raw:
            continue
        try:
            value = cast(raw)
        except ValueError:
            log.warning(f"ignoring {env_name}={raw!r} (malformed)")
            continue
        previous = getattr(policy, field)
        setattr(policy, field, value)
        try:
            policy.validate()
        except ValueError as exc:
            log.warning(f"ignoring {env_name}={raw!r} ({exc})")
            setattr(policy, field, previous)
    return policy


def _merge_window(tables) -> ObservationTable:
    """Fold the sliding window's per-poll tables into the one merged
    table a refit fits against. List evidence concatenates; the
    saturation measurement takes the max (it is a rate, the strongest
    observation wins)."""
    merged = ObservationTable()
    for t in tables:
        merged.interarrival_s.extend(t.interarrival_s)
        merged.row_counts.extend(t.row_counts)
        merged.latency_s.extend(t.latency_s)
        merged.queue_delay_s.extend(t.queue_delay_s)
        merged.dispatch_cost_s.update(t.dispatch_cost_s)
        if t.saturated_goodput_rps is not None:
            merged.saturated_goodput_rps = max(
                merged.saturated_goodput_rps or 0.0, t.saturated_goodput_rps
            )
        merged.sources.extend(t.sources)
    return merged


class OnlineTuneController:
    """Drift -> refit -> guarded apply -> (graduate | one-CAS revert).

    ``poll()`` is driven once per reload-watcher cycle (and directly by
    tests / the bench). The controller never blocks the request path:
    it reads logs and counters the serving threads write, and its two
    store writes (the tuned document, the config-log CAS) happen off
    the hot path inside the watcher thread.
    """

    def __init__(
        self,
        store,
        app,
        policy: OnlineTunePolicy | None = None,
        request_logs=(),
        results_logs=(),
        defaults: dict | None = None,
        cost_model_ref: str | None = "latest",
        apply_buckets=None,
    ):
        from pathlib import Path

        from bodywork_tpu.obs import get_registry

        self.store = store
        self.app = app
        self.policy = policy or OnlineTunePolicy()
        self.policy.validate()
        self.request_logs = [Path(p) for p in request_logs]
        self.results_logs = [Path(p) for p in results_logs]
        self.defaults = defaults
        #: cost-model reference priced into every refit (None = off)
        self.cost_model_ref = cost_model_ref
        #: callable(tuple_of_buckets) applying a ladder change as a
        #: warmed predictor swap — the reload watcher wires its
        #: ``apply_bucket_ladder``; None skips ladder changes (counted)
        self.apply_buckets = apply_buckets
        self._cursors: dict = {}
        self._window: list = []
        #: the shape the active knobs were fitted for (None until the
        #: first adequate window pins it)
        self._reference: dict | None = None
        self._guard: dict | None = None
        self._cooldown = 0
        #: cumulative serve snapshot at boot / last verdict — the
        #: pre-apply window every guard baseline p99 is computed from
        self._anchor: dict | None = None
        self._last_state: dict = {"state": "idle"}
        reg = get_registry()
        self._g_state = reg.gauge(
            "bodywork_tpu_tune_online_state",
            "Online tune controller: 0=idle (watching for drift), "
            "1=guarding a freshly applied config, 2=reverted one this "
            "poll",
            aggregate="max",
        )
        self._g_drift = reg.gauge(
            "bodywork_tpu_tune_drift_ratio",
            "Observed traffic-shape drift vs the active config's "
            "reference shape (>= threshold refits)",
            aggregate="max",
        )
        self._m_refits = reg.counter(
            "bodywork_tpu_tune_online_refits_total",
            "Online refit attempts by outcome (applied, skipped_no_"
            "knobs=fit kept every default, skipped_conflict=lost the "
            "config-log CAS to a concurrent controller)",
        )
        self._m_reverts = reg.counter(
            "bodywork_tpu_tune_online_reverts_total",
            "Tuned configs auto-reverted by the guard verdict, by "
            "breach reason (error_budget|latency)",
        )
        self._g_state.set(STATE_IDLE)

    # -- state ---------------------------------------------------------------

    def state(self) -> dict:
        """The /healthz ``tuning`` block (also pushed onto the app
        every poll)."""
        return dict(self._last_state)

    def _publish(self, state: dict) -> None:
        state["seed"] = self.policy.seed
        self._last_state = state
        self.app.tune_state = dict(state)

    # -- ingestion -----------------------------------------------------------

    def _ingest(self) -> ObservationTable:
        """One poll's table: every complete line appended to a watch
        file since the last poll. A broken/missing file degrades to an
        empty contribution (warned once per failure) — the controller
        outlives its log files."""
        table = ObservationTable()
        for kind, paths, ingest in (
            ("request", self.request_logs, ingest_request_log_incremental),
            ("results", self.results_logs, ingest_results_log_incremental),
        ):
            for path in paths:
                cursor = self._cursors.get(path)
                try:
                    self._cursors[path] = ingest(
                        table, path, cursor or IngestCursor()
                    )
                except FileNotFoundError:
                    continue  # not written yet — normal before a drive
                except Exception as exc:  # torn/foreign file: skip poll
                    log.warning(f"{kind} log {path}: ingest failed: {exc!r}")
        self._window.append(table)
        if len(self._window) > self.policy.window_polls:
            self._window = self._window[-self.policy.window_polls:]
        return table

    # -- shape + drift -------------------------------------------------------

    @staticmethod
    def _shape(table: ObservationTable) -> dict | None:
        rate = table.arrival_rate_rps()
        rows = table.row_quantiles()
        if rate is None or rows is None:
            return None
        return {"arrival_rate_rps": rate, "row_p90": rows["p90"]}

    def _drift_ratio(self, shape: dict) -> float:
        ref = self._reference
        rate_drift = abs(shape["arrival_rate_rps"] - ref["arrival_rate_rps"]) / max(
            ref["arrival_rate_rps"], 1e-9
        )
        rows_drift = abs(shape["row_p90"] - ref["row_p90"]) / max(
            ref["row_p90"], 1.0
        )
        return max(rate_drift, rows_drift)

    # -- live knob application ----------------------------------------------

    def _live_knobs(self) -> dict:
        """The knob values currently live in the process — captured
        before an apply so a revert-to-boot restores exactly them."""
        cfg = self.app.effective_config()
        return {
            k: cfg.get(k) for k in MUTABLE_LIVE_KNOBS
            if cfg.get(k) is not None
        }

    def _apply_knobs(self, knobs: dict) -> dict:
        """Mutate the live service to ``knobs``; returns what was
        actually applied. Each knob that cannot be applied here (no
        coalescer/admission/ladder callback) is skipped with a warning
        — the config log still records the intent, and /healthz
        ``effective_config`` reads the live objects, so a partial
        apply is visible, never silent."""
        applied: dict = {}
        batcher = self.app.batcher
        window_ms = knobs.get("batch_window_ms")
        max_rows = knobs.get("batch_max_rows")
        if window_ms is not None or max_rows is not None:
            if batcher is not None and (window_ms is None or window_ms > 0):
                applied.update(batcher.reconfigure(
                    window_ms=window_ms, max_rows=max_rows,
                ))
            else:
                log.warning(
                    "skipping live coalescer knobs "
                    f"(window_ms={window_ms}, max_rows={max_rows}): "
                    + ("no coalescer is running" if batcher is None
                       else "0=off is a boot-time topology decision")
                )
        max_pending = knobs.get("max_pending")
        if max_pending is not None:
            admission = self.app.admission
            if admission is not None:
                admission.max_pending = int(max_pending)
                applied["max_pending"] = int(max_pending)
            else:
                log.warning(
                    f"skipping live max_pending={max_pending}: no "
                    "admission controller is armed"
                )
        buckets = knobs.get("buckets")
        if buckets is not None:
            current = self.app.effective_config().get("buckets")
            if current is not None and tuple(current) == tuple(buckets):
                pass  # same ladder: nothing to swap, zero device work
            elif self.apply_buckets is not None:
                try:
                    self.apply_buckets(tuple(buckets))
                    applied["buckets"] = list(buckets)
                except Exception as exc:
                    log.error(f"bucket-ladder apply failed: {exc!r}")
            else:
                log.warning(
                    "skipping bucket-ladder change: no apply_buckets "
                    "callback wired (watcher not attached)"
                )
        return applied

    # -- refit + guarded apply -----------------------------------------------

    def _config_day(self) -> date:
        """The date key for an online-written tuned document, WITHOUT
        reading a clock: the served model's date (the day whose traffic
        is being tuned for), falling back to the epoch for a dateless
        boot — the key is an address, the document's digest is its
        identity."""
        model_date = self.app.model_date
        if model_date:
            try:
                return date.fromisoformat(str(model_date))
            except ValueError:
                pass
        return date(1970, 1, 1)

    def _load_cost_model(self):
        if self.cost_model_ref is None:
            return None
        from bodywork_tpu.tune.costmodel import load_cost_model

        doc, _digest = load_cost_model(self.store, self.cost_model_ref)
        return doc

    def _refit(self, merged: ObservationTable, shape: dict,
               drift: float) -> str | None:
        from bodywork_tpu.tune.config import write_tuned_config
        from bodywork_tpu.tune.model import fit_tuned_config

        doc = fit_tuned_config(
            merged, defaults=self.defaults,
            cost_model=self._load_cost_model(),
        )
        if not doc["knobs"]:
            log.info("drift refit kept every default; nothing to apply")
            self._m_refits.inc(outcome="skipped_no_knobs")
            self._reference = shape  # the new regime IS the reference now
            self._cooldown = self.policy.cooldown_polls
            return None
        key, digest = write_tuned_config(self.store, doc, day=self._config_day())
        reason = f"drift_refit(ratio={round(drift, 3)})"
        return self.apply_tuned(
            doc["knobs"], key, digest, reason=reason, shape=shape
        )

    def apply_tuned(self, knobs: dict, key: str, digest: str,
                    reason: str = "manual", shape: dict | None = None,
                    ) -> str | None:
        """Apply a tuned config to the LIVE service under guard: record
        it in the config log (ONE CAS), mutate the live knobs, open the
        guard window. The refit path calls this; so can an operator /
        the bench (that is how the sabotage acceptance injects its
        absurd config through the same machinery it expects to catch
        it)."""
        from bodywork_tpu.ops.slo import (
            serve_window_delta,
            serve_window_snapshot,
        )
        from bodywork_tpu.registry.configlog import (
            ConfigLogConflict,
            record_config_applied,
        )

        baseline = serve_window_snapshot()
        pre_window = (
            serve_window_delta(self._anchor, baseline)
            if self._anchor is not None else None
        )
        prior = self._live_knobs()
        baseline_summary = {
            "requests": baseline["requests"],
            "errors": baseline["errors"],
            "latency_samples": baseline["count"],
            "p99_s": pre_window["p99_s"] if pre_window else None,
            "pre_window_latency_samples": (
                pre_window["latency_samples"] if pre_window else 0
            ),
        }
        try:
            record_config_applied(
                self.store, key, digest, knobs,
                baseline=baseline_summary, reason=reason,
            )
        except ConfigLogConflict:
            log.warning(
                "config apply lost the config-log CAS; a concurrent "
                "controller acted — deferring to it"
            )
            self._m_refits.inc(outcome="skipped_conflict")
            self._cooldown = self.policy.cooldown_polls
            return None
        applied = self._apply_knobs(knobs)
        self.app.tuned_config_digest = digest
        self._guard = {
            "key": key,
            "digest": digest,
            "knobs": dict(knobs),
            "prior": prior,
            "baseline": baseline,
            "baseline_p99_s": baseline_summary["p99_s"],
            "baseline_latency_samples":
                baseline_summary["pre_window_latency_samples"],
            "polls": 0,
            "reason": reason,
        }
        if shape is not None:
            self._reference = shape
        self._m_refits.inc(outcome="applied")
        self._g_state.set(STATE_GUARDING)
        self._cooldown = self.policy.cooldown_polls
        log.info(
            f"tuned config applied LIVE ({digest[:23]}…, {reason}): "
            f"{applied} — guarding for {self.policy.verdict_polls} polls"
        )
        self._publish({
            "state": "guarding", "config": digest, "key": key,
            "applied": applied, "reason": reason, "polls": 0,
        })
        return "applied"

    # -- the guard window ----------------------------------------------------

    def _guard_verdict(self, window: dict) -> str | None:
        """The revert decision — a pure function of the post-apply
        window deltas and the guard's pinned baseline (no clocks, no
        RNG): same contract as ``SloPolicy.verdict``."""
        policy = self.policy
        if window["requests"] < policy.min_verdict_requests:
            return None
        if window["error_rate"] >= policy.revert_error_rate:
            return "error_budget"
        g = self._guard
        base_p99 = g.get("baseline_p99_s")
        if (
            base_p99
            and window["p99_s"] is not None
            and window["latency_samples"]
            >= policy.revert_min_latency_samples
            and g.get("baseline_latency_samples", 0)
            >= policy.revert_min_latency_samples
            and window["p99_s"] / base_p99 >= policy.revert_p99_ratio
        ):
            return "latency"
        return None

    def _dump_flight_record(self, reason: str, digest: str,
                            window: dict | None) -> str | None:
        """The revert's evidence: the tracer's ring of sampled request
        traces, dumped under ``obs/flightrec/`` with verdict
        ``config_revert`` — best-effort, never blocking the CAS."""
        from bodywork_tpu.obs.tracing import (
            flight_record_doc,
            get_tracer,
            write_flight_record,
        )

        tracer = get_tracer()
        if not tracer.enabled:
            return None
        try:
            doc = flight_record_doc(
                tracer.recorder.snapshot(),
                verdict="config_revert",
                reason=reason,
                canary_key=f"tuned-config:{digest}",
                production_key=self.app.model_key,
                window=window,
                sampling={
                    "seed": tracer.seed,
                    "fraction": tracer.sample_fraction,
                },
            )
            key = write_flight_record(self.store, doc)
        except Exception as exc:  # noqa: BLE001 — evidence, not verdict
            log.error(f"config-revert flight-record dump failed: {exc!r}")
            return None
        from bodywork_tpu.obs import get_registry

        get_registry().counter(
            "bodywork_tpu_flight_record_dumps_total",
            "Flight-recorder dumps written to obs/flightrec/ at "
            "watchdog verdicts, by verdict (abort|promote)",
        ).inc(verdict="config_revert")
        log.info(
            f"flight record: {doc['n_traces']} trace(s) -> {key} "
            f"(config_revert: {reason})"
        )
        return key

    def _revert(self, breach: str, window: dict) -> str:
        from bodywork_tpu.registry.configlog import (
            ConfigLogConflict,
            record_config_reverted,
        )

        g = self._guard
        detail = (
            f"config guard breach: {breach} "
            f"(requests={window['requests']}, errors={window['errors']}, "
            f"p99_s={window['p99_s']}, "
            f"baseline_p99_s={g.get('baseline_p99_s')})"
        )
        log.error(
            f"tuned config {g['digest'][:23]}… BREACHED — "
            f"auto-reverting: {detail}"
        )
        # evidence first, so the ONE CAS can carry the dump key
        dump_key = self._dump_flight_record(detail, g["digest"], window)
        restored_entry = None
        try:
            restored_entry, _reverted = record_config_reverted(
                self.store, reason=detail, flight_record=dump_key,
            )
        except (ConfigLogConflict, ValueError) as exc:
            # a concurrent controller already reverted (or the log moved
            # under us): the in-process knobs still need restoring —
            # the local apply was ours
            log.warning(f"config-log revert not recorded here: {exc}")
        if restored_entry is not None:
            self._apply_knobs(restored_entry["knobs"])
            self.app.tuned_config_digest = restored_entry["digest"]
        else:
            self._apply_knobs(g["prior"])
            self.app.tuned_config_digest = None
        from bodywork_tpu.ops.slo import serve_window_snapshot

        self._m_reverts.inc(reason=breach)
        self._g_state.set(STATE_REVERTED)
        self._guard = None
        self._cooldown = self.policy.cooldown_polls
        self._anchor = serve_window_snapshot()
        self._publish({
            "state": "reverted", "verdict": breach, "detail": detail,
            "config": g["digest"], "restored": (
                restored_entry["digest"] if restored_entry else None
            ),
            "flight_record": dump_key,
        })
        return "reverted"

    def _poll_guard(self) -> str | None:
        from bodywork_tpu.ops.slo import (
            serve_window_delta,
            serve_window_snapshot,
        )

        g = self._guard
        g["polls"] += 1
        now = serve_window_snapshot()
        window = serve_window_delta(g["baseline"], now)
        breach = self._guard_verdict(window)
        if breach is not None:
            return self._revert(breach, window)
        if g["polls"] >= self.policy.verdict_polls:
            # survived the budget healthy: graduate. No CAS — the
            # config log already records it as active; the guard state
            # simply closes and the post-apply regime becomes the
            # anchor for the NEXT apply's baseline p99.
            log.info(
                f"tuned config {g['digest'][:23]}… survived its guard "
                f"window healthy ({window['requests']} requests)"
            )
            self._guard = None
            self._anchor = now
            self._g_state.set(STATE_IDLE)
            self._publish({
                "state": "idle", "graduated": g["digest"],
                "window": {
                    "requests": window["requests"],
                    "errors": window["errors"],
                    "p99_s": window["p99_s"],
                },
            })
            return "graduated"
        self._publish({
            "state": "guarding", "config": g["digest"],
            "polls": g["polls"],
            "window": {
                "requests": window["requests"],
                "errors": window["errors"],
                "p99_s": window["p99_s"],
            },
        })
        return None

    # -- the loop ------------------------------------------------------------

    def poll(self) -> str | None:
        """One controller cycle. Returns the action applied this poll
        (``"applied"`` | ``"reverted"`` | ``"graduated"``) or None."""
        from bodywork_tpu.ops.slo import serve_window_snapshot

        if self._anchor is None:
            self._anchor = serve_window_snapshot()
        self._ingest()
        if self._guard is not None:
            return self._poll_guard()
        self._g_state.set(STATE_IDLE)
        if self._cooldown > 0:
            self._cooldown -= 1
            self._publish({"state": "idle", "cooldown": self._cooldown})
            return None
        merged = _merge_window(self._window)
        if len(merged.interarrival_s) < self.policy.min_window_requests:
            self._publish({
                "state": "idle",
                "window_samples": len(merged.interarrival_s),
            })
            return None
        shape = self._shape(merged)
        if shape is None:
            self._publish({"state": "idle", "window_samples": 0})
            return None
        if self._reference is None:
            # first adequate window: pin the reference, don't refit —
            # the knobs the service booted with were (presumably)
            # chosen for the shape it boots into
            self._reference = shape
            self._publish({"state": "idle", "reference": shape})
            return None
        drift = self._drift_ratio(shape)
        self._g_drift.set(drift)
        if drift < self.policy.drift_threshold:
            self._publish({
                "state": "idle", "drift_ratio": round(drift, 4),
            })
            return None
        log.info(
            f"traffic shape drifted {round(drift, 3)}x past threshold "
            f"{self.policy.drift_threshold} "
            f"(now {shape}, reference {self._reference}); refitting"
        )
        return self._refit(merged, shape, drift)
