from bodywork_tpu.utils.logging import configure_logger
from bodywork_tpu.utils.dates import (
    DATE_PATTERN,
    date_from_key,
    day_of_year,
    parse_date,
)
from bodywork_tpu.utils.errors import init_error_monitoring, StageError

__all__ = [
    "configure_logger",
    "DATE_PATTERN",
    "date_from_key",
    "day_of_year",
    "parse_date",
    "init_error_monitoring",
    "StageError",
]
