from bodywork_tpu.utils.logging import configure_logger
from bodywork_tpu.utils.dates import (
    DATE_PATTERN,
    date_from_key,
    day_of_year,
    parse_date,
)
from bodywork_tpu.utils.errors import init_error_monitoring, StageError
from bodywork_tpu.utils.sync import fence
from bodywork_tpu.utils.watchdog import (
    abort_if_backend_hangs,
    backend_timeout_from_env,
)

__all__ = [
    "abort_if_backend_hangs",
    "backend_timeout_from_env",
    "configure_logger",
    "DATE_PATTERN",
    "date_from_key",
    "day_of_year",
    "fence",
    "parse_date",
    "init_error_monitoring",
    "StageError",
]
