"""Persistent XLA compilation-cache wiring (VERDICT r3 item 5).

The reference pays no compile cost — its model compute is sklearn
(``stage_1_train_model.py:105-106``). Here every cold process re-traces
and re-compiles each XLA program (~2.5 s on day 1 vs ~0.09 s steady in
the config-1 bench), and the k8s materialisation runs each daily stage as
a one-shot pod, so without a persistent cache the pipeline re-pays every
compile every day. JAX's persistent compilation cache keys executables by
program fingerprint; pointing it at the shared store volume (or any
stable path) lets today's pod reuse yesterday's compiles.

Resolution order: explicit path > ``BODYWORK_TPU_COMPILE_CACHE`` env >
``JAX_COMPILATION_CACHE_DIR`` env (native JAX config-from-env — already
live, nothing to do) > disabled.
"""
from __future__ import annotations

import os

from bodywork_tpu.utils.logging import get_logger

log = get_logger("utils.compile_cache")

ENV_VAR = "BODYWORK_TPU_COMPILE_CACHE"


def enable_compile_cache(
    path: str | None = None, min_compile_time_s: float = 0.5
) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` and return the
    resolved path (``None`` = disabled, no config touched).

    Must run before the programs whose compiles should be cached are first
    traced (any time before is fine — the cache is consulted per compile).
    ``min_compile_time_s`` floors which compiles are persisted; the
    default catches every real XLA program here while skipping trivial
    sub-second op compiles.
    """
    path = path or os.environ.get(ENV_VAR)
    if not path:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(path))
    # an EXPLICIT operator choice via JAX's own env var wins over our
    # default floor — clobbering it made "persist everything" requests
    # silently flaky around the threshold (compiles hovering near 0.5 s
    # landed or vanished with machine load)
    if "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS" not in os.environ:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_compile_time_s),
        )
    # the default cache policy skips "uninteresting" backends/programs;
    # the daily pods want every program cached, CPU CI included
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    log.info(f"persistent XLA compilation cache at {path}")
    return str(path)
