"""Date-key utilities for artefact versioning.

The reference versions every artefact by a date embedded in the object key and
re-derives it in every stage with the same regex
(``stage_1_train_model.py:47``, ``stage_2_serve_model.py:50``,
``stage_4_test_model_scoring_service.py:43``). That protocol is centralised
here once.
"""
from __future__ import annotations

import re
from datetime import date, datetime, timedelta
from functools import lru_cache

# Same date grammar as the reference's regex: years 2020-2099.
DATE_PATTERN = re.compile(r"20[2-9][0-9]-[0-1][0-9]-[0-3][0-9]")


def parse_date(date_string: str) -> date:
    return datetime.strptime(date_string, "%Y-%m-%d").date()


@lru_cache(maxsize=8192)
def date_from_key(key: str) -> date | None:
    """Extract the (first) embedded date from an artefact key, if any.

    Returns None both when no date-shaped substring exists and when the
    match is not a real calendar date (the regex admits e.g. month 15) —
    such keys are ignored by the versioning protocol rather than crashing
    every store consumer.

    Memoised: keys are immutable strings and every ``history()`` call
    re-parses its whole listing, so a long-horizon store paid O(days)
    strptime per listing per day, forever (a measured growth term in the
    config-10 flatness profile). ``date`` objects are immutable, so the
    shared cache is safe.
    """
    match = DATE_PATTERN.search(key)
    if match is None:
        return None
    try:
        return parse_date(match.group(0))
    except ValueError:
        return None


def day_of_year(d: date) -> int:
    """1-based day-of-year, as used by the drift sinusoid (``stage_3:38``)."""
    return d.timetuple().tm_yday


def date_range(start: date, days: int) -> list[date]:
    """``days`` consecutive dates starting at ``start`` (simulated days)."""
    return [start + timedelta(days=i) for i in range(days)]
