"""Shared env-var parsing for operational knobs."""
from __future__ import annotations

import math
import os

from bodywork_tpu.utils.logging import get_logger

log = get_logger("utils.env")

__all__ = ["positive_float_env"]


def positive_float_env(name: str, default: float) -> float:
    """A finite float > 0 from ``name``, or ``default`` — garbage (and
    NaN, which every ``<= 0`` check silently passes) is ignored with a
    warning rather than crashing the entrypoint."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        value = None
    if value is None or not (value > 0) or not math.isfinite(value):
        log.warning(f"ignoring {name}={raw!r} (need a finite number > 0)")
        return default
    return value
