"""Error monitoring hook (reference C9) and stage failure types.

The reference initialises Sentry in every stage entrypoint
(``sentry_sdk.init(dsn, traces_sample_rate=1.0)`` +
``set_tag('stage', ...)`` — ``stage_1_train_model.py:171-172`` and clones).
Here error monitoring is a *pluggable, optional* hook: if ``sentry_sdk`` is
importable and ``SENTRY_DSN`` is set, it is enabled; otherwise it is a no-op.
This fixes the reference behaviour of hard-failing when ``SENTRY_DSN`` is
unset (``get_sentry_dsn`` raises — ``stage_1:161-167``), and the copy-paste
bug where stage 4 tags itself ``'stage-4-generate-next-dataset'``
(``stage_4:164``).
"""
from __future__ import annotations

import os

from bodywork_tpu.utils.logging import get_logger

log = get_logger("errors")


class StageError(RuntimeError):
    """A pipeline stage failed; carries the stage name for the orchestrator."""

    def __init__(self, stage: str, message: str):
        super().__init__(f"stage '{stage}' failed: {message}")
        self.stage = stage


def init_error_monitoring(stage: str, traces_sample_rate: float = 1.0) -> bool:
    """Initialise the optional Sentry integration for a stage.

    Returns True if monitoring was enabled, False if running without it.
    """
    dsn = os.environ.get("SENTRY_DSN")
    if not dsn:
        return False
    try:
        import sentry_sdk  # type: ignore
    except ImportError:
        log.warning("SENTRY_DSN set but sentry_sdk not installed; continuing")
        return False
    sentry_sdk.init(dsn, traces_sample_rate=traces_sample_rate)
    sentry_sdk.set_tag("stage", stage)
    return True


def tag_stage(stage: str) -> None:
    """Re-tag the active error-monitoring scope with the actual stage name.

    The CLI initialises monitoring before the stage is known (the pod
    entrypoint tags ``cli-run-stage``); once ``run-stage`` resolves its
    stage, this overrides the tag so every stage pod reports under its own
    name — the reference tags each entrypoint with its stage
    (``stage_1_train_model.py:172``; its stage-4 copy-paste bug fixed).
    No-op when monitoring is disabled.
    """
    if not os.environ.get("SENTRY_DSN"):
        return
    try:
        import sentry_sdk  # type: ignore
    except ImportError:
        return
    sentry_sdk.set_tag("stage", stage)
