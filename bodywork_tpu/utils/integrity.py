"""Embedded content digests for CAS-mutated JSON documents.

Every JSON artefact class whose document is mutated in place (run
journals, registry records, the alias document — ``trainstate/`` already
carries its own) embeds a ``doc_digest`` field: the sha256 of the
document's CANONICAL serialization with the digest field removed. The
digest makes silent at-rest corruption *detectable* even when a flipped
byte leaves the JSON parseable and schema-valid — the gap the integrity
scrubber (``bodywork_tpu/audit/fsck.py``) exists to close: schema checks
catch structural damage, the digest catches semantic damage.

Canonical form: ``json.dumps(doc, sort_keys=True,
separators=(",", ":"))`` over the digest-less document — independent of
the indent/whitespace the document was actually stored with, so readers
that round-trip a document through ``json.loads`` can verify it without
access to the original bytes. (The one corruption class this cannot see
is a whitespace-to-whitespace byte flip, which by construction changes
no content; full raw-byte coverage for non-JSON classes comes from the
audit sidecar digests instead.)

Verification is BACKWARD-COMPATIBLE: a document without the field (one
written before this layer existed) verifies as ``None`` — "no digest
recorded" — which readers accept and the scrubber reports as an
advisory ``undigested`` finding whose repair is a rewrite.

Stdlib-only: journal and registry readers sit on serving and stage hot
paths and must not widen any pinned dependency closure.
"""
from __future__ import annotations

import hashlib
import json

DOC_DIGEST_FIELD = "doc_digest"

__all__ = [
    "DOC_DIGEST_FIELD",
    "doc_digest",
    "sha256_digest",
    "stamp_doc",
    "verify_doc",
]


def sha256_digest(data: bytes) -> str:
    """The ONE raw-byte content-digest format every evidence source
    shares — run-journal artefact digests, registry lineage digests,
    audit sidecar digests. They must produce byte-identical strings
    (fsck cross-checks them against each other), so the format lives
    here and the three subsystems delegate."""
    return "sha256:" + hashlib.sha256(data).hexdigest()


def doc_digest(doc: dict) -> str:
    """sha256 over the canonical serialization of ``doc`` with the
    digest field removed (the document must otherwise be JSON-able)."""
    payload = {k: v for k, v in doc.items() if k != DOC_DIGEST_FIELD}
    data = json.dumps(payload, sort_keys=True, separators=(",", ":"))
    return "sha256:" + hashlib.sha256(data.encode("utf-8")).hexdigest()


def stamp_doc(doc: dict) -> dict:
    """Return ``doc`` with its ``doc_digest`` field set (in place —
    writers stamp immediately before serializing)."""
    doc[DOC_DIGEST_FIELD] = doc_digest(doc)
    return doc


def verify_doc(doc: dict) -> bool | None:
    """True when the embedded digest matches the document's content,
    False when it does not (corruption), None when no digest is
    embedded (a legacy document — acceptable to readers, advisory to
    the scrubber)."""
    recorded = doc.get(DOC_DIGEST_FIELD)
    if recorded is None:
        return None
    return recorded == doc_digest(doc)
