"""Shared logging (reference C8).

The reference duplicates an identical ``configure_logger`` in all four stage
scripts (e.g. ``stage_1_train_model.py:145-158``); here it is a single shared
module. The log format is kept identical so operators see the same lines:
``asctime - levelname - module.funcName - message`` to stdout.

The reference also creates the logger only under ``__main__`` and references
the module-global ``log`` from library functions (a known bug — importing a
stage module breaks). Here loggers are real module-level loggers obtained via
``get_logger``.
"""
from __future__ import annotations

import logging
import sys

LOG_FORMAT = (
    "%(asctime)s - "
    "%(levelname)s - "
    "%(module)s.%(funcName)s - "
    "%(message)s"
)

_ROOT_NAME = "bodywork_tpu"


def configure_logger(
    level: str | int = logging.INFO, stream=None
) -> logging.Logger:
    """Configure the framework's root logger (stdout by default, like the
    reference's ``configure_logger``; pass ``stream=sys.stderr`` when stdout
    must stay machine-readable, e.g. bench output).

    Idempotent: repeated calls do not stack handlers; passing a different
    ``stream`` re-points the existing handler.
    """
    logger = logging.getLogger(_ROOT_NAME)
    # exact type check: FileHandler etc. subclass StreamHandler and must not
    # have their streams hijacked
    handlers = [h for h in logger.handlers if type(h) is logging.StreamHandler]
    if handlers:
        if stream is not None:  # only an explicit stream re-points
            for h in handlers:
                try:
                    h.setStream(stream)
                except ValueError:
                    # setStream flushes the OLD stream first; a dead one
                    # (test-harness capture torn down, redirected pipe
                    # closed) must not block re-pointing to a live one —
                    # swap the attribute directly, nothing to flush
                    h.acquire()
                    try:
                        h.stream = stream
                    finally:
                        h.release()
    else:
        handler = logging.StreamHandler(stream if stream is not None else sys.stdout)
        handler.setFormatter(logging.Formatter(LOG_FORMAT))
        logger.addHandler(handler)
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    logger.setLevel(level)
    return logger


def get_logger(name: str) -> logging.Logger:
    """Return a child logger under the framework root (e.g. ``store``)."""
    configure_logger()
    return logging.getLogger(f"{_ROOT_NAME}.{name}")
