"""Shared logging (reference C8).

The reference duplicates an identical ``configure_logger`` in all four stage
scripts (e.g. ``stage_1_train_model.py:145-158``); here it is a single shared
module. The log format is kept identical so operators see the same lines:
``asctime - levelname - module.funcName - message`` to stdout.

The reference also creates the logger only under ``__main__`` and references
the module-global ``log`` from library functions (a known bug — importing a
stage module breaks). Here loggers are real module-level loggers obtained via
``get_logger``.
"""
from __future__ import annotations

import logging
import sys

LOG_FORMAT = (
    "%(asctime)s - "
    "%(levelname)s - "
    "%(module)s.%(funcName)s - "
    "%(message)s"
)

_ROOT_NAME = "bodywork_tpu"


def configure_logger(level: str | int = logging.INFO) -> logging.Logger:
    """Configure the framework's root logger to write to stdout.

    Idempotent: repeated calls do not stack handlers.
    """
    logger = logging.getLogger(_ROOT_NAME)
    if not any(
        isinstance(h, logging.StreamHandler) and getattr(h, "stream", None) is sys.stdout
        for h in logger.handlers
    ):
        handler = logging.StreamHandler(sys.stdout)
        handler.setFormatter(logging.Formatter(LOG_FORMAT))
        logger.addHandler(handler)
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    logger.setLevel(level)
    return logger


def get_logger(name: str) -> logging.Logger:
    """Return a child logger under the framework root (e.g. ``store``)."""
    configure_logger()
    return logging.getLogger(f"{_ROOT_NAME}.{name}")
