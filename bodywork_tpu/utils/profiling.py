"""Tracing/profiling hooks (reference aux subsystem, SURVEY.md §5).

The reference traces every stage through Sentry performance tracing at
full sample rate (``sentry_sdk.init(..., traces_sample_rate=1.0)`` —
``stage_1_train_model.py:171`` and clones) plus wall-clock request-latency
measurement in the tester (``stage_4:75-78``). The TPU-native equivalents:

- request latency is a first-class metric already (``monitor.tester``);
- device-side visibility comes from ``jax.profiler`` traces, viewable in
  TensorBoard/Perfetto or XProf — this module is the on/off switch the
  orchestrator and CLI expose.
"""
from __future__ import annotations

import contextlib

from bodywork_tpu.utils.logging import get_logger

log = get_logger("profiling")


@contextlib.contextmanager
def maybe_trace(trace_dir: str | None, label: str = ""):
    """``jax.profiler.trace`` when ``trace_dir`` is set, no-op otherwise.

    Invocations must be sequential — the jax profiler raises if a trace
    is already active, so wrap ONE outer region (e.g. the whole
    simulation) and use :func:`annotate` for named spans inside it.
    """
    if not trace_dir:
        yield
        return
    import jax

    log.info(f"tracing {label or 'region'} -> {trace_dir}")
    with jax.profiler.trace(trace_dir):
        yield


@contextlib.contextmanager
def annotate(name: str):
    """Named sub-region inside an active trace (shows up as a span)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
