"""Unified resilience primitives: error taxonomy, retry policy, breaker.

Before this module every layer improvised its own failure handling: the
GCS backend had a private fixed-exponential backoff loop, the pipeline
runner burned every ``stage.retries`` attempt against the completion
deadline regardless of whether the failure could ever succeed on retry,
and the live-service tester only retried connection-level failures
(``HTTPAdapter(max_retries=...)`` never sees a 503 *response*). This
module defines the policy ONCE and every layer adopts it:

- **Taxonomy** — :func:`is_transient` (strict allowlist, matched by
  exception-class NAME so optional dependencies' error classes count
  without being importable) and :func:`is_permanent` (strict denylist of
  deterministic programming/lookup errors). :func:`classify_error` walks
  the ``__cause__``/``__context__`` chain so a wrapped transient error
  (e.g. a ``StageError`` raised ``from`` a ``ConnectionError``) keeps
  its retryability.
- **Policy** — :class:`RetryPolicy` + :func:`call_with_retry`:
  exponential backoff with FULL jitter (sleep ~ U(0, min(base·2ᵏ, max))
  — synchronized workers decorrelate instead of thundering-herding) and
  a per-op deadline budget capping cumulative sleep.
- **Breaker** — :class:`CircuitBreaker`: closed → open after N
  consecutive transient failures → half-open single probe → closed on
  probe success. ``store.resilient.ResilientStore`` wires it over any
  artefact-store backend.

The chaos subsystem (:mod:`bodywork_tpu.chaos`) injects faults that this
module's consumers must absorb; ``tests/test_chaos.py`` guards that no
store module re-grows a private backoff loop.
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time

__all__ = [
    "TRANSIENT_ERROR_NAMES",
    "PERMANENT_ERROR_TYPES",
    "TransientError",
    "is_transient",
    "is_permanent",
    "classify_error",
    "RetryPolicy",
    "call_with_retry",
    "full_jitter_delay",
    "CircuitBreaker",
    "CircuitOpenError",
]

#: exception type names treated as transient. Matched by NAME through the
#: MRO because several sources (google.api_core, requests) are optional
#: dependencies this module must classify without importing. The set is
#: an ALLOWLIST: unknown errors are NOT transient (a store retry loop
#: must never spin on a deterministic failure).
TRANSIENT_ERROR_NAMES = frozenset({
    # google.api_core HTTP classes (503/429/500/502/504) + client retries
    "ServiceUnavailable",
    "TooManyRequests",
    "InternalServerError",
    "BadGateway",
    "GatewayTimeout",
    "DeadlineExceeded",
    "RetryError",
    # stdlib / requests connection-level failures
    "ConnectionError",
    "ConnectionResetError",
    "BrokenPipeError",
    "TimeoutError",
    "Timeout",
    "ConnectTimeout",
    "ReadTimeout",
    # this module's own marker class (chaos faults subclass it)
    "TransientError",
})

#: deterministic failures: retrying can never succeed, so a retry loop
#: must fail fast instead of burning its attempt/deadline budget.
#: (``ArtefactNotFound`` is a ``KeyError`` subclass and lands here.)
PERMANENT_ERROR_TYPES = (
    ValueError,
    TypeError,
    KeyError,
    AttributeError,
    NotImplementedError,
)


class TransientError(Exception):
    """A failure expected to clear on retry. ``retry_after_s`` (when the
    failing side names one, e.g. an HTTP ``Retry-After``) is honoured by
    :func:`call_with_retry` as a floor under the jittered sleep."""

    retry_after_s: float | None = None


def is_transient(exc: BaseException) -> bool:
    """True when ``exc`` is on the transient allowlist (name-matched
    through the MRO, so optional-dependency classes count)."""
    return any(
        t.__name__ in TRANSIENT_ERROR_NAMES for t in type(exc).__mro__
    )


def _chain(exc: BaseException, limit: int = 8):
    seen = set()
    while exc is not None and id(exc) not in seen and limit > 0:
        seen.add(id(exc))
        limit -= 1
        yield exc
        exc = exc.__cause__ or exc.__context__


def is_permanent(exc: BaseException) -> bool:
    """True when ``exc`` is a deterministic failure (denylist match with
    no transient error anywhere in its cause chain)."""
    return classify_error(exc) == "permanent"


def classify_error(exc: BaseException) -> str:
    """``"transient"`` | ``"permanent"`` | ``"unknown"``.

    Walks the ``__cause__``/``__context__`` chain: a transient error
    anywhere in the chain wins (a ``StageError`` raised ``from`` a 503
    is still worth retrying), then the permanent denylist, then
    ``"unknown"`` — which callers treat per their own default (stage
    retries keep retrying unknowns, store retry loops do not).
    """
    links = list(_chain(exc))
    if any(is_transient(e) for e in links):
        return "transient"
    from bodywork_tpu.utils.errors import StageError

    if isinstance(exc, (*PERMANENT_ERROR_TYPES, StageError)):
        return "permanent"
    return "unknown"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with full jitter and a deadline budget.

    ``attempts`` includes the first try. ``deadline_s`` caps the
    CUMULATIVE time (op time + sleeps) an op may consume across retries
    — once exceeded, the last error propagates instead of sleeping
    further (a caller's own deadline must not be eaten by backoff).
    """

    attempts: int = 3
    base_delay_s: float = 0.1
    max_delay_s: float = 2.0
    deadline_s: float = 30.0

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {self.attempts}")


#: process-wide jitter source; tests inject their own rng/sleep instead
_JITTER_RNG = random.Random()


def full_jitter_delay(attempt: int, base_s: float, max_s: float,
                      rng=None) -> float:
    """THE backoff delay: full jitter over an exponential cap,
    ``U(0, min(base·2^attempt, max))`` (attempt 0 = first retry).
    Every retry loop in the tree — :func:`call_with_retry`, the
    netqueue reconnect/leader-re-resolve loop, the standby election
    poll — draws its sleep from this one function, so decorrelation is
    a property of the codebase, not of whichever module remembered to
    jitter (tests/test_chaos.py guards that no store or serve module
    re-grows a private ``delay *=`` loop)."""
    cap = min(base_s * (2 ** max(0, attempt)), max_s)
    if cap <= 0:
        return 0.0
    return (rng or _JITTER_RNG).uniform(0.0, cap)


def call_with_retry(
    fn,
    policy: RetryPolicy = RetryPolicy(),
    *,
    is_retryable=is_transient,
    on_retry=None,
    sleep=time.sleep,
    rng=None,
    clock=time.monotonic,
):
    """Run ``fn()`` under ``policy``: retry failures ``is_retryable``
    accepts, with full-jitter exponential backoff, until the attempt or
    deadline budget runs out (then the last error propagates).

    ``on_retry(exc, attempt, sleep_s)`` fires before each backoff sleep —
    the hook through which call sites report retries to the obs registry
    (the util itself stays metric-free). A ``retry_after_s`` attribute on
    the raised error (HTTP 429/503 ``Retry-After``) floors the jittered
    sleep, but only up to ``policy.max_delay_s``: the server's hint is a
    politeness floor, the caller's policy bounds its patience — a client
    configured for millisecond backoff must not be stalled for seconds
    per attempt by a server that advertises a long retry horizon. ``fn``
    must fully materialise its result (paged iteration included) so a
    retry never splices two inconsistent halves.
    """
    rng = rng or _JITTER_RNG
    start = clock()
    for attempt in range(policy.attempts):
        try:
            return fn()
        except Exception as exc:
            if not is_retryable(exc) or attempt == policy.attempts - 1:
                raise
            remaining = policy.deadline_s - (clock() - start)
            if remaining <= 0:
                raise
            delay = full_jitter_delay(
                attempt, policy.base_delay_s, policy.max_delay_s, rng=rng
            )
            floor = getattr(exc, "retry_after_s", None)
            if floor:
                delay = max(delay, min(float(floor), policy.max_delay_s))
            delay = min(delay, remaining)
            if on_retry is not None:
                on_retry(exc, attempt + 1, delay)
            sleep(delay)


class CircuitOpenError(RuntimeError):
    """Fast-fail: the breaker is open — the backend has failed enough
    consecutive ops that further calls are rejected without touching it
    until the reset timeout admits a half-open probe. Deliberately NOT
    on the transient allowlist (a store retry loop must not spin against
    an open breaker) and not on the permanent denylist (a stage retry
    may succeed once the backend recovers)."""


class CircuitBreaker:
    """closed → open after ``failure_threshold`` consecutive failures →
    (after ``reset_timeout_s``) half-open, admitting ONE probe → closed
    on probe success, open again on probe failure.

    Callers bracket each op: :meth:`allow` before (raises
    :class:`CircuitOpenError` when rejecting), then exactly one of
    :meth:`record_success` / :meth:`record_failure`. ``on_state_change``
    receives each new state name — the hook the store wrapper uses to
    export ``bodywork_tpu_store_breaker_state``.
    """

    CLOSED = "closed"
    HALF_OPEN = "half_open"
    OPEN = "open"

    #: gauge encoding of the state machine (docs/RESILIENCE.md)
    STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}

    def __init__(
        self,
        failure_threshold: int = 5,
        reset_timeout_s: float = 30.0,
        clock=time.monotonic,
        on_state_change=None,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self.on_state_change = on_state_change
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at: float | None = None
        self._probe_in_flight = False
        self._probe_started_at: float | None = None

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _set_state(self, state: str) -> str | None:
        """Apply a transition (lock held by caller) and return the new
        state to notify for, or None. The ``on_state_change`` hook is
        deliberately fired OUTSIDE the lock by the public methods: a
        caller-supplied hook that reads ``state`` or records an outcome
        (the natural shape for an alerting callback) must not deadlock
        against the breaker's own non-reentrant lock."""
        if state == self._state:
            return None
        self._state = state
        return state

    def _notify(self, state: str | None) -> None:
        if state is not None and self.on_state_change is not None:
            self.on_state_change(state)

    def allow(self) -> None:
        """Admit one op or raise :class:`CircuitOpenError`."""
        notify = None
        with self._lock:
            if self._state == self.OPEN:
                if (
                    self._opened_at is not None
                    and self._clock() - self._opened_at >= self.reset_timeout_s
                ):
                    notify = self._set_state(self.HALF_OPEN)
                    self._probe_in_flight = True
                    self._probe_started_at = self._clock()
                else:
                    raise CircuitOpenError(
                        f"circuit open after {self._consecutive} consecutive "
                        f"failures; probing again in <= {self.reset_timeout_s}s"
                    )
            elif self._state == self.HALF_OPEN:
                if self._probe_in_flight:
                    # a probe that never reported back (e.g. its op died
                    # on a BaseException the retry layer does not catch)
                    # must not wedge the breaker half-open forever: after
                    # the reset timeout the slot is taken over
                    if (
                        self._probe_started_at is not None
                        and self._clock() - self._probe_started_at
                        >= self.reset_timeout_s
                    ):
                        self._probe_started_at = self._clock()
                    else:
                        raise CircuitOpenError(
                            "half-open probe already in flight"
                        )
                else:
                    self._probe_in_flight = True
                    self._probe_started_at = self._clock()
        self._notify(notify)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._probe_in_flight = False
            self._opened_at = None
            notify = self._set_state(self.CLOSED)
        self._notify(notify)

    def record_failure(self) -> None:
        notify = None
        with self._lock:
            self._probe_in_flight = False
            self._consecutive += 1
            if (
                self._state == self.HALF_OPEN
                or self._consecutive >= self.failure_threshold
            ):
                self._opened_at = self._clock()
                notify = self._set_state(self.OPEN)
        self._notify(notify)
